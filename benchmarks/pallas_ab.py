"""A/B/C promotion harness: scan vs Pallas v1 vs rotband v2 DP fill.

Runs on whatever backend JAX resolves (the real chip when available:
interpret=False on TPU).  Two parts:

  1. correctness — bit-exact comparison of BOTH kernels (v1 band-local
     ops/banded_pallas.py, v2 rotating-band ops/banded_rotband.py)
     against the scan spec at small shapes (the same checks as
     tests/test_banded_pallas.py, but with interpret=False so the
     Mosaic-compiled kernels themselves are what run);
  2. throughput — all three arms timed INTERLEAVED at the bench.py
     shapes (Z=16, P=8, W=1024 by default) under the forced-execution
     marginal method ONLY (per-iteration block_until_ready loops are
     rejected by construction: they read RPC latency on the lazy axon
     runtime, the r3/r5 pollution), reporting zmw_windows/s and DP
     cells/s for each — and a machine-readable DECISION RECORD
     (winner, margin, backend, method) that bench.py vs_prev consumes.
     This record is what settles ROADMAP item 1: the first run on a
     live device backend names the production implementation.

Usage:  python benchmarks/pallas_ab.py [--json out.json]

Reference workload being timed: the banded-striped SIMD fill inside
bsalign's POA (reference main.c:552-572, band=128 at main.c:849).
"""

import argparse
import json
import os
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from marginal_time import marginal_time as _marginal_time  # noqa: E402


def _bench_args(Z, P, W, tlen, seed=0):
    sys.path.insert(0, _REPO)
    import __graft_entry__ as ge

    return ge._example_batch(Z=Z, P=P, W=W, tlen=tlen, seed=seed)


def check_bit_exact(interpret: bool) -> int:
    """Both kernels vs scan at small shapes; returns problems checked.

    With interpret=False on a TPU backend this is the HARDWARE
    bit-exactness arm for v1 and v2 alike (the v2 rotband kernel's
    first tunnel-live proof rides this entry point)."""
    from ccsx_tpu.config import AlignParams
    from ccsx_tpu.ops import banded, banded_pallas, banded_rotband
    from ccsx_tpu.utils import synth

    rng = np.random.default_rng(7)
    Qmax, Tmax, N = 256, 256, 8
    qs = np.full((N, Qmax), banded.PAD, np.uint8)
    qlens = np.zeros(N, np.int32)
    ts = np.full((N, Tmax), banded.PAD, np.uint8)
    tlens = np.zeros(N, np.int32)
    for i in range(N):
        tl = int(rng.integers(40, 200))
        tpl = rng.integers(0, 4, tl).astype(np.uint8)
        q = synth.mutate(rng, tpl, 0.03, 0.05, 0.05)[:Qmax]
        qs[i, : len(q)] = q
        qlens[i] = len(q)
        ts[i, :tl] = tpl
        tlens[i] = tl
    params = AlignParams()
    scan_f = banded.make_batched("global", params, with_moves=True)
    r1, m1, o1 = scan_f(qs, qlens, ts, tlens)
    m1 = np.asarray(m1)
    for name, mod in (("pallas", banded_pallas),
                      ("rotband", banded_rotband)):
        r2, m2, o2 = mod.batched_align_global_moves(
            qs, qlens, ts, tlens, params, interpret=interpret)
        np.testing.assert_array_equal(
            np.asarray(r1.score), np.asarray(r2.score), err_msg=name)
        np.testing.assert_array_equal(
            np.asarray(r1.mat), np.asarray(r2.mat), err_msg=name)
        np.testing.assert_array_equal(
            np.asarray(r1.aln), np.asarray(r2.aln), err_msg=name)
        np.testing.assert_array_equal(
            np.asarray(o1), np.asarray(o2), err_msg=name)
        m2 = np.asarray(m2)
        for i in range(N):
            ql = int(qlens[i])
            np.testing.assert_array_equal(
                m1[i, :ql], m2[i, :ql],
                err_msg=f"{name} moves mismatch, problem {i}")
        # and the slim kernel (the production consensus config)
        r3, m3, o3 = mod.batched_align_global_moves(
            qs, qlens, ts, tlens, params, interpret=interpret,
            with_stats=False)
        np.testing.assert_array_equal(
            np.asarray(r1.score), np.asarray(r3.score), err_msg=name)
        np.testing.assert_array_equal(
            np.asarray(o1), np.asarray(o3), err_msg=name)
        m3 = np.asarray(m3)
        for i in range(N):
            ql = int(qlens[i])
            np.testing.assert_array_equal(
                m1[i, :ql], m3[i, :ql],
                err_msg=f"{name} slim moves mismatch, problem {i}")
    return N


_STEP_CACHE = {}


def _round_step(impl: str, W: int):
    """Jitted full-round step for one banded impl (cached: the interleaved
    timing loop revisits each impl several times and must not re-trace)."""
    key = ("round", impl, W)
    if key in _STEP_CACHE:
        return _STEP_CACHE[key]
    import jax

    from ccsx_tpu.config import AlignParams
    from ccsx_tpu.consensus import star
    from ccsx_tpu.ops import msa, traceback

    params = AlignParams()
    projector = traceback.make_projector(W, 4)
    voter = msa.make_voter(4)
    # NOTE: the impl dispatch happens at TRACE time (star._aligner reads
    # use_pallas() when the jitted step first runs).  The caller
    # (time_impl) holds the CCSX_BANDED_IMPL override through trace/compile,
    # which is when tracing occurs — do not call the returned step
    # outside such a scope or the wrong impl gets traced and cached.
    aligner = star._aligner(params)

    @jax.jit
    def step(qs, qlens, ts, tlens, row_mask):
        Zb, Pb, qmax = qs.shape
        ts_b = jax.numpy.broadcast_to(
            ts[:, None, :], (Zb, Pb, ts.shape[-1]))
        tl_b = jax.numpy.broadcast_to(tlens[:, None], (Zb, Pb))
        _, moves, offs = aligner(
            qs.reshape(Zb * Pb, qmax), qlens.reshape(Zb * Pb),
            ts_b.reshape(Zb * Pb, -1), tl_b.reshape(Zb * Pb))
        moves = moves.reshape(Zb, Pb, qmax, -1)
        offs = offs.reshape(Zb, Pb, qmax)
        proj = jax.vmap(jax.vmap(projector, in_axes=(0, 0, 0, 0, None)),
                        in_axes=(0, 0, 0, 0, 0))
        aligned, ins_cnt, ins_b, _lead = proj(
            moves, offs, qs, qlens, tlens)
        cons, ins_base, ins_votes, ncov, match, nwin = jax.vmap(voter)(
            aligned, ins_cnt, ins_b, row_mask)
        return cons, ncov

    _STEP_CACHE[key] = step
    return step


def time_impl(impl: str, Z, P, W, tlen, iters=100, repeats=3):
    """Time one full consensus round step with the given banded impl.

    Uses the forced-execution marginal method (_marginal_time — the r5
    first-cut artifact pallas_ab_tpu_r05.json predates it and its
    round/fill numbers are RPC-latency readings, not chip time); returns
    zmw_windows/s per window.  The CCSX_BANDED_IMPL override is held
    (try/finally) through trace/compile so a failure can't leak it into
    the process."""
    prior = os.environ.get("CCSX_BANDED_IMPL")
    os.environ["CCSX_BANDED_IMPL"] = impl
    try:
        step = _round_step(impl, W)
        args = _bench_args(Z, P, W, tlen)
        runs = [Z / dt for dt in _marginal_time(
            step, *args, iters=iters, repeats=repeats)]
    finally:
        if prior is None:
            os.environ.pop("CCSX_BANDED_IMPL", None)
        else:
            os.environ["CCSX_BANDED_IMPL"] = prior
    return runs


def time_fill_only(impl: str, Z, P, W, tlen, iters=300,
                   repeats=3):
    """Time just the DP fill (no projection/vote) — isolates the kernel.

    Compiles once; returns a list of result dicts, one per window."""
    import jax

    key = ("fill", impl)
    if key in _STEP_CACHE:
        fill = _STEP_CACHE[key]
    else:
        from ccsx_tpu.config import AlignParams
        from ccsx_tpu.ops import banded, banded_pallas

        params = AlignParams()
        if impl in ("pallas", "rotband"):
            from ccsx_tpu.ops import banded_rotband

            mod = banded_rotband if impl == "rotband" else banded_pallas
            interp = jax.default_backend() != "tpu"

            @jax.jit
            def fill(qs, qlens, ts, tlens):
                # with_stats=False: the consensus-round configuration
                # (star._aligner) — slim carry, 1-array F scan
                return mod.batched_align_global_moves(
                    qs, qlens, ts, tlens, params, interpret=interp,
                    with_stats=False)
        else:
            scan_f = banded.make_batched("global", params, with_moves=True,
                                         with_stats=False)

            @jax.jit
            def fill(qs, qlens, ts, tlens):
                return scan_f(qs, qlens, ts, tlens)
        _STEP_CACHE[key] = fill

    from ccsx_tpu.config import AlignParams as _AP

    band = _AP().band  # the band the fill actually runs at
    qs, qlens, ts, tlens, _ = _bench_args(Z, P, W, tlen)
    n = Z * P
    qs_f = qs.reshape(n, W)
    qlens_f = qlens.reshape(n)
    ts_f = np.ascontiguousarray(
        np.broadcast_to(ts[:, None, :], (Z, P, ts.shape[-1]))).reshape(n, -1)
    tlens_f = np.ascontiguousarray(
        np.broadcast_to(tlens[:, None], (Z, P))).reshape(n)
    cells = n * W * band
    return [{"zmw_windows_per_sec": Z / dt,
             "dp_cells_per_sec": cells / dt,
             "ms_per_dispatch": dt * 1e3}
            for dt in _marginal_time(fill, qs_f, qlens_f, ts_f, tlens_f,
                                     iters=iters, repeats=repeats)]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None)
    ap.add_argument("--Z", type=int, default=16)
    ap.add_argument("--P", type=int, default=8)
    ap.add_argument("--W", type=int, default=1024)
    ap.add_argument("--tlen", type=int, default=1000)
    ap.add_argument("--mode", choices=["time", "check", "both"],
                    default="both")
    ap.add_argument("--gblocks", default="",
                    help="comma list, e.g. 8,16,32: also sweep the "
                         "kernel's problem block (fill-only)")
    args = ap.parse_args()
    # validate up front: a malformed list must not crash AFTER the
    # expensive timing block and lose its results
    try:
        gblock_list = [int(x) for x in args.gblocks.split(",") if x]
    except ValueError:
        ap.error(f"--gblocks {args.gblocks!r}: expected a comma "
                 "list of integers")
    if any(g < 1 for g in gblock_list):
        ap.error(f"--gblocks values must be >= 1: {gblock_list}")

    sys.path.insert(0, _REPO)
    from ccsx_tpu.utils.device import resolve_device

    resolve_device("auto")
    import jax

    backend = jax.default_backend()
    interpret = backend != "tpu"
    out = {"backend": backend, "interpret": interpret,
           "shapes": {"Z": args.Z, "P": args.P, "W": args.W,
                      "tlen": args.tlen}}

    # ORDER MATTERS on the axon TPU tunnel: any device->host transfer
    # permanently flips the runtime into a synchronous dispatch mode with
    # ~80ms RTT per launch (measured: trivial jitted add goes 0.07ms ->
    # 82ms after one np.asarray of a device array).  The invariant is
    # "all timing before any d2h transfer": in --mode both the check runs
    # strictly after the timing block; prefer separate --mode time /
    # --mode check processes when in doubt.
    # The chip's available throughput also drifts minute-to-minute
    # (shared/tunnelled), so scan and pallas windows are INTERLEAVED and
    # medians reported — drift hits both impls equally.
    ARMS = ("scan", "pallas", "rotband")
    if args.mode in ("time", "both"):
        import statistics

        rounds = {impl: [] for impl in ARMS}
        fills = {impl: [] for impl in ARMS}
        # a window where every marginal sample is nonpositive raises
        # RuntimeError (marginal_time's honest refusal) — on a noisy
        # shared chip that is one lost WINDOW, not a lost A/B: count it,
        # keep the samples already collected, and keep interleaving
        lost = []
        for rep in range(5):
            for impl in ARMS:
                try:
                    rounds[impl] += time_impl(
                        impl, args.Z, args.P, args.W, args.tlen,
                        iters=50, repeats=1)
                except RuntimeError as e:
                    lost.append(f"round/{impl}/rep{rep}: {e}")
                try:
                    fills[impl] += time_fill_only(
                        impl, args.Z, args.P, args.W, args.tlen,
                        iters=50, repeats=1)
                except RuntimeError as e:
                    lost.append(f"fill/{impl}/rep{rep}: {e}")
        if lost:
            out["windows_lost"] = lost
            print(f"[pallas_ab] {len(lost)} timing window(s) lost to "
                  "nonpositive marginals (kept going)", file=sys.stderr)
        for impl in ARMS:
            if rounds[impl]:
                out[f"round_{impl}"] = statistics.median(rounds[impl])
            else:
                out[f"round_{impl}"] = None  # every window lost: honest null
            out[f"round_{impl}_runs"] = rounds[impl]
            if fills[impl]:
                fr = sorted(fills[impl],
                            key=lambda d: d["dp_cells_per_sec"])
                out[f"fill_{impl}"] = fr[len(fr) // 2]
            else:
                out[f"fill_{impl}"] = None
            out[f"fill_{impl}_runs"] = [
                f["dp_cells_per_sec"] for f in fills[impl]]
            if rounds[impl] and fills[impl]:
                print(f"{impl}: round {out[f'round_{impl}']:.0f} "
                      "zmw_windows/s (median), fill "
                      f"{out[f'fill_{impl}']['dp_cells_per_sec']:.3e} "
                      "cells/s", file=sys.stderr)

        # ---- the DECISION RECORD (the promotion protocol's verdict,
        # ---- consumed by bench.py vs_prev): winner by the full-round
        # ---- median — the metric star._aligner's dispatch actually
        # ---- moves — with the fill-only medians carried alongside;
        # ---- margin = winner/runner-up.  Method is marginal-fetch by
        # ---- construction (this file has no other timing path).
        round_rates = {impl: out.get(f"round_{impl}") for impl in ARMS
                       if out.get(f"round_{impl}")}
        fill_rates = {
            impl: out[f"fill_{impl}"]["dp_cells_per_sec"]
            for impl in ARMS if out.get(f"fill_{impl}")}
        metric, rates = ("round_zmw_windows_per_sec", round_rates)
        if not rates:
            # every round window lost (degenerate chip): fall back to
            # the fill medians rather than emitting no verdict at all
            metric, rates = ("fill_dp_cells_per_sec", fill_rates)
        if rates:
            ranked = sorted(rates, key=rates.get, reverse=True)
            winner = ranked[0]
            margin = (rates[winner] / rates[ranked[1]]
                      if len(ranked) > 1 else None)
            out["decision"] = {
                "winner": winner,
                "margin": round(margin, 4) if margin else None,
                "metric": metric,
                "round_rates": round_rates,
                "fill_rates": fill_rates,
                "backend": backend,
                "interpret": interpret,
                "method": "marginal-fetch",
            }
            print(f"[decision] winner={winner} "
                  f"margin={out['decision']['margin']} "
                  f"metric={metric} backend={backend} "
                  f"interpret={interpret}", file=sys.stderr)

    if args.mode in ("time", "both") and gblock_list:
        # gblock sweep, fill-only.  NB the env is read at TRACE time of
        # the cached @jax.jit fill closure in time_fill_only — it is the
        # _STEP_CACHE.pop that forces a fresh closure (fresh jit cache)
        # per value; without it every g would re-time the first kernel.
        prior = os.environ.get("CCSX_PALLAS_GBLOCK")
        try:
            for impl in ("pallas", "rotband"):
                out[f"fill_{impl}_gblock"] = {}
                for g in gblock_list:
                    os.environ["CCSX_PALLAS_GBLOCK"] = str(g)
                    _STEP_CACHE.pop(("fill", impl), None)
                    try:
                        fr = sorted(
                            time_fill_only(impl, args.Z, args.P, args.W,
                                           args.tlen, iters=50, repeats=3),
                            key=lambda d: d["dp_cells_per_sec"])
                    except RuntimeError as e:
                        # same lost-window policy as the interleaved arms
                        out[f"fill_{impl}_gblock"][g] = None
                        print(f"{impl} gblock={g}: window lost ({e})",
                              file=sys.stderr)
                        continue
                    out[f"fill_{impl}_gblock"][g] = fr[len(fr) // 2]
                    print(f"{impl} gblock={g}: "
                          f"{fr[len(fr) // 2]['dp_cells_per_sec']:.3e} "
                          "cells/s", file=sys.stderr)
        finally:
            if prior is None:
                os.environ.pop("CCSX_PALLAS_GBLOCK", None)
            else:
                os.environ["CCSX_PALLAS_GBLOCK"] = prior
            _STEP_CACHE.pop(("fill", "pallas"), None)
            _STEP_CACHE.pop(("fill", "rotband"), None)

    if args.mode in ("check", "both"):
        n = check_bit_exact(interpret)
        out["bit_exact_problems"] = n
        print(f"bit-exact vs scan: {n} problems OK "
              f"(interpret={interpret}, backend={backend})", file=sys.stderr)

    print(json.dumps(out, indent=1))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
