"""Native (C++) IO layer loader.

Builds ``libccsx_io.so`` from io_native.cpp on first use if a compiler is
present, loads it via ctypes, and exposes ``lib()``.  Import never fails:
callers check ``available()`` and fall back to the pure-Python parsers
(ccsx_tpu.io.fastx / ccsx_tpu.io.bam) when the toolchain is absent.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libccsx_io.so")
_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-s", "-C", _DIR],
            check=True, capture_output=True, timeout=120,
        )
        return os.path.exists(_SO)
    except (OSError, subprocess.SubprocessError):
        return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    c = ctypes
    lib.ccsx_open.restype = c.c_void_p
    lib.ccsx_open.argtypes = [c.c_char_p, c.c_int]
    lib.ccsx_set_filter.restype = None
    lib.ccsx_set_filter.argtypes = [c.c_void_p, c.c_int32, c.c_int64,
                                    c.c_int64]
    lib.ccsx_next_zmw.restype = c.c_int
    lib.ccsx_next_zmw.argtypes = [
        c.c_void_p,
        c.POINTER(c.c_char_p), c.POINTER(c.c_char_p),
        c.POINTER(c.POINTER(c.c_uint8)), c.POINTER(c.c_int64),
        c.POINTER(c.POINTER(c.c_int32)), c.POINTER(c.c_int32),
    ]
    lib.ccsx_next_record.restype = c.c_int
    lib.ccsx_next_record.argtypes = [
        c.c_void_p,
        c.POINTER(c.c_char_p), c.POINTER(c.c_char_p),
        c.POINTER(c.POINTER(c.c_uint8)), c.POINTER(c.c_int64),
        c.POINTER(c.POINTER(c.c_uint8)), c.POINTER(c.c_int64),
    ]
    lib.ccsx_error.restype = c.c_char_p
    lib.ccsx_error.argtypes = [c.c_void_p]
    # filter accounting (guarded: a stale prebuilt .so without the
    # symbols must degrade to "counts unavailable", not fail to load)
    for name in ("ccsx_filter_counts", "ccsx_prefetch_filter_counts"):
        try:
            fn = getattr(lib, name)
        except AttributeError:
            continue
        fn.restype = None
        fn.argtypes = [c.c_void_p] + [c.POINTER(c.c_int64)] * 3
    # salvage-mode ingest (same stale-.so guard: native/io.py falls
    # back to the pure-Python salvage readers when these are absent)
    try:
        lib.ccsx_set_salvage.restype = None
        lib.ccsx_set_salvage.argtypes = [c.c_void_p, c.c_int, c.c_int64]
        lib.ccsx_prefetch_open_s.restype = c.c_void_p
        lib.ccsx_prefetch_open_s.argtypes = [
            c.c_char_p, c.c_int, c.c_int32, c.c_int64, c.c_int64,
            c.c_int32, c.c_int, c.c_int64]
        for name in ("ccsx_error_reason", "ccsx_prefetch_error_reason",
                     "ccsx_corrupt_summary",
                     "ccsx_prefetch_corrupt_summary"):
            fn = getattr(lib, name)
            fn.restype = c.c_char_p
            fn.argtypes = [c.c_void_p]
        for name in ("ccsx_corrupt_events",
                     "ccsx_prefetch_corrupt_events",
                     "ccsx_corrupt_exempt",
                     "ccsx_prefetch_corrupt_exempt"):
            fn = getattr(lib, name)
            fn.restype = c.c_int64
            fn.argtypes = [c.c_void_p]
    except AttributeError:
        pass
    lib.ccsx_close.restype = None
    lib.ccsx_close.argtypes = [c.c_void_p]
    for name in ("ccsx_encode", "ccsx_revcomp_ascii", "ccsx_revcomp_codes"):
        fn = getattr(lib, name)
        fn.restype = None
        fn.argtypes = [c.POINTER(c.c_uint8), c.c_int64, c.POINTER(c.c_uint8)]
    lib.ccsx_prefetch_open.restype = c.c_void_p
    lib.ccsx_prefetch_open.argtypes = [c.c_char_p, c.c_int, c.c_int32,
                                       c.c_int64, c.c_int64, c.c_int32]
    lib.ccsx_prefetch_next.restype = c.c_int
    lib.ccsx_prefetch_next.argtypes = lib.ccsx_next_zmw.argtypes
    lib.ccsx_prefetch_error.restype = c.c_char_p
    lib.ccsx_prefetch_error.argtypes = [c.c_void_p]
    lib.ccsx_prefetch_close.restype = None
    lib.ccsx_prefetch_close.argtypes = [c.c_void_p]
    lib.ccsx_writer_open.restype = c.c_void_p
    lib.ccsx_writer_open.argtypes = [c.c_char_p, c.c_int]
    lib.ccsx_writer_put_fasta.restype = c.c_int
    lib.ccsx_writer_put_fasta.argtypes = [c.c_void_p, c.c_char_p,
                                          c.POINTER(c.c_uint8), c.c_int64]
    lib.ccsx_writer_put_fastq.restype = c.c_int
    lib.ccsx_writer_put_fastq.argtypes = [c.c_void_p, c.c_char_p,
                                          c.POINTER(c.c_uint8),
                                          c.POINTER(c.c_uint8), c.c_int64]
    lib.ccsx_writer_close.restype = c.c_int
    lib.ccsx_writer_close.argtypes = [c.c_void_p]
    lib.ccsx_bgzf_pool_bench.restype = c.c_double
    lib.ccsx_bgzf_pool_bench.argtypes = [c.c_char_p, c.c_int, c.c_int]
    lib.ccsx_align_scalar.restype = c.c_int
    lib.ccsx_align_scalar.argtypes = [
        c.POINTER(c.c_uint8), c.c_int64, c.POINTER(c.c_uint8), c.c_int64,
        c.c_int, c.c_int, c.c_int, c.c_int, c.c_int,
        c.POINTER(c.c_int64), c.POINTER(c.c_uint8), c.c_int64,
        c.POINTER(c.c_int64),
    ]
    return lib


def lib():
    """The loaded native library, or None when unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        import glob

        srcs = glob.glob(os.path.join(_DIR, "*.cpp"))
        if not os.path.exists(_SO) or any(
            os.path.getmtime(_SO) < os.path.getmtime(s) for s in srcs
        ):
            if not _build():
                return None
        try:
            _lib = _bind(ctypes.CDLL(_SO))
        except OSError:
            _lib = None
    return _lib


def available() -> bool:
    return lib() is not None
