"""Pallas TPU kernel for the banded affine-gap DP fill (global+moves mode).

This is the hot op of the framework: every consensus round aligns each pass
window against the draft (star.round), which the reference does inside
bsalign's banded-striped SIMD POA (end_bspoa, main.c:492; band=128 at
main.c:849).  The semantics here are *identical* to the lax.scan
implementation in ops/banded.py (mode='global', with_moves=True) — that
version remains the spec and the differential-test oracle; this one maps the
fill onto a single Pallas kernel so the whole DP runs out of VMEM with no
per-row HLO overhead.

Design notes (why the kernel looks like this):

* The band-offset schedule ``offs`` is data-INdependent — it is a pure
  function of (qlen, tlen, line) — so it is computed outside the kernel
  with a tiny vectorized ``lax.scan`` (compute_offsets) and fed to the
  kernel through SMEM.  The traceback needs the same array, so nothing is
  wasted.
* The only per-cell input the recurrence needs from (q, t) is the match
  indicator; ``ismatch[i-1, k] = q[i-1] == t[offs[i]+k-1]`` is precomputed
  as a (Qmax, B) int8 gather outside the kernel.  Inside, each row is a
  dynamic *sublane* read — cheap — whereas gathering t by a dynamic lane
  offset in-kernel would be a lane-rotate per row.
* The previous-row band must be shifted by d = offs[i] - offs[i-1] ∈
  [0, maxshift].  d is tiny, so the kernel computes all maxshift+2 static
  lane shifts of the carry block and picks with a select chain — static
  shifts vectorize on the VPU; a dynamic lane shift would not.
* The horizontal (within-row) affine gap F is an associative max-plus
  prefix scan (see ops/banded.py); here it is a log2(B)-step Hillis-Steele
  scan of static lane shifts.
* Outputs: the packed move byte per cell (uint8, written row-by-row into
  the VMEM output block) and the final H/mat/aln bands; score extraction
  happens outside.

The kernel is gated to Qmax <= PALLAS_MAX_QMAX (VMEM/SMEM budget); the
windowed consensus path (the default) always fits.  Callers use
ops/banded.select_aligner-style dispatch in consensus/star.py.

Per-cell cost analysis (r5, after the slim with_stats=False carry):
the per-row tile-op budget of THIS (v1, band-local) layout splits
~24 ops select chain (diag/vert views of the H/E carry at
per-problem shift d), ~21 ops F prefix scan (7 Hillis-Steele steps x
roll+cmp+select), ~15 ops recurrence+moves, ~60 total.  The select
chain is irreducible in the band-local lane layout: d differs per
problem inside a G-block, so a scalar dynamic rotate cannot replace
the per-candidate static shifts, and pre-shifting the carry at row
end just moves the same chain.

The structural attack — a rotating-band layout where lane k holds
column j ≡ k mod B, so the chain becomes one per-problem mask +
static-rotate pair (~11 ops) — is IMPLEMENTED as of r14 in the
sibling ops/banded_rotband.py (v2).  Two estimates in the r5
paragraph above turned out wrong in v2's favor: the F scan needs NO
extra per-step cost (the wrap mask substitutes ``krel`` for the
column index one-for-one, ~21 ops unchanged), and the lane-rotated
moves are restored by a single host-side take_along_axis gather
outside the kernel, not an in-kernel post-pass.  v2's audited budget
is ~45 ops/row vs ~60 here; the full derivation and the audit table
live in banded_rotband.py's docstring.

This v1 kernel stays as the band-local reference point of the
promotion protocol: benchmarks/pallas_ab.py times all three arms
(scan / v1 / v2 rotband) with the forced-execution marginal method
and emits a machine-readable decision record {winner, margin,
backend, method} that bench.py's vs_prev dp-kernel leg gates.  The
scan in ops/banded.py remains the spec and the differential oracle
for BOTH kernels; promotion (flipping the CCSX_BANDED_IMPL default
in consensus/star.py) happens only on a hardware decision record
that names a kernel the winner.

HARDWARE STATUS (v5e, 2026-07-31; pre-rotband): bit-exactness of v1
PROVEN on the real chip — 8/8 problems identical to the scan spec
(`pallas_ab.py --mode check`, a fetch-synced comparison).  All
timing taken before the marginal-fetch method landed
(pallas_ab_tpu_r05.json and earlier) was per-iteration
block_until_ready, which the lazy axon runtime turns into
RPC-latency readings (bench.py docstring has the discovery) — it
consistently ORDERED scan ahead of v1 but none of it is a chip
time.  The rotband v2 arm has bit-exactness proven in interpret
mode and compiles with interpret=False; its first hardware decision
record (tpu_battery.sh step 4, pallas_ab_tpu_r07.json) is the next
promotion input.  Until a hardware record names a kernel the
winner, the scan stays the default: it is the spec, and every
reading so far — however latency-polluted — has the same sign.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ccsx_tpu.config import AlignParams
from ccsx_tpu.ops.banded import (
    BandedResult, EBIT_EXT, FBIT_EXT, MOVE_DIAG, MOVE_LEFT, MOVE_UP, NEG, PAD,
    _line_interp,
)

PALLAS_MAX_QMAX = 4096  # beyond this fall back to the scan implementation


def compute_offsets(qlen, tlen, qmax: int, band: int, maxshift: int,
                    line=None):
    """The band-offset schedule for rows 1..qmax (shape (qmax,) int32).

    Bit-exact replica of the offset recurrence in ops/banded.py's scan body
    (global mode), including the freeze beyond qlen.  Vectorize over a batch
    with jax.vmap.
    """
    qlen = qlen.astype(jnp.int32)
    tlen = tlen.astype(jnp.int32)
    tcap = jnp.maximum(tlen - band + 1, 0)
    if line is None:
        li0, lj0, li1, lj1 = (jnp.int32(0), jnp.int32(0), qlen, tlen)
    else:
        line = jnp.asarray(line, jnp.int32)
        li0, lj0, li1, lj1 = line[0], line[1], line[2], line[3]

    def body(off_prev, i):
        # overflow-exact interpolation SHARED with the scan body (the raw
        # int32 product silently diverged from ops/banded.py for large
        # seeded lines — the pre-r14 drift; one definition, imported)
        nom_j = lj0 + _line_interp(i - li0, lj1 - lj0,
                                   jnp.maximum(li1 - li0, 1))
        desired = nom_j - band // 2
        lo = jnp.maximum(0, tcap - (qlen - i) * maxshift)
        off = jnp.clip(
            jnp.maximum(desired, lo), off_prev,
            jnp.minimum(off_prev + maxshift, tcap),
        )
        off = jnp.maximum(off, off_prev)
        off = jnp.where(i <= qlen, off, off_prev)
        return off, off

    _, offs = jax.lax.scan(
        body, jnp.int32(0), jnp.arange(1, qmax + 1, dtype=jnp.int32))
    return offs


def compute_ismatch(q, t, offs, band: int, maxshift: int):
    """(Qmax, band) int8 match indicators: row i-1 lane k compares q[i-1]
    with the base entering column offs[i]+k (PAD-safe)."""
    qmax = q.shape[0]
    tpad = jnp.concatenate([
        jnp.full((1,), PAD, jnp.uint8), t.astype(jnp.uint8),
        jnp.full((band + maxshift,), PAD, jnp.uint8),
    ])
    j = offs[:, None] + jnp.arange(band, dtype=jnp.int32)[None, :]
    tb = tpad[j]
    qi = q[:, None]
    ismatch = (qi == tb) & (qi < 4) & (tb < 4)
    return ismatch.astype(jnp.int8)


ROWBLOCK = 8  # rows per grid step: aligned sublane tiles for loads/stores
GBLOCK = 8    # alignments per grid step, stacked in the sublane axis


# rows of the G-batched carry: H, E, [mat, aln, Emat, Ealn,] OFF
_CHG = 7          # with_stats carry rows (stats-free carry is 3)


def _kernel_g(tlen_ref, ismatch_ref, moves_ref, fin_ref,
              ch_ref, *, qmax: int, band: int, maxshift: int,
              params: AlignParams, with_stats: bool, gblock: int):
    """G-batched banded DP fill: GBLOCK alignments per grid step.

    The first kernel revision processed one alignment per grid step, so
    every VPU op ran on a (1, B) sliver — 1/8 sublane utilization, and it
    lost to XLA's vmapped scan ~5.7x.  Here GBLOCK alignments ride the
    sublane axis: the carry is (nch, G, B) VMEM scratch, all recurrence
    math is (G, B) tiles, and per-problem row scalars (band shift d, live
    mask, tlen) enter as (G, 1) columns broadcast across lanes.

    ``with_stats=False`` is the consensus-round configuration (star.
    _aligner): the rounds consume only (moves, offs) — BandedResult is
    discarded — so the mat/aln/Emat/Ealn stat channels are dead weight.
    Dropping them shrinks the carry 7 rows -> 3 and the F prefix scan
    from 3 arrays to 1, cutting most of the kernel's per-cell op count
    (the same trade ops/banded.py makes with its with_stats=False path;
    moves/offs are bit-identical either way).

    The d-shift selection is computed ONCE at shift d-1 over the carry
    block and the d view is derived from it with a single static +1
    shift — shift composition holds lane-for-lane except lane B-1 under
    d == 0, which one masked select patches back to the unshifted carry.
    This halves the select-chain cost vs materializing both views per
    candidate d.

    Per-row scalars d (band shift, 0..maxshift) and live (i <= qlen) are
    BIT-PACKED into lane 0 of the ismatch input (bits 1-3 and 4; bit 0
    stays the match indicator on every lane): Mosaic requires lane-dim
    blocks of 128 (so a (G, ROWBLOCK) scalar block never lowers on real
    TPU) and dynamic lane slices must be 128-aligned (so a full-lane
    scalar array can't be sliced per ROWBLOCK chunk either).  Riding the
    already-aligned ismatch tile costs nothing.

    Inputs (blocks):
      tlen_ref    (G, 1) int32
      ismatch_ref (G, ROWBLOCK, B) int32 — bit 0 match; lane 0 carries
                  d at bits 1-3 and live at bit 4
    Outputs: moves (G, ROWBLOCK, B) uint8; fin (G, 8, B) int32 rows
    0/1/2 = final H/mat/aln bands (mat/aln zero when stats are off).
    """
    M, X = params.match, params.mismatch
    O, E = params.gap_open, params.gap_extend
    B = band
    G = gblock
    nch = _CHG if with_stats else 3
    noff = nch - 1                                   # OFF row index
    r = pl.program_id(1)
    karr = jax.lax.broadcasted_iota(jnp.int32, (1, B), 1)
    tlen_col = tlen_ref[:, 0:1]                      # (G, 1)

    def shift_blk(blk, s):
        """Static lane shift of a carry block: out[..., k] = blk[..., k+s],
        NEG fill (matches _pad_prev in ops/banded.py).  Expressed as a
        lane rotate + iota mask: Mosaic lowers tpu.rotate natively, while
        lane-dim concatenates hit "offset mismatch on non-concat
        dimension" and never compile on real TPU."""
        if s == 0:
            return blk
        rolled = jnp.roll(blk, -s, axis=2)
        k3 = karr[None]                              # (1, 1, B)
        if s > 0:
            return jnp.where(k3 >= B - s, NEG, rolled)
        return jnp.where(k3 < -s, NEG, rolled)

    def shift_row(x, s, fill):
        """Static lane shift of one (G, B) tile (rotate + mask)."""
        if s == 0:
            return x
        rolled = jnp.roll(x, -s, axis=1)
        if s > 0:
            return jnp.where(karr >= B - s, fill, rolled)
        return jnp.where(karr < -s, fill, rolled)

    # ---- row 0 init (off = 0), exactly ops/banded.py carry0 ----
    @pl.when(r == 0)
    def _():
        j0 = jnp.broadcast_to(karr, (G, B))
        H0 = jnp.where(j0 <= tlen_col,
                       jnp.where(j0 == 0, 0, O + E * j0), NEG)
        E0 = jnp.full((G, B), NEG, jnp.int32)
        z = jnp.zeros((G, B), jnp.int32)
        rows0 = ([H0, E0, z, j0, z, j0, z] if with_stats
                 else [H0, E0, z])
        ch_ref[:] = jnp.stack(rows0, axis=0)

    # int32 throughout: i8 sublane slices hit Mosaic relayout limits
    packed_tile = ismatch_ref[...].astype(jnp.int32)   # (G, ROWBLOCK, B)
    ismatch_tile = packed_tile & 1
    ch = ch_ref[:]
    moves_rows = []
    for s in range(ROWBLOCK):
        i = r * ROWBLOCK + s + 1
        lane0 = packed_tile[:, s, 0:1]               # (G, 1) packed scalars
        d_col = (lane0 >> 1) & 7
        live_col = ((lane0 >> 4) & 1) != 0           # (G, 1) bool

        # select the (d-1)-shifted view of the shiftable carry rows (the
        # diagonal predecessors), then derive the d view (the vertical
        # predecessors) from it by one static +1 shift
        chs = ch[:noff]
        sel = shift_blk(chs, -1)                     # d == 0 candidate
        for dd in range(1, maxshift + 1):
            cand = chs if dd == 1 else shift_blk(chs, dd - 1)
            sel = jnp.where((d_col == dd)[None], cand, sel)
        up = shift_blk(sel, 1)
        # composition is exact except lane B-1 under d == 0, where
        # shift(ch, 0) keeps the carry value the +1 shift fills with NEG
        patch = (d_col == 0) & (karr == B - 1)       # (G, B)
        up = jnp.where(patch[None], chs, up)

        Hd_diag = sel[0]
        H_up, E_up = up[0], up[1]
        if with_stats:
            mat_diag, aln_diag = sel[2], sel[3]
            mat_up, aln_up = up[2], up[3]
            Emat_up, Ealn_up = up[4], up[5]
        OFF = ch[noff] + d_col                       # this row's band offset

        im = ismatch_tile[:, s, :]                   # (G, B) int32 0/1
        sub = X + (M - X) * im
        j = OFF + karr

        # E (vertical)
        e_ext = E_up + E
        e_open = H_up + O + E
        e_is_open = e_open >= e_ext
        Enew = jnp.maximum(e_ext, e_open)
        if with_stats:
            Emat = jnp.where(e_is_open, mat_up, Emat_up)
            Ealn = jnp.where(e_is_open, aln_up, Ealn_up) + 1

        # Hd = best of diag / E
        diag_term = Hd_diag + sub
        d_wins = diag_term >= Enew
        Hd = jnp.maximum(diag_term, Enew)
        if with_stats:
            Hmat = jnp.where(d_wins, mat_diag + im, Emat)
            Haln = jnp.where(d_wins, aln_diag, Ealn - 1) + 1

        # boundary lane j == 0 (global mode)
        at0 = j == 0
        b_H = O + E * i
        Hd = jnp.where(at0, b_H, Hd)
        Enew = jnp.where(at0, b_H, Enew)
        if with_stats:
            Hmat = jnp.where(at0, 0, Hmat)
            Haln = jnp.where(at0, i, Haln)
            Emat = jnp.where(at0, 0, Emat)
            Ealn = jnp.where(at0, i, Ealn)

        # invalid lanes beyond the template
        invalid = j > tlen_col
        Hd = jnp.where(invalid, NEG, Hd)
        Enew = jnp.where(invalid, NEG, Enew)

        # F (horizontal) max-plus prefix scan, Hillis-Steele over lanes;
        # combine keeps right on ties (ops/banded.py _combine_rightmax)
        v = Hd + O - E * karr
        if with_stats:
            fm = Hmat
            fa = Haln - karr
        step = 1
        while step < B:
            vs = shift_row(v, -step, NEG)
            keep = v >= vs
            if with_stats:
                ms = shift_row(fm, -step, NEG)
                as_ = shift_row(fa, -step, NEG)
                fm = jnp.where(keep, fm, ms)
                fa = jnp.where(keep, fa, as_)
            v = jnp.where(keep, v, vs)
            step *= 2
        # exclusive: shift right by one (score fill NEG, stats fill 0)
        v = shift_row(v, -1, NEG)
        F = v + E * karr
        if with_stats:
            Fmat = shift_row(fm, -1, 0)
            Faln = shift_row(fa, -1, 0) + karr

        hd_wins = Hd >= F
        Hnew = jnp.maximum(Hd, F)
        if with_stats:
            mat_new = jnp.where(hd_wins, Hmat, Fmat)
            aln_new = jnp.where(hd_wins, Haln, Faln)

        # moves byte
        choice = jnp.where(
            hd_wins & d_wins, MOVE_DIAG,
            jnp.where(hd_wins, MOVE_UP, MOVE_LEFT)).astype(jnp.uint8)
        ebit = jnp.where(e_is_open, 0, EBIT_EXT).astype(jnp.uint8)
        H_left = shift_row(Hnew, -1, NEG)
        f_is_open = F == (H_left + O + E)
        fbit = jnp.where(f_is_open, 0, FBIT_EXT).astype(jnp.uint8)
        moves_rows.append((choice | ebit | fbit)[:, None, :])

        rows_new = ([Hnew, Enew, mat_new, aln_new, Emat, Ealn, OFF]
                    if with_stats else [Hnew, Enew, OFF])
        ch_new = jnp.stack(rows_new, axis=0)
        ch = jnp.where(live_col[None], ch_new, ch)

    moves_ref[...] = jnp.concatenate(moves_rows, axis=1)
    ch_ref[:] = ch

    @pl.when(r == pl.num_programs(1) - 1)
    def _():
        fin_ref[:, 0, :] = ch[0]
        if with_stats:
            fin_ref[:, 1, :] = ch[2]
            fin_ref[:, 2, :] = ch[3]
            fin_ref[:, 3:8, :] = jnp.zeros((G, 5, band), jnp.int32)
        else:
            fin_ref[:, 1:8, :] = jnp.zeros((G, 7, band), jnp.int32)


def batched_align_global_moves(
    qs: jnp.ndarray,
    qlens: jnp.ndarray,
    ts: jnp.ndarray,
    tlens: jnp.ndarray,
    params: AlignParams = AlignParams(),
    band: int | None = None,
    maxshift: int = 4,
    interpret: bool = False,
    with_stats: bool = True,
    gblock: int | None = None,
):
    """Batched global banded alignment with move emission (Pallas).

    Drop-in for the vmapped scan aligner used by the consensus rounds
    (consensus/star.py): same argument shapes — (..., Qmax) uint8 queries,
    (...,) lengths, (..., Tmax) uint8 templates — and the same
    (BandedResult, moves, offs) result tuple.  ``with_stats=False``
    mirrors ops/banded.py's slim mode: moves/offs/score are identical,
    BandedResult.mat/aln are zeros, and the kernel drops the stat
    channels from its carry (the consensus rounds never read them).
    ``gblock`` overrides the per-grid-step problem block (default
    GBLOCK=8 = one native VPU sublane tile; 16/32 trade VMEM for fewer
    grid steps — CCSX_PALLAS_GBLOCK env for A/B sweeps).  The env var is
    resolved HERE, outside the jit boundary, so flipping it between
    calls retraces with the new value.
    """
    if gblock is None:
        import os

        raw = os.environ.get("CCSX_PALLAS_GBLOCK", "")
        try:
            gblock = int(raw) if raw else GBLOCK
        except ValueError:
            raise ValueError(
                f"CCSX_PALLAS_GBLOCK={raw!r}: expected an integer >= 1")
    if gblock < 1:
        raise ValueError(
            f"gblock/CCSX_PALLAS_GBLOCK must be >= 1, got {gblock}")
    return _batched_align_impl(
        qs, qlens, ts, tlens, params=params, band=band, maxshift=maxshift,
        interpret=interpret, with_stats=with_stats, gblock=gblock)


@functools.partial(
    jax.jit,
    static_argnames=("params", "band", "maxshift", "interpret",
                     "with_stats", "gblock"))
def _batched_align_impl(
    qs: jnp.ndarray,
    qlens: jnp.ndarray,
    ts: jnp.ndarray,
    tlens: jnp.ndarray,
    params: AlignParams,
    band: int | None,
    maxshift: int,
    interpret: bool,
    with_stats: bool,
    gblock: int,
):
    B = band if band is not None else params.band
    if maxshift > 7:
        # d rides lane 0 of the ismatch tile in bits 1-3 (see _kernel_g)
        raise ValueError(f"maxshift={maxshift} exceeds the 3-bit pack limit")
    lead = qs.shape[:-1]
    qmax = qs.shape[-1]
    if qmax > PALLAS_MAX_QMAX:
        raise ValueError(
            f"qmax={qmax} exceeds PALLAS_MAX_QMAX={PALLAS_MAX_QMAX}; "
            "use the scan aligner")
    n = 1
    for s in lead:
        n *= s
    qs_f = qs.reshape(n, qmax)
    qlens_f = qlens.reshape(n).astype(jnp.int32)
    ts_f = ts.reshape(n, ts.shape[-1])
    tlens_f = tlens.reshape(n).astype(jnp.int32)

    # pad the problem axis to a gblock multiple (pad rows: qlen 0, tlen 0)
    npad = -(-n // gblock) * gblock
    if npad != n:
        pad = npad - n
        qs_f = jnp.concatenate(
            [qs_f, jnp.full((pad, qmax), PAD, qs_f.dtype)])
        qlens_f = jnp.concatenate([qlens_f, jnp.zeros((pad,), jnp.int32)])
        ts_f = jnp.concatenate(
            [ts_f, jnp.full((pad, ts_f.shape[-1]), PAD, ts_f.dtype)])
        tlens_f = jnp.concatenate([tlens_f, jnp.zeros((pad,), jnp.int32)])

    offs = jax.vmap(
        lambda ql, tl: compute_offsets(ql, tl, qmax, B, maxshift)
    )(qlens_f, tlens_f)
    ismatch = jax.vmap(
        lambda q, t, o: compute_ismatch(q, t, o, B, maxshift)
    )(qs_f, ts_f, offs)

    if qmax % ROWBLOCK != 0:
        raise ValueError(f"qmax={qmax} must be a multiple of {ROWBLOCK}")
    dmat = offs - jnp.concatenate(
        [jnp.zeros((npad, 1), jnp.int32), offs[:, :-1]], axis=1)
    rows = jnp.arange(1, qmax + 1, dtype=jnp.int32)
    live = (rows[None, :] <= qlens_f[:, None]).astype(jnp.int32)
    # bit-pack the per-row scalars into lane 0 of the ismatch tile (see
    # _kernel_g docstring): bit 0 match, bits 1-3 d, bit 4 live
    aux = (((dmat & 7) << 1) | (live << 4)).astype(jnp.int8)
    lane_is0 = (jnp.arange(B, dtype=jnp.int32) == 0)[None, None, :]
    ismatch = jnp.where(lane_is0, ismatch | aux[:, :, None], ismatch)

    kern = functools.partial(
        _kernel_g, qmax=qmax, band=B, maxshift=maxshift, params=params,
        with_stats=with_stats, gblock=gblock)
    nb = qmax // ROWBLOCK
    moves, fin = pl.pallas_call(
        kern,
        grid=(npad // gblock, nb),
        in_specs=[
            pl.BlockSpec((gblock, 1), lambda i, r: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((gblock, ROWBLOCK, B), lambda i, r: (i, r, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((gblock, ROWBLOCK, B), lambda i, r: (i, r, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((gblock, 8, B), lambda i, r: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((npad, qmax, B), jnp.uint8),
            jax.ShapeDtypeStruct((npad, 8, B), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM(
            (_CHG if with_stats else 3, gblock, B), jnp.int32)],
        interpret=interpret,
    )(tlens_f[:, None], ismatch)
    moves = moves[:n]
    fin = fin[:n]
    offs = offs[:n]
    qlens_f = qlens_f[:n]
    tlens_f = tlens_f[:n]

    # final-row extraction (mirrors ops/banded.py global-mode epilogue)
    off_fin = offs[:, -1]
    laneT = tlens_f - off_fin
    reachable = (laneT >= 0) & (laneT < B)
    lane = jnp.clip(laneT, 0, B - 1)
    take = jax.vmap(lambda f, l: f[:, l])(fin, lane)  # (n, 8)
    zeros = jnp.zeros(lead, jnp.int32)
    res = BandedResult(
        score=jnp.where(reachable, take[:, 0], NEG).reshape(lead),
        qb=jnp.zeros(lead, jnp.int32),
        qe=qlens_f.reshape(lead),
        tb=jnp.zeros(lead, jnp.int32),
        te=tlens_f.reshape(lead),
        aln=jnp.where(reachable, take[:, 2], 0).reshape(lead)
        if with_stats else zeros,
        mat=jnp.where(reachable, take[:, 1], 0).reshape(lead)
        if with_stats else zeros,
    )
    moves = moves.reshape(lead + (qmax, B))
    offs = offs.reshape(lead + (qmax,))
    return res, moves, offs
