"""Config/code fingerprints for resume-compatibility checks.

A checkpoint (the run journal, utils/journal.py v2; the quality bench's
``.partial`` artifact, benchmarks/quality.py) is only resumable into a
run that would have produced byte-identical output — resuming across a
consensus-code or consensus-config change silently mixes old-code
sections into an artifact that claims the new code.  Both consumers pin
these fingerprints and refuse (recompute from scratch) on mismatch.

``code_fingerprint`` hashes the consensus-critical sources directly
(config + consensus/ops/pipeline modules) rather than reading git HEAD:
uncommitted edits must invalidate a checkpoint too, and the hash needs
no git binary or repository to work from an installed tree.
"""

from __future__ import annotations

import dataclasses
import functools
import hashlib
import json
import os

# modules whose behavior shapes consensus OUTPUT bytes.  io/parallel are
# deliberately out: how bytes are parsed in or sharded across hosts is
# pinned byte-identical by tests, and including them would invalidate
# checkpoints on changes that cannot alter output.  pipeline/fleet.py
# rides in via the pipeline dir, so a leased-range journal (fleet mode
# stamps its split into the journal's input_id: in#lease<i>/<m>@<table>)
# is additionally invalidated by fleet-scheduler changes — conservative,
# never stale.
_SRC_DIRS = ("consensus", "ops", "pipeline")

# CcsConfig fields that tile/observe but never change output bytes
# (bucketing is masked padding — pinned by
# test_pass_buckets_knob_output_invariant — and backend choice is
# bit-identical by the differential suite)
_NON_SEMANTIC = frozenset({
    "threads", "verbose", "device", "mesh_shape", "metrics_path",
    "trace_path", "stall_timeout_s",
    "pass_buckets", "zmw_microbatch", "chunk_size", "chunk_growth",
    "chunk_cap",
    # resilient execution (pipeline/resilience.py): deadlines/breaker
    # only choose WHERE a request computes (device vs the bit-exact
    # host spec), and the failure budget only changes the rc — none
    # can change output bytes, and the canonical recovery move ("it
    # hung; re-run WITH --dispatch-deadline and resume") must not be
    # refused as a config change
    "dispatch_deadline_s", "breaker_strikes", "breaker_window_s",
    "breaker_probe_s", "max_failed_holes",
    # hostile-input salvage (io/corruption.py): on the bytes a resume
    # re-reads, salvage changes nothing until the first corrupt byte —
    # exactly where a fail-fast run died — so the canonical recovery
    # move ("it died on a corrupt block; re-run WITH --salvage and
    # resume") must not be refused as a config change.  The emitted
    # prefix is byte-identical either way (pinned by test_salvage).
    # max_record_bytes stays SEMANTIC: it redefines which healthy
    # records are accepted, so resuming across a change would splice
    # sections read under different acceptance rules.
    "salvage",
    # pre-alignment plane (ops/sketch.py + ops/seed_device.py): the
    # prefilter only rejects pairs whose strand_match acceptance
    # would fail (the walk discards a failed pair's payload), and the
    # device seeder is bit-equal to the host one — neither can change
    # output bytes (pinned by the scale-config md5 across prefilter
    # on/off and both crossover settings)
    "prefilter", "seed_device_min_t",
    # banded DP-fill backend (consensus/star.banded_impl): scan, pallas
    # and rotband are pinned bit-identical by the three-way differential
    # suite and the scale-config md5 across all three values, so the
    # knob (and the canonical A/B move "re-run WITH --banded-impl X and
    # resume") can never change output bytes
    "banded_impl",
})


@functools.lru_cache(maxsize=1)
def code_fingerprint() -> str:
    """Short stable hash of the consensus-critical source files."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = [os.path.join(root, "config.py")]
    for d in _SRC_DIRS:
        dd = os.path.join(root, d)
        paths += [os.path.join(dd, f) for f in sorted(os.listdir(dd))
                  if f.endswith(".py")]
    h = hashlib.sha256()
    for p in paths:
        h.update(os.path.relpath(p, root).encode())
        with open(p, "rb") as f:
            h.update(f.read())
    return h.hexdigest()[:16]


def config_fingerprint(cfg) -> str:
    """Short hash of the output-shaping fields of a CcsConfig."""
    d = dataclasses.asdict(cfg)
    for k in _NON_SEMANTIC:
        d.pop(k, None)
    if d.get("exclude_holes") is not None:
        d["exclude_holes"] = sorted(d["exclude_holes"])
    blob = json.dumps(d, sort_keys=True, default=repr)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def run_fingerprint(cfg) -> str:
    """The journal v2 compatibility key: code + config, either mismatch
    refuses a resume."""
    return f"{code_fingerprint()}-{config_fingerprint(cfg)}"
