"""Minimal BAM reader over a plain gzip stream (Python fallback path).

Replicates the semantics of the reference's bamlite (bamlite.c:78-165):
BAM-through-gzip — BGZF files are valid multi-member gzip streams, so
sequential reading works without BGZF block handling (bamlite.h:13-19 makes
the same choice; no random access).  Per record we decode the read name,
the 4-bit packed sequence via the =ACMGRSVTWYHKDBN table (seqio.h:92,
bamlite.h:86) and qualities as phred+33 clamped at 126 (seqio.h:113).

Truncated-stream handling mirrors bamlite: a clean EOF at a record boundary
ends the stream; a partial record raises.
"""

from __future__ import annotations

import gzip
import io
import os
import struct
import zlib
from typing import Iterator, Optional

import numpy as np

from ccsx_tpu.io.corruption import (CorruptionError,
                                    DEFAULT_MAX_RECORD_BYTES,
                                    MIN_RECORD_BLOCK, SCAN_LOOKAHEAD,
                                    SalvageSink, record_plausible)
from ccsx_tpu.io.fastx import FastxRecord

SEQ_NT16 = b"=ACMGRSVTWYHKDBN"

# 2x256 lookup: byte -> two ASCII bases (high nibble first, bamlite.h:86)
_NIB = np.empty((256, 2), dtype=np.uint8)
for _b in range(256):
    _NIB[_b, 0] = SEQ_NT16[_b >> 4]
    _NIB[_b, 1] = SEQ_NT16[_b & 0xF]


class BamError(CorruptionError):
    """Classified BAM/BGZF parse failure (io/corruption.py taxonomy).

    Subclasses CorruptionError(ValueError), so every pre-taxonomy
    handler (``except BamError`` / ``except ValueError``) still works;
    ``reason`` is the stable code both reader stacks report."""

    def __init__(self, msg: str, reason: str = "bam_bad_record"):
        super().__init__(reason, msg)


def check_record_length(block_size: int,
                        max_record_bytes: int = 0) -> None:
    """THE allocation-bound check on one alignment record's length
    field, shared by the sequential reader and the byte-range sharded
    reader (io/bamindex.py): reject BEFORE any read() allocates, with
    the oversize-vs-corrupt reason split made in exactly one place."""
    max_rec = max_record_bytes or DEFAULT_MAX_RECORD_BYTES
    if not 32 <= block_size <= max_rec:
        raise BamError(
            f"corrupt BAM record length {block_size}"
            + (f" (exceeds the --max-record-bytes bound {max_rec})"
               if block_size > max_rec else ""),
            "bam_record_oversize" if block_size > max_rec
            else "bam_bad_record")


def _read_exact(f, n: int, what: str,
                reason: str = "bam_bad_record") -> bytes:
    buf = f.read(n)
    if len(buf) != n:
        raise BamError(f"truncated BAM: short read in {what}", reason)
    return buf


def read_bam_header(f) -> dict:
    magic = _read_exact(f, 4, "magic", "bam_bad_header")
    if magic != b"BAM\x01":
        raise BamError("invalid BAM header", "bam_bad_header")  # bamlite.c:84
    (l_text,) = struct.unpack("<i",
                              _read_exact(f, 4, "l_text", "bam_bad_header"))
    # allocation bound: a corrupt length field must be rejected BEFORE
    # the read allocates (a flipped high bit reads as multi-GB)
    if not 0 <= l_text <= DEFAULT_MAX_RECORD_BYTES:
        raise BamError(f"corrupt BAM header: l_text={l_text}",
                       "bam_bad_header")
    text = _read_exact(f, l_text, "text", "bam_bad_header").rstrip(
        b"\x00").decode(errors="replace")
    (n_ref,) = struct.unpack("<i",
                             _read_exact(f, 4, "n_ref", "bam_bad_header"))
    if not 0 <= n_ref <= 1 << 24:
        raise BamError(f"corrupt BAM header: n_ref={n_ref}",
                       "bam_bad_header")
    refs = []
    for _ in range(n_ref):
        (l_name,) = struct.unpack(
            "<i", _read_exact(f, 4, "ref name len", "bam_bad_header"))
        if not 1 <= l_name <= 4096:
            raise BamError(f"corrupt BAM header: ref name len={l_name}",
                           "bam_bad_header")
        name = _read_exact(f, l_name, "ref name",
                           "bam_bad_header")[:-1].decode(errors="replace")
        (l_ref,) = struct.unpack(
            "<i", _read_exact(f, 4, "ref len", "bam_bad_header"))
        refs.append((name, l_ref))
    return {"text": text, "refs": refs}


def read_bam_records(path_or_file, with_aux: bool = False,
                     salvage: Optional[SalvageSink] = None,
                     max_record_bytes: int = 0):
    """Stream BAM alignment records as FastxRecords (name/seq/qual).

    With ``with_aux``, yields (FastxRecord, aux_dict) pairs instead,
    where aux_dict is parse_aux of the record's tag region
    (bamlite.c:215-290 equivalent; ccsx's hot path never reads tags).

    ``salvage`` (a SalvageSink) selects salvage mode: classified
    corruption is booked and RESYNCED past — BGZF block resync on
    container damage, plausible-record scan on record damage
    (io/corruption.py spec) — instead of raised.  Without it, the
    historical fail-fast behavior is preserved byte-for-byte (the
    first classified corruption raises BamError).  ``max_record_bytes``
    (0 = DEFAULT_MAX_RECORD_BYTES) is the allocation bound on one
    alignment record, enforced BEFORE allocating either way."""
    max_rec = max_record_bytes or DEFAULT_MAX_RECORD_BYTES
    if salvage is not None:
        yield from _read_bam_salvage(path_or_file, with_aux, salvage,
                                     max_record_bytes
                                     or salvage.max_record_bytes)
        return
    bgzf_path = None
    if hasattr(path_or_file, "read"):
        raw = path_or_file
    else:
        raw = open(path_or_file, "rb")
        bgzf_path = path_or_file
    # transparent gzip/BGZF
    if not hasattr(raw, "peek"):
        raw = io.BufferedReader(raw)
    if raw.peek(2)[:2] == b"\x1f\x8b":
        head = raw.peek(14)
        # BGZF = FEXTRA set (byte 3 bit 2) AND a leading BC subfield; a
        # plain-gzip member whose stored FNAME happens to contain "BC"
        # at offset 12 must NOT be treated as BGZF
        if bgzf_path is not None and not (
                len(head) >= 14 and head[3] & 0x04
                and head[12:14] == b"BC"):
            bgzf_path = None    # plain gzip, no EOF-marker contract
        f = io.BufferedReader(gzip.GzipFile(fileobj=raw))
    else:
        f = raw
        bgzf_path = None

    def check_eof_marker():
        # a BGZF file must end with the 28-byte empty EOF block; a file
        # cut exactly at a member boundary otherwise reads as a clean
        # (shorter) stream.  Same check as the native reader (BgzfMT),
        # so pipeline behavior doesn't depend on which backend loaded.
        if bgzf_path is None:
            return
        with open(bgzf_path, "rb") as fh:
            fh.seek(0, 2)
            size = fh.tell()
            fh.seek(max(0, size - len(BGZF_EOF)))
            if fh.read() != BGZF_EOF:
                raise BamError("BGZF stream missing EOF marker "
                               "(truncated at a block boundary?)",
                               "bgzf_missing_eof")

    read_bam_header(f)
    while True:
        head = f.read(4)
        if len(head) == 0:
            check_eof_marker()
            return  # clean EOF (bamlite.c:141 returns -1)
        if len(head) < 4:
            raise BamError("truncated BAM: partial block size")
        (block_size,) = struct.unpack("<i", head)
        # bound BEFORE the read allocates: a corrupt int32 must not
        # drive a multi-GB buffer (and a negative one would read(-1)
        # the whole rest of the stream)
        check_record_length(block_size, max_rec)
        block = _read_exact(f, block_size, "alignment block")
        rec, aux_buf = decode_record(block)
        if with_aux:
            yield rec, parse_aux(aux_buf)
        else:
            yield rec


def decode_record(block: bytes):
    """One alignment block -> (FastxRecord, aux_region_bytes).

    THE record decode — name, 4-bit packed sequence via the
    =ACMGRSVTWYHKDBN table (seqio.h:92), qualities phred+33 clamped at
    126 (seqio.h:113).  Shared by the sequential reader above and the
    byte-range sharded reader (io/bamindex.py) so the two streams can
    never diverge in decode semantics."""
    if len(block) < 32:
        raise BamError(f"corrupt BAM record: {len(block)}-byte block")
    (refid, pos, l_read_name, mapq, bin_, n_cigar, flag, l_seq,
     next_ref, next_pos, tlen) = struct.unpack("<iiBBHHHiiii", block[:32])
    # field-consistency audit (the native reader makes the same checks,
    # io_native.cpp BamReader::next): a corrupt length field must
    # classify as bam_bad_record, not surface as a numpy bounds error
    if (l_read_name < 1 or l_seq < 0
            or 32 + l_read_name + 4 * n_cigar + (l_seq + 1) // 2 + l_seq
            > len(block)):
        raise BamError(
            f"corrupt BAM record fields (l_read_name={l_read_name}, "
            f"n_cigar={n_cigar}, l_seq={l_seq}, block={len(block)})")
    off = 32
    name = block[off:off + l_read_name - 1].decode(errors="replace")
    off += l_read_name
    off += 4 * n_cigar
    nseq_bytes = (l_seq + 1) // 2
    packed = np.frombuffer(block, dtype=np.uint8,
                           count=nseq_bytes, offset=off)
    seq = _NIB[packed].reshape(-1)[:l_seq].tobytes()
    off += nseq_bytes
    qual_raw = np.frombuffer(block, dtype=np.uint8, count=l_seq,
                             offset=off)
    # phred+33 clamped at 126 (seqio.h:113)
    qual = np.minimum(qual_raw.astype(np.int16) + 33, 126).astype(
        np.uint8).tobytes()
    return (FastxRecord(name=name, comment="", seq=seq, qual=qual),
            block[off + l_seq:])


# ---- aux-tag walk (bamlite.c:215-290) ------------------------------------
#
# ccsx itself never reads aux tags, but bamlite ships the full walk +
# typed getters; parity keeps them available (real subreads.bam carries
# np/rq/sn/... tags a downstream user may want).

_AUX_SCALAR = {"c": "<b", "C": "<B", "s": "<h", "S": "<H",
               "i": "<i", "I": "<I", "f": "<f", "d": "<d"}


def parse_aux(buf: bytes) -> dict:
    """Walk an alignment record's aux region into {tag: (type, value)}.

    Mirrors bam_aux_get/skip_aux (bamlite.c:192-241): scalar types
    c/C/s/S/i/I/f/d, char A, NUL-terminated Z/H, and B arrays."""
    out = {}
    off, n = 0, len(buf)
    try:
        while off + 3 <= n:
            tag = buf[off:off + 2].decode("ascii", errors="replace")
            typ = chr(buf[off + 2])
            off += 3
            if typ in _AUX_SCALAR:
                fmt = _AUX_SCALAR[typ]
                val = struct.unpack_from(fmt, buf, off)[0]
                off += struct.calcsize(fmt)
            elif typ == "A":
                val = chr(buf[off])
                off += 1
            elif typ in "ZH":
                end = buf.index(b"\x00", off)
                val = buf[off:end].decode(errors="replace")
                off = end + 1
            elif typ == "B":
                sub = chr(buf[off])
                (cnt,) = struct.unpack_from("<i", buf, off + 1)
                if sub not in _AUX_SCALAR:
                    raise BamError(f"bad B-array sub-type {sub!r}")
                fmt = _AUX_SCALAR[sub]
                size = struct.calcsize(fmt)
                off += 5
                # a negative/oversized count is corruption; without the
                # guard `off += cnt * size` could walk backwards and
                # loop forever
                if cnt < 0 or off + cnt * size > n:
                    raise BamError(f"bad B-array count {cnt} for {tag}")
                val = [struct.unpack_from(fmt, buf, off + i * size)[0]
                       for i in range(cnt)]
                off += cnt * size
            else:
                raise BamError(f"unknown aux type {typ!r} for tag {tag}")
            out[tag] = (typ, val)
    except (ValueError, IndexError, struct.error) as e:
        if isinstance(e, BamError):
            raise
        raise BamError(f"corrupt aux data: {e}") from e
    return out


def _aux_tv(aux: dict, tag: str):
    return aux.get(tag, ("", None))


def aux2i(aux: dict, tag: str) -> int:
    """Integer getter: c/C/s/S/i/I else 0 (bam_aux2i, bamlite.c:243-252)."""
    typ, val = _aux_tv(aux, tag)
    return int(val) if typ in tuple("cCsSiI") else 0


def aux2f(aux: dict, tag: str) -> float:
    """Float getter: f else 0.0 (bam_aux2f, bamlite.c:254-260)."""
    typ, val = _aux_tv(aux, tag)
    return float(val) if typ == "f" else 0.0


def aux2d(aux: dict, tag: str) -> float:
    """Double getter: d else 0.0 (bam_aux2d, bamlite.c:262-268)."""
    typ, val = _aux_tv(aux, tag)
    return float(val) if typ == "d" else 0.0


def aux2A(aux: dict, tag: str) -> str:
    """Char getter: A else '\\0' (bam_aux2A, bamlite.c:270-276)."""
    typ, val = _aux_tv(aux, tag)
    return val if typ == "A" else "\x00"


def aux2Z(aux: dict, tag: str):
    """String getter: Z/H else None (bam_aux2Z, bamlite.c:278-285)."""
    typ, val = _aux_tv(aux, tag)
    return val if typ in ("Z", "H") else None


# ---- salvage-mode reading (io/corruption.py taxonomy + resync spec) ------
#
# Salvage mode degrades per-record, not per-file: classified corruption
# books an event into the SalvageSink and the reader RESYNCS —
#   * BGZF container damage: scan the raw file forward for the next
#     valid block header (magic + BC subfield + a BSIZE that chains to
#     another block header or EOF);
#   * record damage (or the gap a skipped block leaves): scan the
#     inflated stream for the next plausible record start
#     (corruption.record_plausible — the contract io_native.cpp
#     mirrors byte-for-byte, pinned by the differential fuzz tests).
# Records that survive flow on unchanged; a hole that lost records
# emits a consensus from its surviving passes (it is damaged either
# way — the salvage invariant only constrains undamaged holes).

_BGZF_MAGIC3 = b"\x1f\x8b\x08"


def _read_bgzf_header(f, pos: int, size: int):
    """(bsize, xlen, ok) for a candidate BGZF block header at file
    offset ``pos``; bsize is the total on-disk block size.  Pure
    structure check — shared by the salvage block walk and its resync
    scan (and mirrored by io_native.cpp's read_raw/try_candidate)."""
    if size - pos < 12:
        return 0, 0, False
    f.seek(pos)
    head = f.read(12)
    if len(head) < 12 or head[:3] != _BGZF_MAGIC3 or not head[3] & 4:
        return 0, 0, False
    (xlen,) = struct.unpack_from("<H", head, 10)
    extra = f.read(xlen)
    if len(extra) < xlen:
        return 0, xlen, False
    i = 0
    while i + 4 <= xlen:
        (slen,) = struct.unpack_from("<H", extra, i + 2)
        if extra[i:i + 2] == b"BC" and slen == 2 and i + 6 <= xlen:
            (bs,) = struct.unpack_from("<H", extra, i + 4)
            bsize = bs + 1
            if bsize >= 12 + xlen + 8:
                return bsize, xlen, True
            return 0, xlen, False
        i += 4 + slen
    return 0, xlen, False


def _bgzf_salvage_chunks(path: str, sink: SalvageSink):
    """Yield (inflated_block_bytes, gap_before) over a possibly-damaged
    BGZF file, STREAMING — O(one block) of memory, never the whole file
    (salvage exists for production-sized inputs).  Container damage
    books one event per resync region: header damage -> bgzf_bad_block
    + forward scan for the next valid chained header; payload damage ->
    bgzf_bad_deflate + skip the block; truncation -> bgzf_torn_tail;
    a missing EOF marker -> bgzf_missing_eof (degrades but is
    budget-exempt: no hole is provably lost)."""
    with open(path, "rb") as f:
        f.seek(0, 2)
        size = f.tell()
        pos = 0
        gap = False
        last_was_eof_marker = False

        def try_candidate(cand: int) -> bool:
            """Valid chained header at cand: its BSIZE lands exactly on
            EOF or on another block magic (the header-integrity check
            BGZF itself lacks)."""
            bsize, _, ok = _read_bgzf_header(f, cand, size)
            if not ok or cand + bsize > size:
                return False
            if cand + bsize == size:
                return True
            f.seek(cand + bsize)
            return f.read(3) == _BGZF_MAGIC3

        def rescan(start: int) -> int:
            """Next offset > start holding a valid chained block
            header, or -1 — a windowed forward scan (2-byte overlap so
            a magic spanning two windows is still seen)."""
            o = start + 1
            while o + 12 <= size:
                f.seek(o)
                win = f.read(1 << 16)
                if len(win) < 3:
                    break
                j = win.find(_BGZF_MAGIC3)
                while j != -1:
                    if try_candidate(o + j):
                        return o + j
                    j = win.find(_BGZF_MAGIC3, j + 1)
                o += max(len(win) - 2, 1)
            return -1

        while pos < size:
            bsize, xlen, ok = _read_bgzf_header(f, pos, size)
            if not ok or pos + bsize > size:
                # header damage (or a block running past EOF = torn tail)
                sink.record("bgzf_torn_tail" if ok or size - pos < 12
                            else "bgzf_bad_block")
                last_was_eof_marker = False
                nxt = rescan(pos)
                if nxt == -1:
                    break
                pos, gap = nxt, True
                continue
            f.seek(pos + 12 + xlen)
            payload = f.read(bsize - 12 - xlen - 8)
            crc, isize = struct.unpack("<II", f.read(8))
            last_was_eof_marker = len(payload) <= 4 and isize == 0
            if isize > 1 << 16:
                # BGZF caps the uncompressed block at 64KB; a larger
                # ISIZE is a payload lie — reject before allocating
                sink.record("bgzf_bad_deflate")
                pos, gap = pos + bsize, True
                continue
            try:
                data = zlib.decompress(payload, -15)
            except zlib.error:
                data = None
            if (data is None or len(data) != isize
                    or zlib.crc32(data) != crc):
                sink.record("bgzf_bad_deflate")
                pos, gap = pos + bsize, True
                continue
            pos += bsize
            if data:
                yield data, gap
                gap = False
        if not last_was_eof_marker:
            sink.record("bgzf_missing_eof")


def _gzip_salvage_chunks(f, sink: SalvageSink, own: bool = False):
    """Yield (chunk, False) from a plain-gzip (or raw) stream; a
    corrupt/truncated deflate stream has no block structure to resync
    on, so it books one gzip_truncated and ends the stream — the
    records already delivered are the salvage.  ``own``: this
    generator opened the handle and closes it at exhaustion."""
    try:
        while True:
            try:
                data = f.read(1 << 16)
            except (OSError, EOFError, zlib.error):
                sink.record("gzip_truncated")
                return
            if not data:
                return
            yield data, False
    finally:
        if own:
            try:
                f.close()
            except OSError:
                pass


class _SalvageFeed:
    """Byte feed over a (chunk, gap_before) iterator with explicit gap
    surfacing: bytes on the two sides of a gap must never be parsed as
    one contiguous record."""

    def __init__(self, chunks):
        self._it = iter(chunks)
        self.buf = bytearray()
        self.pos = 0
        self._queued = None   # post-gap chunk awaiting take_gap()
        self.eof = False

    def ensure(self, n: int) -> str:
        """'ok' when n bytes are available at pos; 'gap' when a gap
        interrupts first (call take_gap()); 'eof' at stream end."""
        while len(self.buf) - self.pos < n:
            if self._queued is not None:
                return "gap"
            if self.eof:
                return "eof"
            try:
                data, gap = next(self._it)
            except StopIteration:
                self.eof = True
                return "eof"
            if gap:
                self._queued = data
                return "gap"
            self.buf += data
        return "ok"

    def take_gap(self) -> None:
        """Discard the unconsumed pre-gap tail (bytes of a damaged
        record) and absorb the post-gap chunk."""
        del self.buf[self.pos:]
        if self._queued is not None:
            self.buf += self._queued
            self._queued = None

    def avail(self) -> int:
        return len(self.buf) - self.pos

    def compact(self) -> None:
        if self.pos > 1 << 16:
            del self.buf[:self.pos]
            self.pos = 0


def _salvage_scan(feed: _SalvageFeed, max_rec: int) -> str:
    """Advance feed.pos to the next plausible record start ('ok'), or
    consume the tail and report 'eof'.  One byte per rejection — the
    exact scan io_native.cpp mirrors."""
    while True:
        st = feed.ensure(SCAN_LOOKAHEAD)
        if st == "gap":
            feed.take_gap()
            continue
        if st == "eof" and feed.avail() < 36:
            feed.pos = len(feed.buf)
            return "eof"
        if record_plausible(feed.buf, feed.pos, max_rec):
            return "ok"
        feed.pos += 1
        feed.compact()


def _salvage_header(feed: _SalvageFeed) -> bool:
    """Tolerant BAM-header parse over the feed; False = damaged (the
    caller falls back to the record scan)."""
    if feed.ensure(12) != "ok" or bytes(feed.buf[feed.pos:feed.pos + 4]) \
            != b"BAM\x01":
        return False
    (l_text,) = struct.unpack_from("<i", feed.buf, feed.pos + 4)
    if not 0 <= l_text <= DEFAULT_MAX_RECORD_BYTES:
        return False
    if feed.ensure(12 + l_text) != "ok":
        return False
    (n_ref,) = struct.unpack_from("<i", feed.buf, feed.pos + 8 + l_text)
    if not 0 <= n_ref <= 1 << 24:
        return False
    feed.pos += 12 + l_text
    for _ in range(n_ref):
        if feed.ensure(4) != "ok":
            return False
        (l_name,) = struct.unpack_from("<i", feed.buf, feed.pos)
        if not 1 <= l_name <= 4096:
            return False
        if feed.ensure(8 + l_name) != "ok":
            return False
        feed.pos += 8 + l_name
    return True


def _read_bam_salvage(path_or_file, with_aux: bool, sink: SalvageSink,
                      max_rec: int = 0):
    """The salvage-mode record stream: block-resynced BGZF chunks (real
    paths) or a classified plain-gzip stream, walked with the shared
    plausible-record scan."""
    max_rec = max_rec or sink.max_record_bytes
    if isinstance(path_or_file, (str, os.PathLike)) \
            and os.path.exists(str(path_or_file)):
        with open(path_or_file, "rb") as fh:
            head = fh.read(14)
        if (len(head) >= 14 and head[:3] == _BGZF_MAGIC3
                and head[3] & 4 and head[12:14] == b"BC"):
            chunks = _bgzf_salvage_chunks(str(path_or_file), sink)
        else:
            raw = open(path_or_file, "rb")
            if head[:2] == b"\x1f\x8b":
                raw = io.BufferedReader(gzip.GzipFile(fileobj=raw))
            chunks = _gzip_salvage_chunks(raw, sink, own=True)
    else:
        raw = path_or_file
        if not hasattr(raw, "peek"):
            raw = io.BufferedReader(raw)
        if raw.peek(2)[:2] == b"\x1f\x8b":
            raw = io.BufferedReader(gzip.GzipFile(fileobj=raw))
        chunks = _gzip_salvage_chunks(raw, sink)

    feed = _SalvageFeed(chunks)
    resync = False
    if not _salvage_header(feed):
        sink.record("bam_bad_header")
        resync = True
    while True:
        feed.compact()
        if resync:
            if _salvage_scan(feed, max_rec) == "eof":
                return
            resync = False
        st = feed.ensure(4)
        if st == "gap":
            feed.take_gap()
            resync = True
            continue
        if st == "eof":
            if feed.avail():
                sink.record("bam_bad_record")
                feed.pos = len(feed.buf)
            return
        (block_size,) = struct.unpack_from("<i", feed.buf, feed.pos)
        if not MIN_RECORD_BLOCK <= block_size <= max_rec:
            sink.record("bam_record_oversize"
                        if block_size > max_rec else "bam_bad_record")
            feed.pos += 1
            resync = True
            continue
        st = feed.ensure(4 + block_size)
        if st == "gap":
            feed.take_gap()
            resync = True
            continue
        if st == "eof":
            sink.record("bam_bad_record")
            feed.pos = len(feed.buf)
            return
        block = bytes(feed.buf[feed.pos + 4:feed.pos + 4 + block_size])
        try:
            rec, aux_buf = decode_record(block)
        except (BamError, ValueError):
            sink.record("bam_bad_record")
            feed.pos += 1
            resync = True
            continue
        feed.pos += 4 + block_size
        if with_aux:
            try:
                aux = parse_aux(aux_buf)
            except BamError:
                sink.record("bam_bad_record")
                aux = {}
            yield rec, aux
        else:
            yield rec


# BGZF framing (the real subreads.bam container): gzip members <=64KB
# with a "BC" extra subfield holding the compressed block size, ending in
# a fixed 28-byte empty EOF block.  Valid multi-member gzip, so every
# plain-gzip reader (incl. this module's read path and the reference's
# bamlite, bamlite.h:13-19) still reads it; the native reader additionally
# exploits the block structure for parallel inflate (io_native.cpp).
BGZF_EOF = bytes.fromhex(
    "1f8b08040000000000ff0600424302001b0003000000000000000000")
BGZF_BLOCK_PAYLOAD = 0xFF00      # htslib's default uncompressed chunk


def _bgzf_block(data: bytes) -> bytes:
    import zlib

    co = zlib.compressobj(6, zlib.DEFLATED, -15)
    comp = co.compress(data) + co.flush()
    bsize = 18 + len(comp) + 8 - 1          # total block size minus 1
    header = (b"\x1f\x8b\x08\x04" + b"\x00" * 4 + b"\x00\xff"
              + struct.pack("<H", 6) + b"BC" + struct.pack("<HH", 2, bsize))
    return (header + comp + struct.pack("<II", zlib.crc32(data),
                                        len(data) & 0xFFFFFFFF))


def write_bgzf(path, data: bytes) -> None:
    """Write `data` as a BGZF stream (blocked gzip + EOF marker)."""
    with open(path, "wb") as fh:
        for i in range(0, len(data), BGZF_BLOCK_PAYLOAD):
            fh.write(_bgzf_block(data[i:i + BGZF_BLOCK_PAYLOAD]))
        fh.write(BGZF_EOF)


class BamWriter:
    """Ordered unaligned-BAM output writer (CLI --bam).

    Buffers records and writes the BGZF container at close() — CCS
    output is orders of magnitude smaller than the subread input, so
    buffering is fine at real run sizes, and it keeps the writer a thin
    shim over write_bam.  Each record carries the consensus sequence,
    the vote-margin qualities (phred+33 in, raw phred in BAM), and an
    ``rq`` float aux tag (predicted read accuracy = 1 - mean per-base
    error), the tag HiFi consumers expect.  The reference has no BAM
    output (FASTA only, main.c:714)."""

    def __init__(self, path: str):
        self.path = path
        # fail fast on an unwritable path (the container itself is
        # written at close, after hours of compute on real inputs);
        # the container goes to a temp path and is renamed into place
        # at close so a crash mid-run can't leave a zero-byte,
        # EOF-marker-less file at the final path that downstream tools
        # would read as a complete-but-empty run.  The temp name is
        # unique (mkstemp in the target dir, same filesystem for the
        # rename): a fixed path+'.tmp' would leak forever after a crash
        # and let two writers on the same output silently clobber each
        # other's temp before the atomic rename
        import tempfile

        fd, self._tmp = tempfile.mkstemp(
            prefix=os.path.basename(path) + ".tmp.",
            dir=os.path.dirname(os.path.abspath(path)))
        os.close(fd)
        # mkstemp creates 0600; the final BAM must honor the umask like
        # any normally-open()ed output (os.replace preserves the mode)
        umask = os.umask(0)
        os.umask(umask)
        os.chmod(self._tmp, 0o666 & ~umask)
        self._records = []
        self._closed = False

    def put(self, name: str, seq: bytes, qual: bytes | None = None) -> None:
        if self._closed:
            raise ValueError("writer is closed")
        aux = ()
        if qual is not None:
            import numpy as np

            q = np.frombuffer(qual, np.uint8).astype(np.float64) - 33
            rq = 1.0 - float(np.mean(10.0 ** (-q / 10.0))) if len(q) else 0.0
            aux = (("rq", "f", rq),)
        self._records.append((name, seq, qual, aux))

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        write_bam(self._tmp, self._records)
        os.replace(self._tmp, self.path)
        self._records = []


def write_bam(path, records, refs=(), bgzf: bool = True) -> None:
    """Tiny BAM writer for tests/fixtures (unmapped records only).

    BGZF container by default, like real subreads.bam; ``bgzf=False``
    writes one plain gzip member (also valid BAM-through-gzip, and
    exercises the native reader's non-BGZF fallback)."""
    import zlib

    out = io.BytesIO()
    text = b"@HD\tVN:1.6\n"
    out.write(b"BAM\x01")
    out.write(struct.pack("<i", len(text)))
    out.write(text)
    out.write(struct.pack("<i", len(refs)))
    for name, ln in refs:
        nm = name.encode() + b"\x00"
        out.write(struct.pack("<i", len(nm)))
        out.write(nm)
        out.write(struct.pack("<i", ln))
    rev = {v: i for i, v in enumerate(SEQ_NT16)}
    for rec in records:
        name, seq, qual = rec[:3]
        aux = rec[3] if len(rec) > 3 else ()   # (tag, type, value) triples
        nm = name.encode() + b"\x00"
        l_seq = len(seq)
        packed = bytearray((l_seq + 1) // 2)
        for i, b in enumerate(seq):
            code = rev.get(b, 15)
            if i % 2 == 0:
                packed[i // 2] |= code << 4
            else:
                packed[i // 2] |= code
        q = bytes((min(max(x - 33, 0), 93) for x in qual)) if qual \
            else b"\xff" * l_seq
        body = struct.pack("<iiBBHHHiiii", -1, -1, len(nm), 255, 0, 0, 4,
                           l_seq, -1, -1, 0)
        body += nm + bytes(packed) + q
        for tag, typ, val in aux:
            tb = tag.encode("ascii")
            if len(tb) != 2:
                raise BamError(f"aux tag must be 2 ASCII chars: {tag!r}")
            body += tb + typ.encode("ascii")
            if typ in _AUX_SCALAR:
                body += struct.pack(_AUX_SCALAR[typ], val)
            elif typ == "A":
                vb = val.encode("ascii")
                if len(vb) != 1:
                    raise BamError(f"aux A value must be 1 char: {val!r}")
                body += vb
            elif typ in "ZH":
                body += val.encode() + b"\x00"
            else:
                raise BamError(f"unsupported aux write type {typ!r}")
        out.write(struct.pack("<i", len(body)))
        out.write(body)
    data = out.getvalue()
    if bgzf:
        write_bgzf(path, data)
    else:
        with open(path, "wb") as fh:
            fh.write(gzip.compress(data))
