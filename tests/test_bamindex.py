"""BGZF hole index + byte-range sharded ingest (io/bamindex.py).

The contract under test: (a) every rank's range, concatenated in rank
order, reproduces the sequential record stream exactly; (b) each rank
inflates only ~1/N of the compressed bytes; (c) the CLI end-to-end
range-sharded run merges byte-identical to the single-host batched
output (SURVEY §5.8 "each host reads its own input shard").
"""

import json
import os

import numpy as np
import pytest

from ccsx_tpu import cli
from ccsx_tpu.io import bam, bamindex
from ccsx_tpu.ops import encode as enc
from ccsx_tpu.utils import synth


@pytest.fixture
def rng():
    return np.random.default_rng(5)


def _write_bam(path, rng, n_holes=8, tlen=500, n_passes=5):
    zs = [synth.make_zmw(rng, tlen, n_passes, movie="mv", hole=str(h),
                         sub_rate=0.02, ins_rate=0.04, del_rate=0.04)
          for h in range(n_holes)]
    recs = [(name, enc.decode(p).encode(), None)
            for z in zs for name, p in zip(z.names, z.passes)]
    bam.write_bam(str(path), recs)
    return zs, [r[0] for r in recs]


def test_index_build_and_ranges(tmp_path, rng):
    # ~150KB of uncompressed BAM data = 3 BGZF blocks, so byte-range
    # reads can demonstrably touch a proper subset of the file
    p = tmp_path / "in.bam"
    _, names = _write_bam(p, rng, n_holes=15, tlen=2000)
    idx = bamindex.build_index(str(p), every=3)
    assert idx["n_holes"] == 15
    assert idx["n_records"] == len(names)
    assert bamindex.load_index(str(p)) is not None

    seq_names = [r.name for r in bam.read_bam_records(str(p))]
    size = os.path.getsize(p)
    for N in (1, 2, 3, 4, 15, 20):
        got, cbytes = [], []
        for rank in range(N):
            lo, hi = bamindex.hole_range(idx["n_holes"], rank, N)
            got.extend(r.name for r in bamindex.read_hole_range(
                str(p), idx, lo, hi, counter=cbytes.append))
            if N >= 3 and hi > lo:
                # each rank inflates a proper subset of the file
                assert 0 < cbytes[-1] < 0.9 * size
        assert got == seq_names, f"N={N}"

    # record CONTENT identical to the sequential reader on a mid range
    mid = list(bamindex.read_hole_range(str(p), idx, 3, 6))
    ref = [r for r in bam.read_bam_records(str(p))
           if 3 <= int(r.name.split("/")[1]) < 6]
    assert [(a.name, a.seq, a.qual) for a in mid] == \
           [(b.name, b.seq, b.qual) for b in ref]


def test_index_staleness(tmp_path, rng):
    p = tmp_path / "in.bam"
    _write_bam(p, rng, n_holes=3)
    bamindex.build_index(str(p))
    assert bamindex.load_index(str(p)) is not None
    # rewrite the input: the fingerprint (size+mtime) must invalidate
    _write_bam(p, rng, n_holes=4)
    os.utime(p, ns=(1, 1))
    assert bamindex.load_index(str(p)) is None


def test_make_index_rejects_fastx(tmp_path, capsys):
    fa = tmp_path / "in.fa"
    fa.write_text(">x\nACGT\n")
    rc = cli.main(["--make-index", "-A", str(fa), "ignored"])
    assert rc == 1
    assert "BAM" in capsys.readouterr().err


@pytest.mark.slow  # ~80s: three sharded CLI runs + reference run
def test_range_sharded_cli_merge_identical(tmp_path, rng):
    """End-to-end: --make-index, then 2 range-sharded host runs whose
    merge is byte-identical to the single-host batched run, with each
    rank's metrics showing a partial-file ingest."""
    p = tmp_path / "in.bam"
    _write_bam(p, rng, n_holes=8, tlen=2000)   # ~2 BGZF blocks
    ref = tmp_path / "ref.fa"
    assert cli.main(["-m", "1000", "--batch", "on", str(p), str(ref)]) == 0

    assert cli.main(["--make-index", str(p), "ignored"]) == 0
    assert os.path.exists(str(p) + bamindex.INDEX_SUFFIX)
    # fine-grained boundaries for the small fixture (the CLI default
    # every=64 is sized for real inputs, where lead-in is <0.01%)
    bamindex.build_index(str(p), every=2)

    out = tmp_path / "dist.fa"
    size = os.path.getsize(p)
    ingests = []
    for r in range(2):
        m = tmp_path / f"m{r}.jsonl"
        assert cli.main(["-m", "1000", "--hosts", "2", "--host-id", str(r),
                         "--metrics", str(m), str(p), str(out)]) == 0
        final = [json.loads(ln) for ln in m.read_text().splitlines()
                 if json.loads(ln).get("event") == "final"][-1]
        assert 0 < final["ingest_bytes"] <= size
        ingests.append(final["ingest_bytes"])
    # the ranks together inflated strictly less than 2x the file — the
    # whole point of byte-range sharding vs full-parse round-robin
    assert sum(ingests) < 2 * size
    assert cli.main(["--merge-shards", "2", "ignored.in", str(out)]) == 0
    assert out.read_text() == ref.read_text()


def test_merge_refuses_mixed_modes(tmp_path):
    """One rank range-sharded, the other round-robined (stale sidecar on
    one host): merging would silently corrupt, so it must raise."""
    from ccsx_tpu.parallel import distributed as dist

    for r, start in ((0, 0), (1, None)):   # range vs round-robin
        w = dist.ShardWriter(str(tmp_path / "o.fa"), r, 2, append=False,
                             start_ordinal=start)
        w.put_at(0, f"mv/{r}/ccs", b"ACGT")
        w.close()
        # completion markers, so the mode check (not the dead-shard
        # refusal, tests/test_faults.py) is what this exercises
        dist._write_done_marker(str(tmp_path / "o.fa"), r, 2, 1)
    with pytest.raises(ValueError, match="sharding mode"):
        dist.merge_shards(str(tmp_path / "o.fa"), 2)


def test_range_read_detects_corruption(tmp_path, rng):
    """A bit-flipped BGZF block under a range read must raise BamError
    (CRC check), not yield silently wrong records."""
    p = tmp_path / "in.bam"
    _write_bam(p, rng, n_holes=6, tlen=1500)
    idx = bamindex.build_index(str(p), every=2)
    data = bytearray(p.read_bytes())
    data[len(data) // 2] ^= 0xFF        # flip a payload byte mid-file
    p.write_bytes(bytes(data))
    # the index fingerprint still matches (same size; mtime refreshed)
    st = os.stat(p)
    idx["mtime_ns"] = st.st_mtime_ns
    with pytest.raises(bam.BamError):
        for _ in bamindex.read_hole_range(str(p), idx, 0,
                                          idx["n_holes"]):
            pass


def test_range_read_truncated_file(tmp_path, rng):
    """Truncation mid-block under a range read raises, mirroring the
    sequential reader's truncated-stream contract."""
    p = tmp_path / "in.bam"
    _write_bam(p, rng, n_holes=6, tlen=1500)
    idx = bamindex.build_index(str(p), every=2)
    data = p.read_bytes()
    p.write_bytes(data[: len(data) - len(data) // 3])
    idx["size"] = os.path.getsize(p)
    with pytest.raises(bam.BamError):
        for _ in bamindex.read_hole_range(str(p), idx, 0,
                                          idx["n_holes"]):
            pass
