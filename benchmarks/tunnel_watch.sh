#!/bin/sh
# Background tunnel watcher: probe the tunnelled TPU with a real jitted
# dispatch AND a byte materialization (block_until_ready does not wait
# on the lazy axon runtime — memory/axon notes; enumeration alone can
# succeed while dispatch hangs) every ~3 minutes; exit 0 the moment the
# chip answers so the caller can run benchmarks/tpu_battery.sh while
# the window is open.
LOG=${1:-/tmp/tunnel_watch.log}
: > "$LOG"
while true; do
    ts=$(date -u +%H:%M:%S)
    if timeout 90 python -c "
import jax, numpy
v = numpy.asarray(jax.jit(lambda a: a + 1)(numpy.ones(8)))
assert v[0] == 2 and jax.devices()[0].platform != 'cpu'" 2>>"$LOG"; then
        echo "$ts TUNNEL ALIVE" >> "$LOG"
        exit 0
    fi
    echo "$ts probe failed/hung" >> "$LOG"
    sleep 170
done
