"""Deterministic fault-injection harness (the test surface of the
fault-tolerance layer; ARCHITECTURE.md "Failure domains").

The reference has no failure story beyond abort-or-soldier-on (SURVEY.md
§5.5), so there is nothing to inject against; here every recovery path —
per-hole quarantine, OOM resplit, torn-tail journal recovery — must be
provable on CPU in CI, which requires failures that fire on demand and on
a deterministic schedule.

Arming: the ``CCSX_FAULTS`` env var or the ``--inject-faults`` CLI flag,
with a comma-separated spec of ``point@N`` entries:

    CCSX_FAULTS="device_oom@1,write@3"

``point@N`` fires on the Nth call of that point (once); ``point@N+``
fires on every call from the Nth on; bare ``point`` means ``point@1``.
Schedules are call-count based, so a given input + spec reproduces the
same failure every run.

Points and their actions (each placed at ONE spot in the pipeline):

  ingest      raise ValueError at the stream read — the drivers' clean
              rc=1 invalid-input path, no traceback
  compute     raise RuntimeError inside a hole's consensus step — the
              per-hole quarantine path (one bad hole never kills a run)
  device_oom  raise RuntimeError("RESOURCE_EXHAUSTED...") at a
              BatchExecutor device dispatch — the OOM resplit/fallback
              ladder (pipeline/batch.py)
  stall       sleep CCSX_FAULT_STALL_S seconds (default 1.0) INSIDE a
              device dispatch, while its trace span is open — the
              deterministic hang that proves the stall watchdog
              (utils/trace.py, --stall-timeout) fires and dumps; the
              dispatch then completes normally
  device_hang sleep CCSX_FAULT_HANG_S seconds (default 3600) inside a
              device dispatch — a PERMANENT wedge at test scale, the
              r5 dead-tunnel failure made deterministic.  Only the
              dispatch deadline (--dispatch-deadline,
              pipeline/resilience.py) rescues the run: the call is
              abandoned and the group replays on the host path; with
              deadlines off the run stalls exactly as r5 did (watchdog
              dumps, never kills)
  rank_death  hard process exit (os._exit) at a hole-retirement point
              in the batched driver — models a sharded rank dying
              mid-run (SIGKILL/OOM-killer), the failure the
              `ccsx-tpu shepherd` supervisor (pipeline/supervisor.py)
              must detect, restart, and merge through
  write       hard process exit (os._exit) after a record is written and
              flushed but BEFORE the journal advances — the torn-tail
              crash the journal v2 resume must repair
  journal     hard process exit inside a journal DISK update, after the
              tmp journal is fsynced but BEFORE the atomic replace —
              proves the journal update itself is atomic.  Disk updates
              are rate-limited (utils/journal.py fsync_interval_s); set
              CCSX_JOURNAL_FSYNC_S=0 for a deterministic per-advance
              schedule
  input_corrupt  raise a classified CorruptionError (reason
              "injected") at the stream read — with --salvage the
              drivers book a corrupt hole and continue (the salvage
              rung, drivable without a crafted file); without it, the
              clean rc-1 invalid-input path
  disk_full   raise OSError(ENOSPC) inside the synchronous output
              writer's put — the disk-full reality: the run must exit
              through the clean rc-1 path with the journal consistent
              (no traceback, no torn record past the journaled
              offset), and a resume must complete byte-identical
  sigterm     deliver a real SIGTERM to this process at a hole
              retirement (signal.raise_signal, so the drivers'
              graceful-drain handler runs exactly as it would for an
              external kill) — deterministic drain-and-resume testing

The hard exits use ``os._exit`` (no atexit, no finally blocks, writer
not closed) to model SIGKILL as closely as a same-process mechanism can.

**Scoped arming (the serving plane's per-job fault domain)**: a
resident `ccsx-tpu serve` process runs many jobs concurrently in one
address space, so the global plan above would fire on whichever
tenant's thread reaches the point first.  ``scope_arm(spec)`` instead
arms a plan carried by a ``contextvars.ContextVar``: it applies to the
calling thread and to every thread whose target was wrapped with
``inherit()`` at spawn (the deadline runner and the prep pool do this —
contextvars do NOT cross ``threading.Thread`` by default).  While a
scope is set — even an empty one — the global plan is ignored for that
thread family: a job's fault domain is exactly its own spec, and
server-side faults can never leak into a tenant.  Threads outside any
scope (the warmup pool, the HTTP server) keep the global-plan behavior.
"""

from __future__ import annotations

import contextvars
import os
import threading
from typing import Dict, Optional

POINTS = ("ingest", "compute", "device_oom", "stall", "device_hang",
          "rank_death", "write", "journal", "input_corrupt",
          "disk_full", "sigterm")

# exit code of the write/journal crash actions — distinctive, so a test
# (or an operator) can tell an injected kill from a real failure
EXIT_CODE = 57

_UNSET = object()
# point -> [fire_at_call, repeat(bool)]; None = disarmed; _UNSET = not
# yet initialized from the environment
_plan = _UNSET
_calls: Dict[str, int] = {}
# fire() runs on worker threads too (run_pipeline -j>1 computes holes on
# a pool): the call counter must be atomic or an @N schedule can be
# skipped under a racy read-modify-write
_lock = threading.Lock()

# the per-context (per-job) fault domain; None = use the global plan
_scope_var: "contextvars.ContextVar[Optional[Scope]]" = \
    contextvars.ContextVar("ccsx_fault_scope", default=None)


class Scope:
    """One fault domain: a plan plus its own call counters, so two
    jobs arming the same point@N spec each see their own schedule."""

    def __init__(self, spec: Optional[str]):
        self.plan = parse_spec(spec) if spec else None
        self.calls: Dict[str, int] = {}
        self.lock = threading.Lock()


def scope_arm(spec: Optional[str]):
    """Arm ``spec`` for the current context (and for threads spawned
    through ``inherit()``-wrapped targets).  A falsy spec arms an EMPTY
    domain — the caller is isolated from the global plan but fires
    nothing.  Returns a token for ``scope_reset``."""
    return _scope_var.set(Scope(spec))


def scope_reset(token) -> None:
    _scope_var.reset(token)


def current_scope() -> Optional[Scope]:
    return _scope_var.get()


def inherit(fn):
    """Wrap a thread target so the new thread runs in a COPY of the
    spawning thread's context (carrying its fault scope): plain
    ``threading.Thread`` starts every target in a fresh context, which
    would silently drop a job's fault domain at the first pool or
    deadline-runner hop."""
    ctx = contextvars.copy_context()

    def _run(*args, **kwargs):
        return ctx.run(fn, *args, **kwargs)

    return _run


def parse_spec(spec: str) -> dict:
    """``"point@N[+],..."`` -> {point: [n, repeat]}; ValueError on junk."""
    plan = {}
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        point, _, at = item.partition("@")
        repeat = at.endswith("+")
        n = at[:-1] if repeat else at
        if point not in POINTS:
            raise ValueError(
                f"unknown fault point {point!r} (choose from {POINTS})")
        try:
            nth = int(n) if n else 1
        except ValueError:
            raise ValueError(f"bad fault schedule {item!r}: expected "
                             "point@N or point@N+") from None
        if nth < 1:
            raise ValueError(f"fault schedule {item!r}: N must be >= 1")
        plan[point] = [nth, repeat]
    return plan


def arm(spec: Optional[str]) -> None:
    """Arm (or with a falsy spec, disarm) the harness; resets call counts."""
    global _plan
    _plan = parse_spec(spec) if spec else None
    _calls.clear()


def disarm() -> None:
    arm(None)


def armed(point: Optional[str] = None) -> bool:
    scope = _scope_var.get()
    if scope is not None:
        plan = scope.plan
    else:
        _ensure_init()
        plan = _plan
    if plan is None:
        return False
    return point in plan if point else bool(plan)


def _ensure_init() -> None:
    # lazy env arming keeps import free of side effects and lets the CLI
    # flag override the environment (arm() is explicit).  A malformed
    # CCSX_FAULTS must fail ATTRIBUTED to the env var, not surface as a
    # ValueError inside whatever pipeline stage fired first (where the
    # drivers would misreport it as an input-stream error) — so it
    # escalates to SystemExit, which no recovery layer swallows.
    global _plan
    if _plan is _UNSET:
        try:
            _plan = parse_spec(os.environ.get("CCSX_FAULTS", "")) or None
        except ValueError as e:
            _plan = None
            raise SystemExit(f"Error: CCSX_FAULTS: {e}") from None


def fire(point: str) -> None:
    """Injection point hook: a no-op unless this point is armed and its
    schedule says this call is the one.  Raises/exits per the point's
    documented action.  A thread carrying a fault scope consults ONLY
    that scope's plan and counters (its job's fault domain)."""
    scope = _scope_var.get()
    if scope is not None:
        plan, calls, lock = scope.plan, scope.calls, scope.lock
    else:
        _ensure_init()
        plan, calls, lock = _plan, _calls, _lock
    if plan is None or point not in plan:
        return
    with lock:
        calls[point] = n = calls.get(point, 0) + 1
    fire_at, repeat = plan[point]
    if n != fire_at and not (repeat and n >= fire_at):
        return
    import sys

    print(f"[ccsx-tpu] faultinject: firing {point!r} (call {n})",
          file=sys.stderr)
    if point == "ingest":
        raise ValueError(f"injected ingest fault (faultinject, call {n})")
    if point == "input_corrupt":
        # deferred import: corruption.py must stay importable without
        # this module's side effects and vice versa
        from ccsx_tpu.io.corruption import CorruptionError

        raise CorruptionError(
            "injected",
            f"injected input corruption (faultinject, call {n})")
    if point == "disk_full":
        import errno

        raise OSError(errno.ENOSPC,
                      f"No space left on device (injected, call {n})")
    if point == "sigterm":
        import signal

        signal.raise_signal(signal.SIGTERM)
        return
    if point == "compute":
        raise RuntimeError(
            f"injected compute fault (faultinject, call {n})")
    if point == "device_oom":
        raise RuntimeError(
            "RESOURCE_EXHAUSTED: injected device OOM "
            f"(faultinject, call {n})")
    if point in ("stall", "device_hang"):
        # a hang, not a failure: sleep with the dispatch span open.
        # `stall` is transient (the dispatch then completes — proves
        # the watchdog fires); `device_hang` is effectively permanent
        # (default 1 h — proves the dispatch DEADLINE abandons it; the
        # parked thread is daemonic and dies with the process)
        import time

        env, dflt = (("CCSX_FAULT_STALL_S", 1.0) if point == "stall"
                     else ("CCSX_FAULT_HANG_S", 3600.0))
        try:
            dur = float(os.environ.get(env, str(dflt)))
        except ValueError:
            dur = dflt
        time.sleep(max(dur, 0.0))
        return
    # write / journal / rank_death: simulated SIGKILL — flush the
    # injection notice, then exit without running any cleanup
    sys.stderr.flush()
    os._exit(EXIT_CODE)
