"""Crash-safe file leases: one audited primitive, many queues.

This is the lease state machine factored out of the fleet plane
(pipeline/fleet.py, PR 13) so that shard RANGES and serve JOBS are two
instantiations of the same machinery rather than two implementations
of it.  A *lease domain* is a directory; a lease is a file
``<d>/lease.<key>`` whose lifecycle is:

* **acquire** — ``O_CREAT|O_EXCL``: of any number of racers the kernel
  admits exactly one, with no read-check-write window.  The winner's
  owner record (worker, pid, heartbeat, caller extras) is fsynced into
  the fresh file; a SIGKILL between create and write leaves a TORN
  lease (unreadable record), which ages by file mtime and expires like
  any stale one.
* **renew** — a fully-fsynced atomic replace (utils/journal.py
  ``write_json_atomic``) bumping the ``renewed`` heartbeat.  Returns
  False — and the caller must STOP working — when the lease is gone or
  owned by someone else.  The read-then-replace window is closed by
  the kill-before-steal invariant, not by renew itself.
* **expire/steal** — eviction is scheduler-only and KILL-BEFORE-STEAL:
  a live same-host holder is SIGKILLed before its lease is atomically
  renamed into the ``expired/`` graveyard, so no two writers ever
  touch one key's artifacts.  Losing the rename race means someone
  else already freed it — not an error.
* **retire** — the lease protocol guards WORK IN PROGRESS; completed
  work is fenced separately by an EXCLUSIVE done marker
  (utils/journal.py ``write_json_exclusive``, an ``os.link`` publish):
  even a zombie that survived expiry cannot double-commit a key.

Keys are strings.  The fleet plane uses ``str(range_index)`` so its
on-disk layout (``lease.<i>``, graveyard names) is byte-identical to
the pre-extraction code; the serve fleet (pipeline/serve.py, PR 16)
uses job ids (``lease.j00012``) and replica slots (``lease.r0``).
This module is deliberately dependency-light (stdlib + the journal
write idioms) so discovery-side tools (gateway, top) can scan a lease
domain without importing the compute stack.
"""

from __future__ import annotations

import json
import os
import signal
import time
from typing import Iterable, List, Optional, Tuple

from ccsx_tpu.utils.journal import write_json_atomic

GRAVEYARD = "expired"


def lease_path(d: str, key: str) -> str:
    return os.path.join(d, f"lease.{key}")


def read_lease(d: str, key: str) -> Optional[dict]:
    """The lease's owner record, {} for a torn lease (crash between
    O_EXCL create and the owner write), None when free."""
    try:
        with open(lease_path(d, key)) as f:
            return json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, ValueError):
        return {}


def try_acquire(d: str, key: str, worker: str,
                extra: Optional[dict] = None,
                kind: Optional[str] = None) -> Optional[dict]:
    """Acquire lease ``key``, or None if it is held.  ``O_CREAT|O_EXCL``
    is the arbitration: of any number of racers the kernel admits
    exactly one.  ``extra`` fields ride in the owner record (the fleet
    plane stores the range index + correlation id; the serve fleet
    stores replica name, host and telemetry port).  ``kind`` labels the
    acquire-latency histogram family (job/range/slot); None skips the
    observation (discovery-side callers)."""
    t0 = time.monotonic()
    try:
        fd = os.open(lease_path(d, key),
                     os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
    except FileExistsError:
        return None
    now = time.time()
    rec = {"key": key, "worker": worker, "pid": os.getpid(),
           "acquired": now, "renewed": now}
    if extra:
        rec.update(extra)
    try:
        os.write(fd, json.dumps(rec).encode())
        os.fsync(fd)
    finally:
        os.close(fd)
    if kind:
        # lease-acquire latency (create + owner-record fsync): reported
        # through the installed tracer's Metrics so this module stays
        # dependency-light — no-op when no tracer/metrics is installed
        from ccsx_tpu.utils import trace

        tr = trace.current()
        if tr is not None and tr.metrics is not None:
            tr.metrics.observe("lease_acquire_s",
                               time.monotonic() - t0, kind)
    return rec


def renew(d: str, key: str, rec: dict,
          extra: Optional[dict] = None) -> bool:
    """Re-assert ownership by bumping the heartbeat (optionally
    refreshing ``extra`` fields, e.g. a replica's load gauge).  Returns
    False — and the caller must STOP renewing — when the lease is gone
    or owned by someone else (the scheduler expired us).  The
    read-then-replace window is closed by the kill-before-steal
    invariant, not by this function: the scheduler SIGKILLs a local
    holder before renaming its lease away, so a holder that can still
    run this code has not been stolen from."""
    cur = read_lease(d, key)
    if (not cur or cur.get("worker") != rec["worker"]
            or cur.get("pid") != rec["pid"]):
        return False
    upd = dict(rec, renewed=time.time())
    if extra:
        upd.update(extra)
    try:
        write_json_atomic(lease_path(d, key), upd)
    except OSError:
        return False
    return True


def release(d: str, key: str, rec: dict) -> None:
    """Free the lease (after the done marker is durable, or on drain).
    Losing a steal race (FileNotFoundError) is fine — released is
    released."""
    cur = read_lease(d, key)
    if (cur and cur.get("worker") == rec["worker"]
            and cur.get("pid") == rec["pid"]):
        try:
            os.unlink(lease_path(d, key))
        except OSError:
            pass


def steal_lease(d: str, key: str, cur: dict, kill: bool = True,
                seq: int = 0) -> Optional[dict]:
    """Scheduler-side eviction.  KILL-BEFORE-STEAL: the local holder is
    SIGKILLed before its lease is renamed away, so no two writers ever
    touch one key's artifacts (a survivor that could still renew past
    our read would otherwise clobber the next owner).  The rename into
    the graveyard is atomic; losing the rename race means someone else
    already freed it — not an error."""
    pid = cur.get("pid")
    if kill and pid and int(pid) != os.getpid():
        try:
            os.kill(int(pid), signal.SIGKILL)
        except (OSError, ValueError):
            pass   # already gone (or never ours to kill)
    grave = os.path.join(d, GRAVEYARD)
    os.makedirs(grave, exist_ok=True)
    dst = os.path.join(grave, f"lease.{key}.{os.getpid()}.{seq}")
    k = 0
    while os.path.exists(dst):
        k += 1
        dst = os.path.join(grave, f"lease.{key}.{os.getpid()}.{seq}~{k}")
    try:
        os.replace(lease_path(d, key), dst)
    except OSError:
        return None
    # forensics link: if the evicted holder left a black-box ring
    # (CCSX_BLACKBOX), stamp its path into the graveyard record so the
    # post-mortem (`ccsx-tpu blackbox`) is one hop from the eviction.
    # Best effort — a torn lease has no pid and links nothing.
    pid = cur.get("pid") if cur else None
    if pid:
        from ccsx_tpu.utils import blackbox

        for bb_dir in (os.environ.get(blackbox.ENV_DIR), d):
            if not bb_dir:
                continue
            bb_path = blackbox.box_path(bb_dir, int(pid))
            if os.path.exists(bb_path):
                try:
                    write_json_atomic(dst,
                                      dict(cur, blackbox=bb_path))
                except OSError:
                    pass
                break
    return cur


def expire_lease(d: str, key: str, timeout_s: float, kill: bool = True,
                 seq: int = 0) -> Optional[dict]:
    """Expire lease ``key`` if its heartbeat is older than
    ``timeout_s``.  Torn leases (no readable owner record) age by file
    mtime — a crash between acquire and owner-write must not pin the
    key forever.  Returns the evicted owner record, or None when
    live/free."""
    try:
        st = os.stat(lease_path(d, key))
    except OSError:
        return None
    cur = read_lease(d, key)
    if cur is None:
        return None
    beat = None
    if cur:
        try:
            beat = float(cur["renewed"])
        except (KeyError, TypeError, ValueError):
            beat = None
    if beat is None:
        beat = st.st_mtime
    if time.time() - beat < timeout_s:
        return None
    return steal_lease(d, key, cur, kill=kill, seq=seq)


def reclaim_pid_leases(d: str, keys: Iterable[str],
                       pid: int) -> List[str]:
    """Fast rebalance: a worker the scheduler KNOWS is dead (its child
    was just reaped) frees every lease it held immediately — no
    timeout wait, no kill needed.  This is what keeps a mid-run
    SIGKILL's cost at ~one unit of recompute instead of a full
    lease-timeout stall."""
    freed = []
    for seq, key in enumerate(keys):
        cur = read_lease(d, key)
        if cur and cur.get("pid") == pid:
            if steal_lease(d, key, cur, kill=False, seq=seq) is not None:
                freed.append(key)
    return freed


def list_leases(d: str, prefix: str = "") -> List[Tuple[str, dict]]:
    """Scan a lease domain: every live lease whose key starts with
    ``prefix``, as ``(key, owner_record)`` pairs ({} for torn).  This is
    the discovery primitive — the gateway and ``top`` find serve
    replicas by scanning slot leases (``r<k>``) without guessing ports;
    write_json_atomic staging files (``*.tmp``) are skipped."""
    out = []
    try:
        names = os.listdir(d)
    except OSError:
        return out
    for name in sorted(names):
        if not name.startswith("lease."):
            continue
        key = name[len("lease."):]
        if not key.startswith(prefix) or ".tmp" in key:
            continue
        rec = read_lease(d, key)
        if rec is not None:
            out.append((key, rec))
    return out
