"""Crash-persistent black-box recorder (utils/blackbox.py) and the
cross-process correlation id (ISSUE 18): ring write/recover WITHOUT a
clean close, restart-resume and lap/seam behavior, in-flight pairing,
env gating of the process singleton, the tracer mirror that stamps
cids, the gateway's cid mint, and — slow tier — a real SIGKILL whose
dump still names the in-flight work.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from ccsx_tpu import cli
from ccsx_tpu.pipeline import gateway
from ccsx_tpu.utils import blackbox, synth, trace


@pytest.fixture(autouse=True)
def _isolated_singleton(monkeypatch):
    """Each test starts with the plane OFF and a fresh singleton (get()
    caches per pid; a leaked instance would write into another test's
    tmp dir)."""
    monkeypatch.delenv(blackbox.ENV_DIR, raising=False)
    blackbox.reset()
    yield
    blackbox.reset()


# ---- ring format -----------------------------------------------------------


def test_ring_recovers_without_close(tmp_path):
    """The crash-survival claim, minus the kill: records are readable
    from the FILE with no close()/msync — file-backed mmap pages belong
    to the kernel the moment they are written."""
    p = str(tmp_path / "bb.bin")
    box = blackbox.BlackBox(p, capacity=4096)
    for i in range(5):
        box.record({"i": i})
    events = blackbox.read_dump(p)          # no close, no flush
    assert [e["i"] for e in events] == list(range(5))
    box.close()


def test_restart_resumes_and_lap_drops_torn_oldest(tmp_path):
    """A restarted pid resumes its old ring (head read back from the
    header), and once the ring laps, the reader returns a contiguous
    TAIL of the stream — the lap-seam record is torn and dropped, never
    returned as garbage."""
    p = str(tmp_path / "bb.bin")
    box = blackbox.BlackBox(p, capacity=4096)
    box.record({"n": 0})
    box.close()
    box = blackbox.BlackBox(p, capacity=4096)
    assert box.head > 0                     # resumed, not zeroed
    pad = "x" * 80
    for n in range(1, 200):                 # ~100 B/record: laps 4 KiB
        box.record({"n": n, "pad": pad})
    box.close()
    ns = [e["n"] for e in blackbox.read_dump(p)]
    assert ns and ns == list(range(ns[0], 200))
    assert 0 < ns[0] < 199                  # oldest lapped away, tail kept


def test_read_dump_exactly_full_ring(tmp_path):
    """head == capacity is the unwrapped boundary, not a lap: the ring
    is exactly full of whole records and the reader must return them
    all (a wrap-based slice at head % capacity == 0 returns nothing)."""
    p = str(tmp_path / "bb.bin")
    box = blackbox.BlackBox(p, capacity=4096)
    pad = "x" * (4096 - 11)        # {"pad":"..."}\n == capacity bytes
    box.record({"pad": pad})
    assert box.head == box.capacity
    events = blackbox.read_dump(p)
    assert len(events) == 1 and events[0]["pad"] == pad
    box.close()


def test_reader_rejects_foreign_and_capacity_change_resets(tmp_path):
    bad = tmp_path / "junk.bin"
    bad.write_bytes(b"not a ring")
    with pytest.raises(ValueError):
        blackbox.read_dump(str(bad))
    # a capacity change (CCSX_BLACKBOX_CAP bumped across a restart)
    # starts the ring over instead of misreading old offsets
    p = str(tmp_path / "bb.bin")
    box = blackbox.BlackBox(p, capacity=4096)
    box.record({"n": 1})
    box.close()
    box = blackbox.BlackBox(p, capacity=8192)
    assert box.head == 0
    box.close()


def test_inflight_pairing():
    """inflight() names exactly the UNFINISHED work: claim notes
    without a 'done', and span-begin mirrors without their close."""
    events = [
        {"bb": "inflight", "what": "job", "id": "j1"},
        {"bb": "inflight", "what": "range", "id": 3},
        {"bb": "done", "what": "job", "id": "j1"},
        {"ev": "begin", "tid": "T", "name": "refine"},
        {"ev": "begin", "tid": "T", "name": "poa"},
        {"ev": "span", "tid": "T", "name": "poa"},
    ]
    live = blackbox.inflight(events)
    notes = {(e.get("what"), e.get("id")) for e in live if e.get("bb")}
    assert notes == {("range", 3)}
    assert [e["name"] for e in live if e.get("ev") == "begin"] == ["refine"]


# ---- process singleton + env gating ----------------------------------------


def test_env_gates_singleton(tmp_path, monkeypatch):
    assert blackbox.get() is None           # plane off: no files, no cost
    blackbox.note("inflight", what="job", id="j9")     # no-op
    assert not list(tmp_path.iterdir())
    monkeypatch.setenv(blackbox.ENV_DIR, str(tmp_path))
    bb = blackbox.get()
    assert bb is not None and blackbox.get() is bb     # cached per pid
    blackbox.note("inflight", what="job", id="j9")
    blackbox.reset()
    events = blackbox.read_dump(blackbox.box_path(str(tmp_path)))
    last = events[-1]
    assert (last["bb"], last["what"], last["id"]) == ("inflight", "job", "j9")
    assert last["pid"] == os.getpid() and last["ts"] > 0


def test_unwritable_dir_disables_loudly(tmp_path, monkeypatch, capsys):
    """An unusable CCSX_BLACKBOX must degrade the recorder (off, one
    stderr line), never the run."""
    f = tmp_path / "not_a_dir"
    f.write_text("x")
    monkeypatch.setenv(blackbox.ENV_DIR, str(f))
    assert blackbox.get() is None
    assert blackbox.ENV_DIR not in os.environ   # disabled for good
    assert "blackbox disabled" in capsys.readouterr().err


# ---- correlation id --------------------------------------------------------


def test_cid_scope_stamps_trace_records_and_ring_mirror(tmp_path,
                                                        monkeypatch):
    """Every trace record written inside a cid_scope carries the cid —
    in the JSONL file AND in the black-box mirror — and records outside
    the scope stay unstamped (correlation is per job, not per
    process-lifetime)."""
    monkeypatch.setenv(blackbox.ENV_DIR, str(tmp_path))
    tp = str(tmp_path / "t.jsonl")
    tr = trace.Tracer(tp)
    assert trace.current_cid() is None
    with trace.cid_scope("cabc123def456"):
        with tr.span("stitch"):
            pass
        tr.instant("mark")
    with tr.span("outside"):
        pass
    tr.close()
    recs = [json.loads(ln) for ln in open(tp) if ln.strip()]
    by = {r["name"]: r for r in recs if "name" in r}
    assert by["stitch"]["cid"] == "cabc123def456"
    assert by["mark"]["cid"] == "cabc123def456"
    assert "cid" not in by["outside"]
    blackbox.reset()
    ring = blackbox.read_dump(blackbox.box_path(str(tmp_path)))
    assert any(r.get("ev") == "span" and r.get("name") == "stitch"
               and r.get("cid") == "cabc123def456" for r in ring)


def test_cid_scope_concurrent_jobs_do_not_cross(tmp_path):
    """Serve runs jobs CONCURRENTLY (--max-active defaults to 2): two
    overlapping scopes on different threads must each stamp their own
    records, and the unbalanced exit interleave (A enters, B enters,
    A exits, B exits) must not leave a finished job's cid on anything
    written afterwards."""
    import threading

    tp = str(tmp_path / "t.jsonl")
    tr = trace.Tracer(tp)
    a_in, b_in, a_out = (threading.Event() for _ in range(3))

    def job_a():
        with trace.cid_scope("cjob-a"):
            a_in.set()
            b_in.wait(5)               # B's scope is now open too
            with tr.span("work-a"):
                pass
        a_out.set()                    # A exited while B is still open

    def job_b():
        a_in.wait(5)
        with trace.cid_scope("cjob-b"):
            b_in.set()
            a_out.wait(5)
            with tr.span("work-b"):
                pass

    ta = threading.Thread(target=job_a)
    tb = threading.Thread(target=job_b)
    ta.start(); tb.start(); ta.join(5); tb.join(5)
    assert trace.current_cid() is None
    with tr.span("after"):
        pass
    tr.close()
    by = {r["name"]: r for r in
          (json.loads(ln) for ln in open(tp) if ln.strip())
          if "name" in r}
    assert by["work-a"]["cid"] == "cjob-a"
    assert by["work-b"]["cid"] == "cjob-b"      # B survived A's exit
    assert "cid" not in by["after"]             # nothing leaked


def test_cid_inherited_by_worker_threads(tmp_path):
    """A job's device work fans across pool threads spawned through
    faultinject.inherit() (prep pool, deadline runner): the copied
    context carries the cid, so worker-thread spans still name the
    job."""
    import threading

    from ccsx_tpu.utils import faultinject

    tp = str(tmp_path / "t.jsonl")
    tr = trace.Tracer(tp)

    def work():
        with tr.span("pool-work"):
            pass

    with trace.cid_scope("cfam42"):
        t = threading.Thread(target=faultinject.inherit(work))
        t.start()
        t.join(5)
    tr.close()
    recs = [json.loads(ln) for ln in open(tp) if ln.strip()]
    sp = next(r for r in recs if r.get("name") == "pool-work")
    assert sp["cid"] == "cfam42"


def test_gateway_mints_cid_into_spool_record(tmp_path):
    """submit_job mints the correlation id; the spool record carries it
    (that is how the replica lease, fan-out leases, and fleet state
    inherit it) and job_view exposes it to clients."""
    spool = str(tmp_path / "spool")
    jid = gateway.submit_job(spool, input_path="in.fa")
    rec = gateway.read_job_record(spool, jid)
    cid = rec["cid"]
    assert cid.startswith("c") and len(cid) == 13
    assert gateway.job_view(spool, jid)["cid"] == cid
    # distinct submissions get distinct ids
    jid2 = gateway.submit_job(spool, input_path="in2.fa")
    assert gateway.read_job_record(spool, jid2)["cid"] != cid


def test_output_bytes_identical_plane_on_off(tmp_path, rng, monkeypatch):
    """The plane is observability, not semantics: a real CLI run with
    the recorder armed emits byte-identical output to one without, and
    the ring actually recorded the run's spans."""
    zs = [synth.make_zmw(rng, template_len=700, n_passes=5, movie="mv",
                         hole=str(h)) for h in range(2)]
    fa = tmp_path / "in.fa"
    fa.write_text(synth.make_fasta(zs))
    out_off = str(tmp_path / "off.fa")
    out_on = str(tmp_path / "on.fa")
    assert cli.main(["-A", "-m", "1000", str(fa), out_off]) == 0
    bb_dir = tmp_path / "bb"
    monkeypatch.setenv(blackbox.ENV_DIR, str(bb_dir))
    assert cli.main(["-A", "-m", "1000", str(fa), out_on]) == 0
    blackbox.reset()
    assert open(out_on, "rb").read() == open(out_off, "rb").read()
    events = blackbox.read_dump(blackbox.box_path(str(bb_dir)))
    assert any(e.get("ev") == "span" for e in events)


# ---- the actual crash ------------------------------------------------------

_CHILD = r"""
import os, sys, time
from ccsx_tpu.utils import blackbox, trace

tr = trace.Tracer(None)              # file-less: the ring is the only sink
with trace.cid_scope("cdeadbeef0001"):
    blackbox.note("inflight", what="job", id="j7", cid="cdeadbeef0001")
    with tr.device_span("refine_packed", group="packed:q9"):
        print("READY", flush=True)
        time.sleep(60)
"""


@pytest.mark.slow  # ~2s: subprocess spawn + interpreter import cost; the
# in-process tier-1 siblings (test_ring_recovers_without_close,
# test_cid_scope_stamps_trace_records_and_ring_mirror) pin the same
# format/stamping guarantees without the kill
def test_sigkill_leaves_readable_dump_naming_inflight_work(tmp_path):
    """The acceptance crash: a replica SIGKILLed mid-dispatch (no
    atexit, no flush) leaves a dump that names the in-flight job AND
    the open device span, both stamped with the fleet cid."""
    env = dict(os.environ, CCSX_BLACKBOX=str(tmp_path),
               JAX_PLATFORMS="cpu")
    proc = subprocess.Popen([sys.executable, "-c", _CHILD], env=env,
                            stdout=subprocess.PIPE, text=True)
    try:
        assert proc.stdout.readline().strip() == "READY"
        time.sleep(0.1)              # let the begin mirror land
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
    assert proc.returncode == -signal.SIGKILL
    path = blackbox.box_path(str(tmp_path), proc.pid)
    events = blackbox.read_dump(path)
    live = blackbox.inflight(events)
    jobs = [e for e in live if e.get("what") == "job"]
    spans = [e for e in live if e.get("ev") == "begin"]
    assert jobs and jobs[0]["id"] == "j7"
    assert spans and spans[0]["name"] == "refine_packed"
    assert spans[0]["group"] == "packed:q9"
    assert {e.get("cid") for e in live} == {"cdeadbeef0001"}
    # and the operator-facing renderer headlines it
    import io

    buf = io.StringIO()
    assert blackbox.render(path, out=buf) == 0
    page = buf.getvalue()
    assert "in-flight at death" in page and "refine_packed" in page
