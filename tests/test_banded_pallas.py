"""Differential tests: Pallas banded kernel vs the scan implementation.

The lax.scan aligner (ops/banded.py) is the spec; the Pallas kernel
(ops/banded_pallas.py) must be bit-exact in global+moves mode: same scores,
same stats, same band offsets, and identical move bytes for every live row
(rows beyond qlen carry frozen garbage in both — not compared).

On CPU (the default test mesh) the kernel runs in interpret mode, so
shapes are kept small.  Run with CCSX_TEST_TPU=1 on a TPU host and the
kernel runs Mosaic-compiled (interpret=False) on the chip — last done
2026-07-29 on v5e, all green.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ccsx_tpu.config import AlignParams
from ccsx_tpu.ops import banded, banded_pallas, banded_rotband
from ccsx_tpu.utils import synth

# interpret only off-TPU: Mosaic-compile the kernel when the chip is real
INTERPRET = jax.default_backend() != "tpu"


def _random_case(rng, Qmax, Tmax, tmin=40, tspan=160):
    tl = int(rng.integers(tmin, tmin + tspan))
    tpl = rng.integers(0, 4, tl).astype(np.uint8)
    q = synth.mutate(rng, tpl, 0.03, 0.05, 0.05)[:Qmax]
    qs = np.full(Qmax, banded.PAD, np.uint8)
    qs[: len(q)] = q
    ts = np.full(Tmax, banded.PAD, np.uint8)
    ts[:tl] = tpl
    return qs, np.int32(len(q)), ts, np.int32(tl)


def _compare(qs, qlens, ts, tlens, params):
    scan_f = banded.make_batched("global", params, with_moves=True)
    r1, m1, o1 = scan_f(qs, qlens, ts, tlens)
    r2, m2, o2 = banded_pallas.batched_align_global_moves(
        qs, qlens, ts, tlens, params, interpret=INTERPRET)
    np.testing.assert_array_equal(np.asarray(r1.score), np.asarray(r2.score))
    np.testing.assert_array_equal(np.asarray(r1.mat), np.asarray(r2.mat))
    np.testing.assert_array_equal(np.asarray(r1.aln), np.asarray(r2.aln))
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    m1, m2 = np.asarray(m1), np.asarray(m2)
    for i in range(len(qlens)):
        ql = int(qlens[i])
        np.testing.assert_array_equal(
            m1[i, :ql], m2[i, :ql], err_msg=f"moves mismatch, problem {i}")


def test_bit_exact_random_batch():
    rng = np.random.default_rng(7)
    Qmax, Tmax, N = 256, 256, 5
    cases = [_random_case(rng, Qmax, Tmax) for _ in range(N)]
    qs = np.stack([c[0] for c in cases])
    qlens = np.array([c[1] for c in cases], np.int32)
    ts = np.stack([c[2] for c in cases])
    tlens = np.array([c[3] for c in cases], np.int32)
    _compare(qs, qlens, ts, tlens, AlignParams())


@pytest.mark.slow  # ~15s edge sweep; bit_exact_random_batch and
# gblock/qmax siblings keep the kernel's tier-1 pin (r13 audit)
def test_empty_and_extreme_rows():
    """Padding rows (qlen=0), very short queries, and full-length queries."""
    rng = np.random.default_rng(11)
    Qmax, Tmax = 128, 128
    tl = 100
    tpl = rng.integers(0, 4, tl).astype(np.uint8)
    ts_row = np.full(Tmax, banded.PAD, np.uint8)
    ts_row[:tl] = tpl
    qs = np.full((3, Qmax), banded.PAD, np.uint8)
    qlens = np.zeros(3, np.int32)
    # row 0: empty (padding row); row 1: tiny query; row 2: qlen == Qmax
    qs[1, :5] = tpl[:5]
    qlens[1] = 5
    full = synth.mutate(rng, tpl, 0.02, 0.3, 0.02)
    full = np.concatenate([full, rng.integers(0, 4, Qmax).astype(np.uint8)])
    qs[2] = full[:Qmax]
    qlens[2] = Qmax
    ts = np.broadcast_to(ts_row, (3, Tmax)).copy()
    tlens = np.full(3, tl, np.int32)
    _compare(qs, qlens, ts, tlens, AlignParams())


@pytest.mark.slow  # ~43s: interpret-mode kernel at an extra batch shape
def test_leading_batch_dims():
    """(Z, P, Qmax) nested batching reshapes correctly."""
    rng = np.random.default_rng(3)
    Qmax, Tmax = 128, 128
    cases = [_random_case(rng, Qmax, Tmax, tmin=40, tspan=60)
             for _ in range(4)]
    qs = np.stack([c[0] for c in cases]).reshape(2, 2, Qmax)
    qlens = np.array([c[1] for c in cases], np.int32).reshape(2, 2)
    ts = np.stack([c[2] for c in cases]).reshape(2, 2, Tmax)
    tlens = np.array([c[3] for c in cases], np.int32).reshape(2, 2)
    r, moves, offs = banded_pallas.batched_align_global_moves(
        qs, qlens, ts, tlens, AlignParams(), interpret=INTERPRET)
    assert r.score.shape == (2, 2)
    assert moves.shape == (2, 2, Qmax, 128)
    assert offs.shape == (2, 2, Qmax)
    flat = banded_pallas.batched_align_global_moves(
        qs.reshape(4, Qmax), qlens.reshape(4), ts.reshape(4, Tmax),
        tlens.reshape(4), AlignParams(), interpret=INTERPRET)
    np.testing.assert_array_equal(
        np.asarray(r.score).ravel(), np.asarray(flat[0].score))


@pytest.mark.slow  # ~7s: with_stats-knob A/B; test_bit_exact_random_batch
# keeps the kernel's bit-exactness tier-1 (r16 budget audit)
def test_with_stats_false_same_moves_and_score():
    """The slim kernel (with_stats=False — the consensus-round config,
    star._aligner) must emit bit-identical moves/offs/score; mat/aln are
    zeros by contract, as in ops/banded.py's with_stats=False."""
    rng = np.random.default_rng(19)
    Qmax, Tmax, N = 256, 256, 5
    cases = [_random_case(rng, Qmax, Tmax) for _ in range(N)]
    qs = np.stack([c[0] for c in cases])
    qlens = np.array([c[1] for c in cases], np.int32)
    ts = np.stack([c[2] for c in cases])
    tlens = np.array([c[3] for c in cases], np.int32)
    # compare the slim kernel against the scan spec's slim mode directly
    # (the full-mode kernel is pinned by the _compare tests above; not
    # re-run here to keep suite runtime down)
    r2, m2, o2 = banded_pallas.batched_align_global_moves(
        qs, qlens, ts, tlens, AlignParams(), interpret=INTERPRET,
        with_stats=False)
    assert not np.asarray(r2.mat).any() and not np.asarray(r2.aln).any()
    scan_f = banded.make_batched("global", AlignParams(), with_moves=True,
                                 with_stats=False)
    r3, m3, o3 = scan_f(qs, qlens, ts, tlens)
    np.testing.assert_array_equal(np.asarray(r3.score), np.asarray(r2.score))
    np.testing.assert_array_equal(np.asarray(o3), np.asarray(o2))
    m2, m3 = np.asarray(m2), np.asarray(m3)
    for i in range(N):
        ql = int(qlens[i])
        np.testing.assert_array_equal(
            m3[i, :ql], m2[i, :ql], err_msg=f"moves mismatch, problem {i}")


@pytest.mark.slow  # ~12s: gblock-knob A/B; test_rotband_slim_and_gblock
# keeps gblock coverage tier-1 (r16 budget audit)
def test_gblock_override_bit_exact():
    """A non-default problem block (gblock=16, the A/B sweep knob) must
    not change any output."""
    rng = np.random.default_rng(23)
    Qmax, Tmax, N = 128, 128, 18   # N % 16 != 0 to exercise padding
    cases = [_random_case(rng, Qmax, Tmax, tmin=40, tspan=60)
             for _ in range(N)]
    qs = np.stack([c[0] for c in cases])
    qlens = np.array([c[1] for c in cases], np.int32)
    ts = np.stack([c[2] for c in cases])
    tlens = np.array([c[3] for c in cases], np.int32)
    r1, m1, o1 = banded_pallas.batched_align_global_moves(
        qs, qlens, ts, tlens, AlignParams(), interpret=INTERPRET,
        with_stats=False)
    r2, m2, o2 = banded_pallas.batched_align_global_moves(
        qs, qlens, ts, tlens, AlignParams(), interpret=INTERPRET,
        with_stats=False, gblock=16)
    np.testing.assert_array_equal(np.asarray(r1.score), np.asarray(r2.score))
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    m1, m2 = np.asarray(m1), np.asarray(m2)
    for i in range(N):
        ql = int(qlens[i])
        np.testing.assert_array_equal(m1[i, :ql], m2[i, :ql])


def test_qmax_cap():
    with pytest.raises(ValueError):
        banded_pallas.batched_align_global_moves(
            np.zeros((1, banded_pallas.PALLAS_MAX_QMAX + 8), np.uint8),
            np.zeros(1, np.int32),
            np.zeros((1, 128), np.uint8),
            np.zeros(1, np.int32),
            AlignParams(), interpret=INTERPRET)


# ---- offset-schedule differentials (r14) -----------------------------------
# compute_offsets is shared by BOTH kernels and fed to the traceback, so a
# silent divergence from the scan's in-body recurrence mis-bands every
# kernel alignment at once.  The r14 bugfix replaced its raw int32
# interpolation product with the shared _line_interp (the raw product
# overflowed for large seeded lines); these tests pin the schedule against
# (1) a pure-Python big-int oracle at coordinates whose product crosses
# 2**31 and (2) the scan's own emitted offsets under seeded lines.


def _offsets_oracle(qlen, tlen, qmax, band, maxshift, line):
    """The scan's offset recurrence in pure Python (unbounded ints) —
    overflow-free by construction, floor division exact on negatives
    (Python // == the mathematical floor _line_interp implements)."""
    li0, lj0, li1, lj1 = line
    tcap = max(tlen - band + 1, 0)
    denom = max(li1 - li0, 1)
    off_prev, out = 0, []
    for i in range(1, qmax + 1):
        nom_j = lj0 + ((i - li0) * (lj1 - lj0)) // denom
        desired = nom_j - band // 2
        lo = max(0, tcap - (qlen - i) * maxshift)
        off = min(max(max(desired, lo), off_prev),
                  min(off_prev + maxshift, tcap))
        off = max(off, off_prev)
        if i > qlen:
            off = off_prev
        out.append(off)
        off_prev = off
    return out


def test_compute_offsets_matches_bigint_oracle_large_coords():
    """Seeded lines (and the default global line) at template coordinates
    where the interpolation product (i-li0)*(lj1-lj0) exceeds int32 —
    the exact regime where the pre-r14 raw product silently wrapped."""
    rng = np.random.default_rng(29)
    qmax, band, maxshift = 256, 128, 4
    for rep in range(6):
        qlen = int(rng.integers(64, qmax + 1))
        tlen = int(rng.integers(2**24, 2**25))
        if rep % 2 == 0:
            line = (0, 0, qlen, tlen)  # the default global line
            arg = None
        else:
            lj0 = int(rng.integers(0, 2**20))
            lj1 = int(rng.integers(lj0 + 2**24, tlen))
            line = (0, lj0, qlen, lj1)
            arg = np.array(line, np.int32)
        assert (qmax - line[0]) * (line[3] - line[1]) > 2**31
        got = np.asarray(banded_pallas.compute_offsets(
            jnp.int32(qlen), jnp.int32(tlen), qmax, band, maxshift,
            line=arg))
        want = _offsets_oracle(qlen, tlen, qmax, band, maxshift, line)
        np.testing.assert_array_equal(
            got, np.array(want, np.int32),
            err_msg=f"rep {rep}: qlen={qlen} tlen={tlen} line={line}")


def test_compute_offsets_matches_scan_schedule_seeded_lines():
    """compute_offsets == the offsets the scan itself emits, under random
    seeded lines — the kernels' schedule and the spec's must be the SAME
    array or the traceback walks a different band than the fill wrote."""
    rng = np.random.default_rng(31)
    Qmax, Tmax, N = 128, 2048, 6
    params = AlignParams()
    qs = np.full((N, Qmax), banded.PAD, np.uint8)
    ts = np.full((N, Tmax), banded.PAD, np.uint8)
    qlens = np.zeros(N, np.int32)
    tlens = np.zeros(N, np.int32)
    lines = np.zeros((N, 4), np.int32)
    for i in range(N):
        tl = int(rng.integers(600, Tmax))
        ql = int(rng.integers(40, Qmax + 1))
        tb = int(rng.integers(0, tl - 300))
        te = int(rng.integers(tb + 200, tl + 1))
        ts[i, :tl] = rng.integers(0, 4, tl)
        qs[i, :ql] = rng.integers(0, 4, ql)
        qlens[i], tlens[i] = ql, tl
        lines[i] = (0, tb, ql, te)
    scan_f = banded.make_batched("global", params, with_moves=True,
                                 with_line=True)
    _, _, offs_scan = scan_f(qs, qlens, ts, tlens, lines)
    offs_cmp = jax.vmap(
        lambda ql, tl, ln: banded_pallas.compute_offsets(
            ql, tl, Qmax, params.band, 4, line=ln)
    )(jnp.asarray(qlens), jnp.asarray(tlens), jnp.asarray(lines))
    np.testing.assert_array_equal(np.asarray(offs_scan),
                                  np.asarray(offs_cmp))


# ---- rotband v2 differentials (r14) ----------------------------------------


def _compare3(qs, qlens, ts, tlens, params, with_stats=True):
    """All three impls on the same batch: the scan is the oracle, both
    kernels must match it bit-for-bit (scores, stats, offsets, and every
    live move row)."""
    scan_f = banded.make_batched("global", params, with_moves=True,
                                 with_stats=with_stats)
    r0, m0, o0 = scan_f(qs, qlens, ts, tlens)
    m0 = np.asarray(m0)
    for name, mod in (("pallas", banded_pallas), ("rotband", banded_rotband)):
        r, m, o = mod.batched_align_global_moves(
            qs, qlens, ts, tlens, params, interpret=INTERPRET,
            with_stats=with_stats)
        np.testing.assert_array_equal(
            np.asarray(r0.score), np.asarray(r.score),
            err_msg=f"{name}: score")
        if with_stats:
            np.testing.assert_array_equal(
                np.asarray(r0.mat), np.asarray(r.mat),
                err_msg=f"{name}: mat")
            np.testing.assert_array_equal(
                np.asarray(r0.aln), np.asarray(r.aln),
                err_msg=f"{name}: aln")
        np.testing.assert_array_equal(
            np.asarray(o0), np.asarray(o), err_msg=f"{name}: offs")
        m = np.asarray(m)
        for i in range(len(qlens)):
            ql = int(qlens[i])
            np.testing.assert_array_equal(
                m0[i, :ql], m[i, :ql],
                err_msg=f"{name}: moves mismatch, problem {i}")


@pytest.mark.slow  # ~27s: three interpret-mode arms; the tier-1 pins
# are rotband_slim_and_gblock (rotband vs scan) + bit_exact_random_batch
# (v1 vs scan), and the 256-wide edge sweep covers all three in slow
def test_rotband_three_way_bit_exact():
    """The tier-1 slice of the three-way fuzz: scan vs Pallas v1 vs
    rotband v2 on a small random batch, full-stats mode (the slim mode
    rides test_rotband_slim_and_gblock; the heavy shape/edge sweep is
    the slow sibling below)."""
    rng = np.random.default_rng(37)
    Qmax, Tmax, N = 128, 128, 4
    cases = [_random_case(rng, Qmax, Tmax, tmin=40, tspan=60)
             for _ in range(N)]
    qs = np.stack([c[0] for c in cases])
    qlens = np.array([c[1] for c in cases], np.int32)
    ts = np.stack([c[2] for c in cases])
    tlens = np.array([c[3] for c in cases], np.int32)
    _compare3(qs, qlens, ts, tlens, AlignParams())


def test_rotband_slim_and_gblock():
    """rotband in the consensus-round config (with_stats=False — the
    arm star._aligner actually dispatches) must match the scan's slim
    mode, and a non-default gblock must not change a byte of it."""
    rng = np.random.default_rng(41)
    Qmax, Tmax, N = 128, 128, 10   # N % 8 != 0 to exercise G padding
    cases = [_random_case(rng, Qmax, Tmax, tmin=40, tspan=60)
             for _ in range(N)]
    qs = np.stack([c[0] for c in cases])
    qlens = np.array([c[1] for c in cases], np.int32)
    ts = np.stack([c[2] for c in cases])
    tlens = np.array([c[3] for c in cases], np.int32)
    scan_f = banded.make_batched("global", AlignParams(), with_moves=True,
                                 with_stats=False)
    r0, m0, o0 = scan_f(qs, qlens, ts, tlens)
    r1, m1, o1 = banded_rotband.batched_align_global_moves(
        qs, qlens, ts, tlens, AlignParams(), interpret=INTERPRET,
        with_stats=False)
    assert not np.asarray(r1.mat).any() and not np.asarray(r1.aln).any()
    np.testing.assert_array_equal(np.asarray(r0.score), np.asarray(r1.score))
    np.testing.assert_array_equal(np.asarray(o0), np.asarray(o1))
    r2, m2, o2 = banded_rotband.batched_align_global_moves(
        qs, qlens, ts, tlens, AlignParams(), interpret=INTERPRET,
        with_stats=False, gblock=16)
    np.testing.assert_array_equal(np.asarray(r1.score), np.asarray(r2.score))
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    m0, m1, m2 = np.asarray(m0), np.asarray(m1), np.asarray(m2)
    for i in range(N):
        ql = int(qlens[i])
        np.testing.assert_array_equal(
            m0[i, :ql], m1[i, :ql], err_msg=f"slim moves, problem {i}")
        np.testing.assert_array_equal(
            m1[i, :ql], m2[i, :ql], err_msg=f"gblock moves, problem {i}")


def test_rotband_guards():
    """rotband's residue arithmetic needs a power-of-two band (the & mask
    IS the layout); the qmax cap matches v1's."""
    with pytest.raises(ValueError):
        banded_rotband.batched_align_global_moves(
            np.zeros((1, 128), np.uint8), np.zeros(1, np.int32),
            np.zeros((1, 128), np.uint8), np.zeros(1, np.int32),
            AlignParams(), band=96, interpret=INTERPRET)
    with pytest.raises(ValueError):
        banded_rotband.batched_align_global_moves(
            np.zeros((1, banded_pallas.PALLAS_MAX_QMAX + 8), np.uint8),
            np.zeros(1, np.int32),
            np.zeros((1, 128), np.uint8), np.zeros(1, np.int32),
            AlignParams(), interpret=INTERPRET)


@pytest.mark.slow  # ~1-2 min: interpret-mode kernels at an extra shape x
# stats sweep; the fast slices above keep the tier-1 pin (r14 audit)
def test_rotband_three_way_edge_sweep():
    """The full three-way adversarial sweep: 256-wide shapes, padding
    rows (qlen=0), tiny queries, qlen == Qmax, both stats modes."""
    rng = np.random.default_rng(43)
    Qmax, Tmax = 256, 256
    tl = 200
    tpl = rng.integers(0, 4, tl).astype(np.uint8)
    ts_row = np.full(Tmax, banded.PAD, np.uint8)
    ts_row[:tl] = tpl
    qs = np.full((4, Qmax), banded.PAD, np.uint8)
    qlens = np.zeros(4, np.int32)
    # row 0: empty (padding row); row 1: tiny; row 2: qlen == Qmax;
    # row 3: ordinary mutated read
    qs[1, :5] = tpl[:5]
    qlens[1] = 5
    full = synth.mutate(rng, tpl, 0.02, 0.3, 0.02)
    full = np.concatenate([full, rng.integers(0, 4, Qmax).astype(np.uint8)])
    qs[2] = full[:Qmax]
    qlens[2] = Qmax
    mid = synth.mutate(rng, tpl, 0.03, 0.05, 0.05)[:Qmax]
    qs[3, :len(mid)] = mid
    qlens[3] = len(mid)
    ts = np.broadcast_to(ts_row, (4, Tmax)).copy()
    tlens = np.full(4, tl, np.int32)
    _compare3(qs, qlens, ts, tlens, AlignParams(), with_stats=True)
    _compare3(qs, qlens, ts, tlens, AlignParams(), with_stats=False)


@pytest.mark.slow  # ~minutes: three full 64-hole scale-config CLI runs
def test_scale64_bytes_invariant_across_impls(tmp_path, monkeypatch):
    """The acceptance pin: the 64-hole scale config produces the SAME
    output bytes (the committed md5) under all three CCSX_BANDED_IMPL
    values — the impl knob is non-semantic (utils/fingerprint.py
    _NON_SEMANTIC) and this is the test that earns it."""
    import hashlib
    import sys as _sys

    _sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "benchmarks"))
    import fleet as fleet_bench

    in_bam = fleet_bench.make_scale64_corpus(str(tmp_path))
    for impl in ("scan", "pallas", "rotband"):
        monkeypatch.setenv("CCSX_BANDED_IMPL", impl)
        sub = tmp_path / impl
        sub.mkdir()
        ref = fleet_bench.run_scale64_reference(in_bam, str(sub))
        assert hashlib.md5(ref).hexdigest() == fleet_bench.SCALE64_MD5, (
            f"impl={impl}: scale64 bytes drifted "
            f"({len(ref)} bytes vs pinned {fleet_bench.SCALE64_BYTES})")
