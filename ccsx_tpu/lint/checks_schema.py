"""schema-drift: the static half of the telemetry schema guard.

The runtime guard (tests/test_telemetry.py) builds a populated
snapshot and cross-checks the consumer tuples against it.  This
checker computes the SAME contract from the AST alone — no imports,
no Metrics instance — so it holds even for code paths the populated
snapshot doesn't reach, and it runs anywhere the linter does.

Two directions, both from parsed source:

1. every counter key a consumer tuple names —
   ``PROM_COUNTERS``/``PROM_GAUGES``/``TOP_SUM_KEYS``/
   ``HEALTH_DETAIL_KEYS``/``JOB_PROM_COUNTERS``/``JOB_PROM_GAUGES``
   (utils/telemetry.py), ``OCCUPANCY_KEYS``/``RESILIENCE_KEYS``
   (utils/trace.py), ``REPORT_TILE_KEYS``/``REPORT_HEADER_KEYS``
   (utils/report.py) — must exist in ``Metrics.snapshot()``'s key set
   (the dict literal plus every ``snap["..."] = ...`` assignment), or
   stats/top/report render a permanently-empty column;

2. every snapshot key must reach ``/metrics`` —
   ``PROM_COUNTERS | PROM_GAUGES | PROM_STRUCTURED`` — or a new
   counter ships invisible to every dashboard.

(The ``FLEET_*`` gauges are sourced from the gateway's spool summary,
not from Metrics.snapshot(), so they are deliberately outside this
contract.)
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ccsx_tpu.lint.core import Finding

CHECK = "schema-drift"

# (file under the scan root, tuple names consumed from snapshot keys)
CONSUMER_TUPLES = (
    ("utils/telemetry.py", ("PROM_COUNTERS", "PROM_GAUGES",
                            "TOP_SUM_KEYS", "HEALTH_DETAIL_KEYS",
                            "JOB_PROM_COUNTERS", "JOB_PROM_GAUGES")),
    ("utils/trace.py", ("OCCUPANCY_KEYS", "RESILIENCE_KEYS")),
    ("utils/report.py", ("REPORT_TILE_KEYS", "REPORT_HEADER_KEYS")),
)
EXPORT_TUPLES = ("PROM_COUNTERS", "PROM_GAUGES", "PROM_STRUCTURED")


def _module_tuples(tree: ast.AST) -> Dict[str, Tuple[int, Set[str]]]:
    """name -> (lineno, string elements) for module-level tuple/list
    assignments of string constants."""
    out: Dict[str, Tuple[int, Set[str]]] = {}
    for node in getattr(tree, "body", []):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            continue
        value = node.value
        if not isinstance(value, (ast.Tuple, ast.List)):
            continue
        elems = set()
        ok = True
        for el in value.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                elems.add(el.value)
            else:
                ok = False  # mixed tuple (e.g. HIST_FAMILIES triples)
        if ok and elems:
            out[node.targets[0].id] = (node.lineno, elems)
    return out


def _snapshot_keys(tree: ast.AST) -> Tuple[Optional[int], Set[str]]:
    """Key set of ``class Metrics: def snapshot()``: dict-literal keys
    plus ``<name>["key"] = ...`` assignments in the method body."""
    for cls in ast.walk(tree):
        if not (isinstance(cls, ast.ClassDef) and cls.name == "Metrics"):
            continue
        for fn in cls.body:
            if not (isinstance(fn, ast.FunctionDef)
                    and fn.name == "snapshot"):
                continue
            keys: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Dict):
                    for k in node.keys:
                        if isinstance(k, ast.Constant) and isinstance(
                                k.value, str):
                            keys.add(k.value)
                if isinstance(node, ast.Assign):
                    for tgt in node.targets:
                        if (isinstance(tgt, ast.Subscript)
                                and isinstance(tgt.slice, ast.Constant)
                                and isinstance(tgt.slice.value, str)):
                            keys.add(tgt.slice.value)
            return fn.lineno, keys
    return None, set()


def _parse(path: Path) -> Optional[ast.AST]:
    try:
        return ast.parse(path.read_text(encoding="utf-8",
                                        errors="replace"))
    except (OSError, SyntaxError):
        return None


def check_tree(scan_root: Path, rel_prefix: str = "") -> Iterable[Finding]:
    mpath = scan_root / "utils" / "metrics.py"
    tpath = scan_root / "utils" / "telemetry.py"
    mtree = _parse(mpath)
    ttree = _parse(tpath)
    if mtree is None or ttree is None:
        return []  # not a tree that carries the telemetry contract
    snap_line, snap_keys = _snapshot_keys(mtree)
    if snap_line is None:
        return []
    out: List[Finding] = []
    telemetry_tuples = _module_tuples(ttree)

    for relfile, names in CONSUMER_TUPLES:
        path = scan_root / relfile
        tree = ttree if relfile.endswith("telemetry.py") else _parse(path)
        if tree is None:
            continue
        tuples = (telemetry_tuples
                  if relfile.endswith("telemetry.py")
                  else _module_tuples(tree))
        for name in names:
            if name not in tuples:
                continue
            lineno, keys = tuples[name]
            for key in sorted(keys - snap_keys):
                out.append(Finding(
                    CHECK, rel_prefix + relfile, lineno, 0,
                    f"{name} consumes {key!r} which Metrics.snapshot() "
                    f"never emits — the column renders permanently "
                    f"empty; add it to snapshot() or drop it here",
                    name))

    exported: Set[str] = set()
    for name in EXPORT_TUPLES:
        if name in telemetry_tuples:
            exported |= telemetry_tuples[name][1]
    if exported:
        for key in sorted(snap_keys - exported):
            out.append(Finding(
                CHECK, rel_prefix + "utils/metrics.py", snap_line, 0,
                f"snapshot() emits {key!r} but no PROM_COUNTERS/"
                f"PROM_GAUGES/PROM_STRUCTURED entry exports it — the "
                f"key is invisible to /metrics and every dashboard",
                "snapshot"))
    return out
