"""Multi-host distribution (SURVEY.md §5.8).

The reference is strictly single-host (no MPI/NCCL/sockets anywhere in the
repo; its "communication backend" is pthread mutex/condvar + atomics,
kthread.c:30-223).  The TPU framework scales across hosts the JAX way:

  * control plane — ``jax.distributed.initialize`` over DCN (one process
    per host); collectives inside jitted steps ride ICI within a slice via
    the mesh in parallel/mesh.py.
  * input sharding — every host reads the same input stream and owns the
    holes with ``global_index % num_processes == process_index``
    (round-robin over the *filtered* hole stream, so the assignment is a
    pure function of the input and needs no coordination).  ZMWs are
    independent, so the hot path has zero cross-host traffic.
  * output — each host writes ``<out>.shard<r>`` plus a sidecar index of
    the global hole ordinal per record; ``merge_shards`` restores the
    reference's input-ordered single FASTA exactly (kthread.c:202-213
    ordering invariant, across hosts).

The round-robin-over-one-stream design trades redundant parsing (every
host decodes the full input) for zero coordination; with the native C++
reader parsing is far faster than consensus, so it remains the default.
For BGZF BAM inputs with a hole index sidecar (``ccsx --make-index``,
io/bamindex.py), sharded runs switch to byte-range ingest: each host
inflates only its ~1/N of the compressed bytes and owns a contiguous
raw-hole range, with ordinal bookkeeping that keeps merge_shards'
output byte-identical (metrics.ingest_bytes records each mode's cost).
"""

from __future__ import annotations

import heapq
import json
import os
import sys
from typing import Iterator, Optional

from ccsx_tpu.config import CcsConfig
from ccsx_tpu.io import fastx
from ccsx_tpu.utils.journal import (Journal, write_json_atomic,
                                    write_json_exclusive)
from ccsx_tpu.utils.metrics import Metrics


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> tuple:
    """Initialize JAX's distributed runtime; returns (process_id, n).

    With no arguments, relies on the environment (TPU pod metadata or
    JAX_* env vars).  Safe to call once per process before any backend
    use.  Single-process callers should not call this at all.
    """
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    return jax.process_index(), jax.process_count()


def shard_stream(stream, rank: int, n: int) -> Iterator:
    """Round-robin hole ownership: yields this rank's holes (the local
    ordinal k maps to global ordinal rank + k*n)."""
    for i, z in enumerate(stream):
        if i % n == rank:
            yield z


def shard_path(out_path: str, rank: int) -> str:
    return f"{out_path}.shard{rank}"


def done_path(out_path: str, rank: int) -> str:
    """Per-shard completion marker: written atomically by a rank that
    drained its stream cleanly; its absence is how merge_shards knows a
    shard DIED rather than merely produced few records (a silently
    short merge would drop that rank's holes)."""
    return shard_path(out_path, rank) + ".done"


def _write_done_marker(out_path: str, rank: int, n: int,
                       holes_done: int, extra: Optional[dict] = None,
                       exclusive: bool = False) -> bool:
    # records counted from the closed (fsynced) ordinal sidecar, so a
    # resumed run's marker covers prior runs' records too
    records = 0
    try:
        with open(shard_path(out_path, rank) + ".idx") as fi:
            records = sum(1 for line in fi if not line.startswith("#"))
    except OSError:
        pass
    # fsynced like the journal (same shared idiom, write_json_atomic):
    # the marker VOUCHES for the shard bytes — merge_shards trusts its
    # existence — so it must never become durable while unfsynced shard
    # data could still be lost to a power cut; ShardWriter.close fsyncs
    # both shard files first.  ``extra``: the fleet plane's provenance
    # fields (range table hash, worker identity, [lo,hi)) ride in the
    # same marker so merge_shards can refuse stale-table markers.
    obj = {"rank": rank, "hosts": n, "records": records,
           "holes_done": holes_done}
    if extra:
        obj.update(extra)
    if exclusive:
        # fleet ranges commit through the exclusive fence: exactly one
        # of any number of racing retirers publishes the marker
        # (write_json_exclusive; the loser's False means someone else
        # already vouched for this range)
        return write_json_exclusive(done_path(out_path, rank), obj)
    write_json_atomic(done_path(out_path, rank), obj)
    return True


class ShardWriter:
    """FASTA shard + sidecar of global hole ordinals, for exact merge.

    Round-robin mode (``start_ordinal`` None): local hole ordinal k
    (what drive_batched passes to put_at) maps to global ordinal
    rank + k*n.  Range mode (byte-range sharded BAM ingest,
    io/bamindex.py): ordinal = start_ordinal + k — monotone across
    ranks because a contiguous range's filtered hole count never
    exceeds its raw width, so rank r's keys stay below rank r+1's
    start.  Either way merge_shards' ordinal heap restores the exact
    single-host output order.
    """

    def __init__(self, out_path: str, rank: int, n: int, append: bool,
                 start_ordinal: int | None = None,
                 mode_header: str | None = None):
        self.rank, self.n = rank, n
        self.start_ordinal = start_ordinal
        mode = "a" if append else "w"
        self.path = shard_path(out_path, rank)
        # UTF-8 pinned: bytes_out counts encoded bytes (non-ASCII read
        # names must not skew the journal's truncation offsets)
        self._f = open(self.path, mode, encoding="utf-8")
        self._idx = open(self.path + ".idx", mode, encoding="utf-8")
        # byte accounting for journal v2's torn-tail recovery; resumes
        # continue from the on-disk sizes the journal already verified
        self.bytes_out = os.path.getsize(self.path) if append else 0
        self.idx_bytes_out = (os.path.getsize(self.path + ".idx")
                              if append else 0)
        if not append:
            # the sharding mode is chosen per-rank from local state (a
            # BGZF index sidecar may be fresh on one host and stale on
            # another); a mixed-mode run would interleave overlapping
            # ordinal spaces into a silently corrupt merge, so each
            # shard declares its mode and merge_shards refuses a mix.
            # The fleet plane passes its own header ("#mode=lease/<table
            # hash>", pipeline/fleet.py) so leased-range outputs can
            # never be merged with static shards or a different split.
            hdr = mode_header if mode_header is not None else (
                "#mode=range\n" if start_ordinal is not None
                else "#mode=rr\n")
            self._idx.write(hdr)
            self.idx_bytes_out += len(hdr)

    def put_at(self, local_idx: int, name: str, seq: bytes,
               qual: bytes | None = None) -> None:
        rec, nbytes = fastx.format_record(name, seq, qual)
        self._f.write(rec)
        self.bytes_out += nbytes
        ordinal = (self.rank + local_idx * self.n
                   if self.start_ordinal is None
                   else self.start_ordinal + local_idx)
        line = f"{ordinal}\n"
        self._idx.write(line)
        self.idx_bytes_out += len(line)

    def put(self, name: str, seq: bytes,
            qual: bytes | None = None) -> None:  # pragma: no cover
        raise RuntimeError("ShardWriter requires put_at")

    def flush(self) -> None:
        # both streams, record before sidecar (a crash between the two
        # leaves an indexless record tail, which verify_output truncates)
        self._f.flush()
        self._idx.flush()

    def close(self) -> None:
        # fsync both shard files: the completion marker written after
        # close vouches for these bytes, so they must be durable first.
        # A REAL fsync failure (e.g. EIO: writeback lost dirty pages)
        # must propagate — rc becomes 1 and the marker is suppressed —
        # and only genuinely-unsupported fsync is ignored.
        import errno

        err = None
        for f in (self._f, self._idx):
            try:
                try:
                    f.flush()
                    os.fsync(f.fileno())
                except OSError as e:
                    if e.errno not in (errno.EINVAL, errno.ENOTSUP,
                                       getattr(errno, "EOPNOTSUPP", -1)):
                        err = err or e
                except ValueError:
                    pass  # already closed (double close): nothing to sync
            finally:
                # BOTH streams always get closed, and the FIRST error is
                # the one reported (an unguarded close() re-raising the
                # flush failure would skip the sidecar entirely)
                try:
                    f.close()
                except OSError as e:
                    err = err or e
        if err is not None:
            raise err


def run_pipeline_sharded(in_path: str, out_path: str, cfg: CcsConfig,
                         rank: int, n: int,
                         journal_path: Optional[str] = None,
                         inflight: Optional[int] = None) -> int:
    """One host's share of a distributed run.

    Writes <out>.shard<rank> (+ .idx).  After all ranks finish, any one
    process calls merge_shards(out_path, n) to produce the final FASTA.
    """
    from ccsx_tpu.pipeline.batch import drive_batched
    from ccsx_tpu.pipeline.run import open_zmw_stream
    from ccsx_tpu.utils.device import resolve_device

    if not (0 <= rank < n):
        raise ValueError(f"rank {rank} outside [0, {n})")
    import dataclasses

    if cfg.trace_path:
        # per-rank flight-recorder files: ranks on one filesystem would
        # otherwise clobber each other's span JSONL.  Metrics streams
        # append and every event carries a wall-clock ts, so THOSE merge
        # on a common timeline; the trace file is opened "w" per run.
        cfg = dataclasses.replace(
            cfg, trace_path=f"{cfg.trace_path}.shard{rank}")
    if cfg.telemetry_port:
        # per-rank telemetry ports (base + rank): every rank of a
        # same-host sharded run is scrapeable at a predictable address,
        # and `ccsx-tpu top host:P host:P+1 ...` aggregates them.  The
        # server still auto-bumps upward if something else holds the
        # offset port (drive_batched starts it).
        cfg = dataclasses.replace(
            cfg, telemetry_port=cfg.telemetry_port + rank)
    metrics = Metrics(verbose=cfg.verbose, stream=cfg.metrics_stream())
    # byte-range sharded ingest (SURVEY §5.8 "each host reads its own
    # input shard"): a fresh BGZF hole index (ccsx --make-index) lets
    # this rank inflate only its ~1/N of the compressed bytes and own a
    # contiguous raw-hole range; without one, fall back to the
    # zero-coordination full-parse round-robin.  Range mode streams
    # through the Python record parser (the native prefetch streamer
    # reads whole files); its 1/N byte share beats the native reader's
    # full-file speed for N >= ~2 hosts.
    range_lo = None
    idx = None
    if cfg.is_bam and in_path != "-" and os.path.exists(in_path):
        from ccsx_tpu.io import bamindex

        idx = bamindex.load_index(in_path)
    try:
        from ccsx_tpu.io import zmw as zmw_mod

        if idx is not None:
            range_lo, range_hi = bamindex.hole_range(
                idx["n_holes"], rank, n)
            # progress/ETA total in RAW holes: this rank owns exactly
            # its contiguous index range
            metrics.holes_total = range_hi - range_lo

            def _count(nbytes, m=metrics):
                m.ingest_bytes += nbytes

            stream = zmw_mod.stream_zmws(
                bamindex.read_hole_range(
                    in_path, idx, range_lo, range_hi, counter=_count,
                    max_record_bytes=getattr(cfg, "max_record_bytes",
                                             0)),
                cfg, metrics=metrics)
        else:
            stream = open_zmw_stream(in_path, cfg, metrics=metrics)
            if in_path != "-" and os.path.exists(in_path):
                # full-parse round-robin: every host ingests the file
                metrics.ingest_bytes = os.path.getsize(in_path)
    except (OSError, RuntimeError) as e:
        print(f"Error: Failed to open infile! ({e})", file=sys.stderr)
        return 1
    # validate the mesh BEFORE the shard writer truncates its file
    # (same single validation point as the single-host driver)
    resolve_device(cfg.device)
    from ccsx_tpu.pipeline.batch import mesh_precheck

    if mesh_precheck(cfg):
        return 1
    jp = f"{journal_path}.shard{rank}" if journal_path else None
    # the input_id pins the sharding MODE too: a journal written under
    # round-robin must not resume a range-sharded run (the ordinal
    # spaces differ)
    mode_id = (f"{in_path}#range{rank}/{n}" if range_lo is not None
               else f"{in_path}#{rank}/{n}")
    # load under this run's fingerprint + reconcile BOTH shard files
    # (record + ordinal sidecar) with the cursor before appending: a
    # crash can tear either tail
    sp = shard_path(out_path, rank)
    journal = Journal.for_run(jp, mode_id, cfg, sp, sp + ".idx")
    # retract the stale completion marker BEFORE the writer can truncate
    # the shard files: the reverse order leaves a crash window where a
    # durable marker vouches for an already-truncated shard and the
    # merge goes silently short
    try:
        os.unlink(done_path(out_path, rank))
    except OSError:
        pass
    try:
        writer = ShardWriter(out_path, rank, n,
                             append=bool(journal.holes_done),
                             start_ordinal=range_lo)
    except OSError:
        print("Cannot open file for write!", file=sys.stderr)
        return 1

    import contextlib

    import jax

    # Under a live jax.distributed control plane the default sharding
    # spans ALL processes' devices, which would turn every jit dispatch
    # into a cross-host SPMD program (and device_put would require
    # identical inputs on every host).  The hosts here are share-nothing
    # (round-robin hole ownership), so pin this host's dispatch to its
    # own devices; the per-host mesh already spans local chips only
    # (BatchExecutor.__init__).
    ctx = (jax.default_device(jax.local_devices()[0])
           if jax.process_count() > 1 else contextlib.nullcontext())
    with ctx:
        # range mode: the stream is already this rank's contiguous
        # share; round-robin: interleave-filter the shared full stream
        shard = (stream if range_lo is not None
                 else shard_stream(stream, rank, n))
        # None = adaptive admission window (explicit --inflight pins)
        rc = drive_batched(shard, writer, cfg, journal, metrics, inflight)
    if rc == 0:
        _write_done_marker(out_path, rank, n, journal.holes_done)
    return rc


def merge_shards(out_path: str, n: int, cleanup: bool = True,
                 allow_unmarked: bool = False,
                 expect_table: Optional[str] = None) -> int:
    """K-way merge of <out>.shard0..n-1 by global hole ordinal into
    out_path; returns the record count.  Restores exactly the single-host
    output order.

    Every rank must have written its completion marker (done_path): a
    rank that died mid-run leaves a plausible-looking partial shard, and
    merging it would produce a silently short output — refused instead,
    naming exactly which shard(s) died and how far each got.  That
    includes ALL ranks missing (a node-wide kill looks exactly like a
    pre-marker legacy shard set, and guessing "legacy" would silently
    drop holes); a caller who KNOWS the set is legacy-complete passes
    ``allow_unmarked=True``.

    Leased-range sets (fleet runs, pipeline/fleet.py) carry a range
    table hash both in the shard's idx mode header and in its done
    marker: the two must agree (a stale marker from a previous run with
    a different M must not vouch for these bytes), and when the caller
    knows the live table it passes ``expect_table`` to refuse any
    foreign split outright."""
    dead = []
    markers: dict = {}
    for r in range(n):
        if os.path.exists(done_path(out_path, r)):
            # the marker records the host count its run was sharded
            # over: merging a K-host set with --merge-shards N<K would
            # pass the existence check for shards 0..N-1 and silently
            # drop shards N..K-1's holes — refuse the mismatch instead
            try:
                with open(done_path(out_path, r)) as f:
                    markers[r] = json.load(f)
                hosts = markers[r].get("hosts")
            except (OSError, ValueError):
                hosts = None  # unreadable marker: can't vouch -> dead
            if hosts == n:
                continue
            if hosts is not None:
                raise ValueError(
                    f"shard{r}'s completion marker says the run used "
                    f"{hosts} hosts, but --merge-shards got {n}; "
                    f"merge with the run's host count ({hosts})")
        p = shard_path(out_path, r)
        if os.path.exists(p + ".idx"):
            with open(p + ".idx") as fi:
                recs = sum(1 for line in fi if not line.startswith("#"))
            dead.append(f"shard{r} (died after {recs} durable records)")
        elif os.path.exists(p):
            dead.append(f"shard{r} (no ordinal sidecar)")
        else:
            dead.append(f"shard{r} (never started: no shard file)")
    if dead and allow_unmarked and len(dead) == n:
        print(f"[ccsx-tpu] merge: no completion markers on any of {n} "
              "shards; merging anyway (allow_unmarked) — completion "
              "cannot be verified", file=sys.stderr)
    elif dead:
        hint = ("; if every rank is unmarked because the shards predate "
                "completion markers, merge with --merge-unmarked "
                "(allow_unmarked=True)" if len(dead) == n else "")
        raise ValueError(
            "refusing to merge incomplete shards — a merge now would "
            f"silently drop their holes: {'; '.join(dead)}; re-run the "
            "dead rank(s) (with --journal they resume from their shard "
            f"cursor), then merge again{hint}")

    def shard_mode(rank: int) -> str:
        with open(shard_path(out_path, rank) + ".idx") as fi:
            first = fi.readline()
        return first.strip() if first.startswith("#") else "#mode=rr"

    modes = {shard_mode(r) for r in range(n)}
    if len(modes) > 1:
        # one rank ran byte-range sharding while another round-robined
        # (e.g. the BGZF index sidecar was fresh on one host only), or
        # a static-shard output set got mixed with leased-range shards
        # from a fleet run: their ordinal spaces overlap, so a merge
        # would silently drop and duplicate holes — refuse instead
        detail = ("a static-shard run's outputs are mixed with a fleet "
                  "run's leased ranges; re-run one of them, don't merge "
                  "across schedulers"
                  if any(m.startswith("#mode=lease") for m in modes)
                  else "re-run all ranks with a consistent .ccsx_idx "
                       "sidecar (or none)")
        raise ValueError(
            f"shards disagree on sharding mode ({sorted(modes)}); "
            f"{detail}")
    mode = next(iter(modes)) if modes else "#mode=rr"
    if mode.startswith("#mode=lease/"):
        # leased-range set: every marker's recorded range table must
        # match the split the shard was actually written under (the idx
        # header) — a stale marker from a previous run with a different
        # M must not vouch for these bytes — and, when given, the live
        # table the scheduler expects
        table = mode[len("#mode=lease/"):]
        if expect_table is not None and table != expect_table:
            raise ValueError(
                f"leased shards were written under range table {table} "
                f"but this run's split is {expect_table}; stale outputs "
                "from a different -M split cannot be merged — re-run")
        for r in range(n):
            mt = markers.get(r, {}).get("table")
            if mt != table:
                raise ValueError(
                    f"shard{r}'s completion marker records range table "
                    f"{mt}, but the shard was written under {table}; a "
                    "stale marker from a different split cannot vouch "
                    "for these bytes — re-run the range")
    elif expect_table is not None:
        raise ValueError(
            f"expected a leased-range shard set (table {expect_table}) "
            f"but found mode {mode}; refusing to merge")

    def records(rank: int):
        p = shard_path(out_path, rank)
        with open(p) as f, open(p + ".idx") as fi:
            pos = fi.tell()
            if fi.readline()[:1] != "#":
                fi.seek(pos)   # legacy sidecar without a mode header
            while True:
                header = f.readline()
                if not header:
                    return
                # FASTA record = 2 lines, FASTQ = 4 (seq, '+', qual)
                lines = 1 if header[0] == ">" else 3
                rec = header + "".join(f.readline() for _ in range(lines))
                idx = int(fi.readline())
                yield idx, rec

    count = 0
    with open(out_path, "w") as out:
        for _, rec in heapq.merge(*[records(r) for r in range(n)]):
            out.write(rec)
            count += 1
    if cleanup:
        for r in range(n):
            p = shard_path(out_path, r)
            os.unlink(p)
            os.unlink(p + ".idx")
            try:
                os.unlink(done_path(out_path, r))
            except OSError:
                pass  # pre-marker shard sets (legacy) have none
    return count
