"""Overlapped prep plane: a bounded background host-prep pool.

Why (ISSUE 8 / VERDICT r5 Weak #5): the batched driver ALTERNATES
ingest/prep and device sweeps on one thread, so host prep time and chip
time add instead of overlap — 22% of wall at r5 scale
(benchmarks/e2e_scale_r05.json) and the named ceiling once dispatch is
compile-lean.  The reference hides prep entirely inside its 3-stage
read->compute->write pipeline (kthread.c:228-256); this module is that
overlap for the batched scheduler:

* ``PrepPool`` — N worker threads pull ZMWs off the (lock-serialized)
  input stream ahead of the admission window, run each hole's combined
  prep generator (encode + group_lens + the orientation/strand walk,
  consensus/prepare.py) to its FIRST consensus request, and publish the
  prepped hole on a thread-safe ready queue.  The driver's sweep loop
  keeps dispatching device work the whole time; it only blocks on the
  queue when it has nothing dispatchable (that wait is
  ``Metrics.t_prep_blocked`` — the critical-path prep exposure the
  ``prep_share <= 0.10`` bar reads).

* ``_PairGate`` — the walk's pair-alignment requests still batch across
  holes: a worker whose generator yields a PairRequest parks on the
  gate, and one pump thread collects the concurrently-parked requests
  into a single ``PairExecutor.run`` (the same batched device path as
  the inline driver's pair sweep, recovery ladder included).

Invariants preserved (pinned by tests/test_prep_overlap.py):

* Output bytes are IDENTICAL with the pool on or off: pair/refine
  results are batch-composition-invariant by the masked-padding design,
  per-hole prep is deterministic, and ordered emission + the journal's
  flush-before-cursor invariant live unchanged in the driver (the
  writer path does not change).
* A prep-thread exception quarantines exactly that hole (hole.err set,
  generator closed), never the run — the same contract as the inline
  ``_start_hole``.  An INGEST failure (corrupt stream) is re-raised on
  the driver thread so the drivers' existing clean-rc-1 handling fires.
* Backpressure: at most ``max_outstanding`` holes are ingested but not
  yet retired (the driver releases one permit per emitted hole).  The
  COUNT bound matches the inline loop's ``next_idx - next_emit <
  4 x inflight``, but the pool preps ahead, so up to that many holes
  can hold full prep state (generator + encoded passes) where inline
  held only ~window prepped holes plus instantly-done parked ones —
  bounded, but a deliberately higher steady-state RSS than inline;
  shrink ``--inflight``/``zmw_microbatch`` if it ever matters.

``--prep-threads 0`` disables the pool entirely (the inline A/B
control); the default (None) auto-sizes to the host.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import List, Optional

from ccsx_tpu.consensus import prepare as prep_mod
from ccsx_tpu.utils import faultinject
from ccsx_tpu.utils import trace


def resolve_prep_threads(cfg) -> int:
    """cfg.prep_threads -> worker count: explicit N pins (0 = inline),
    None auto-sizes — half the cores, capped small: prep is
    Python/NumPy host work that competes with the dispatch stream and
    the warmup compiler for cores, and a few workers already cover the
    admission burst."""
    pt = getattr(cfg, "prep_threads", None)
    if pt is None:
        return min(4, max(1, (os.cpu_count() or 2) // 2))
    return max(0, int(pt))


class _PairGate:
    """Batches pair alignments across concurrently-prepping holes.

    Workers call ``align(req)`` and block; the single pump thread
    drains every parked request into one ``PairExecutor.run`` (host
    seeding + batched banded fill + the shared recovery ladder) and
    delivers results.  A result that is an Exception (the executor's
    host replay failed for that pair) quarantines the CALLING hole —
    exactly what the inline driver's ``_feed_hole`` does."""

    # short accumulation window after the first request arrives: the
    # other walkers' requests of the same instant join the batch, while
    # a lone walker is delayed by ~nothing against the DP it waits for
    linger_s = 0.002

    def __init__(self, pair_executor, metrics):
        self._pe = pair_executor
        self._metrics = metrics
        self._cv = threading.Condition()
        self._pending: List[list] = []   # [req, Event, result]
        self._stop = False
        # faultinject.inherit: the pump must stay inside the spawning
        # job's fault scope (serve runs many jobs in one process)
        self._thread = threading.Thread(
            target=faultinject.inherit(self._pump), daemon=True,
            name="ccsx-prep-pairs")
        self._thread.start()

    def align(self, req):
        slot = [req, threading.Event(), None]
        with self._cv:
            if self._stop:
                return RuntimeError("prep pool closed")
            self._pending.append(slot)
            self._cv.notify()
        slot[1].wait()
        return slot[2]

    def _pump(self) -> None:
        while True:
            with self._cv:
                while not self._pending and not self._stop:
                    self._cv.wait()
                if not self._pending and self._stop:
                    return
            time.sleep(self.linger_s)
            with self._cv:
                batch, self._pending = self._pending, []
            try:
                with self._metrics.timer("prep"), \
                        trace.span("pair_sweep", cat="prep",
                                   n=len(batch)):
                    results = self._pe.run([s[0] for s in batch])
            except Exception as e:
                # PairExecutor.run owns the per-pair recovery ladder;
                # anything escaping it is delivered per caller so each
                # hole quarantines instead of the pump dying silently
                results = [e] * len(batch)
            for slot, r in zip(batch, results):
                slot[2] = r
                slot[1].set()

    def close(self) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._thread.join(timeout=10.0)
        with self._cv:
            stragglers, self._pending = self._pending, []
        for slot in stragglers:
            slot[2] = RuntimeError("prep pool closed")
            slot[1].set()


class PrepPool:
    """The background ingest+prep pool feeding the batched driver."""

    def __init__(self, stream, cfg, pair_executor, metrics,
                 threads: int, max_outstanding: int, resume: int = 0,
                 hole_factory=None, finish=None):
        # _Hole/_finish are injected by the driver (pipeline/batch.py)
        # to avoid a circular import; they are the SAME objects the
        # inline path uses, so a prepped hole is indistinguishable
        # downstream.
        from ccsx_tpu.pipeline import batch as batch_mod

        self._stream = stream
        self._cfg = cfg
        self._metrics = metrics
        self._resume = int(resume)
        self._hole = hole_factory or batch_mod._Hole
        self._finish = finish or batch_mod._finish
        self._gate = _PairGate(pair_executor, metrics)
        self._cv = threading.Condition()
        self._ready: List[object] = []
        self._budget = threading.Semaphore(max(1, int(max_outstanding)))
        self._ingest_lock = threading.Lock()
        self._next_idx = 0
        self._outstanding = 0        # ingested, not yet handed to driver
        self._exhausted = False      # stream EOF (or ingest error) seen
        self._ingest_error: Optional[BaseException] = None
        self._stop = False
        metrics.prep_threads = max(1, int(threads))
        # workers run inside the spawning job's fault scope (see pump)
        self._threads = [
            threading.Thread(target=faultinject.inherit(self._work),
                             daemon=True, name=f"ccsx-prep-{i}")
            for i in range(max(1, int(threads)))]
        for t in self._threads:
            t.start()

    # ---- worker side -----------------------------------------------------

    def _acquire_budget(self) -> bool:
        while not self._stop:
            if self._budget.acquire(timeout=0.2):
                if self._stop:
                    self._budget.release()
                    return False
                return True
        return False

    def _work(self) -> None:
        while True:
            if not self._acquire_budget():
                return
            h = self._ingest_one()
            if h is None:
                self._budget.release()
                return
            if not h.done:
                self._prep(h)
            self._publish(h)

    def _ingest_one(self):
        """One hole off the shared stream (serialized; stream iterators
        are not thread-safe), with the same ingest accounting, fault
        point, and resume-skip logic as the inline admission loop."""
        with self._ingest_lock:
            if self._stop or self._exhausted:
                return None
            m = self._metrics
            try:
                with m.timer("ingest"), \
                        trace.span("ingest_hole", cat="ingest"):
                    z = next(self._stream)
                    faultinject.fire("ingest")
            except StopIteration:
                self._set_exhausted()
                return None
            except Exception as e:
                # surfaced to the driver thread at the next poll/get so
                # the drivers' invalid-input rc-1 handling stays theirs
                self._ingest_error = e
                self._set_exhausted()
                return None
            m.holes_in += 1          # serialized by _ingest_lock
            h = self._hole(idx=self._next_idx, zmw=z)
            self._next_idx += 1
            if m.holes_in <= self._resume:
                h.done = h.resumed = True
            with self._cv:
                self._outstanding += 1
            return h

    def _set_exhausted(self) -> None:
        self._exhausted = True
        with self._cv:
            self._cv.notify_all()

    def _prep(self, h) -> None:
        """Run one hole's combined prep generator to its first
        consensus request — the off-thread twin of the inline
        ``_start_hole`` + pair-sweep loop.  Pair waits are excluded
        from t_prep (the pump books its own prep seconds) and recorded
        on the span for honesty."""
        from ccsx_tpu.consensus.hole import full_gen_for_zmw

        t0 = time.perf_counter()
        wait_s = 0.0
        try:
            with trace.span("prep_hole", cat="prep",
                            hole=str(h.zmw.hole)) as sp:
                faultinject.fire("compute")
                h.gen = full_gen_for_zmw(h.zmw, self._cfg)
                req = next(h.gen)
                while isinstance(req, (prep_mod.PairRequest,
                                       prep_mod.PairBatch)):
                    w0 = time.perf_counter()
                    res = self._gate.align(req)
                    wait_s += time.perf_counter() - w0
                    if isinstance(res, list):
                        # PairBatch result: its first embedded failure
                        # quarantines, like a scalar one below
                        exc = next((r for r in res
                                    if isinstance(r, Exception)), None)
                        if exc is not None:
                            res = exc
                    if isinstance(res, Exception):
                        # the executor's last-resort host replay failed
                        # for this pair: quarantine this hole (same as
                        # the inline _feed_hole contract)
                        raise res
                    req = h.gen.send(res)
                h.req = req
                if wait_s and sp is not None and hasattr(sp, "args"):
                    sp.args = dict(sp.args, pair_wait=round(wait_s, 6))
        except StopIteration as e:
            # skipped (<3 passes -> None) or consensus without device work
            h.done, h.cns = True, self._finish(e.value)
        except Exception as e:   # quarantine: one bad hole, not the run
            h.done, h.req, h.err = True, None, e
            if h.gen is not None:
                try:
                    h.gen.close()
                except Exception:
                    pass
        finally:
            self._metrics.add_stage(
                "prep", max(time.perf_counter() - t0 - wait_s, 0.0))

    def _publish(self, h) -> None:
        with self._cv:
            self._ready.append(h)
            d = len(self._ready)
            self._metrics.prep_queue_depth = d
            if d > self._metrics.prep_queue_peak:
                self._metrics.prep_queue_peak = d
            self._cv.notify_all()

    # ---- driver side -----------------------------------------------------

    def _raise_ingest_error(self) -> None:
        if self._ingest_error is not None:
            e, self._ingest_error = self._ingest_error, None
            raise e

    def _take_locked(self):
        h = self._ready.pop(0)
        self._outstanding -= 1   # the driver owns it from here
        self._metrics.prep_queue_depth = len(self._ready)
        return h

    def poll(self):
        """Next prepped hole without blocking, or None."""
        with self._cv:
            if self._ready:
                return self._take_locked()
        self._raise_ingest_error()
        return None

    def get(self, timeout: float = 1.0):
        """Next prepped hole, blocking up to ``timeout`` — the driver's
        nothing-dispatchable wait (timed by the caller into
        t_prep_blocked)."""
        with self._cv:
            self._cv.wait_for(
                lambda: self._ready or self.drained(), timeout=timeout)
            if self._ready:
                return self._take_locked()
        self._raise_ingest_error()
        return None

    def drained(self) -> bool:
        """True once no hole will ever be published again."""
        return (self._exhausted and self._outstanding == 0
                and not self._ready)

    def release(self, n: int = 1) -> None:
        """The driver retired (emitted) ``n`` holes: free that much
        ingest-ahead budget.  The budget spans ingest to EMISSION, so
        it is the pool-mode form of the inline loop's
        ``next_idx - next_emit < 4 x inflight`` memory bound."""
        for _ in range(n):
            self._budget.release()

    def close(self) -> None:
        """Stop workers + the pair pump.  Idempotent; driver-finally
        safe.  Queued-but-untaken holes are dropped (the run is ending
        — either complete, in which case none exist, or failing, in
        which case the driver's rc already says so)."""
        self._stop = True
        with self._cv:
            self._cv.notify_all()
        self._gate.close()
        for t in self._threads:
            t.join(timeout=10.0)
        alive = [t.name for t in self._threads if t.is_alive()]
        if alive:
            print(f"[ccsx-tpu] prep pool: threads still alive at close: "
                  f"{alive}", file=sys.stderr)
