"""Device mesh construction and the sharded consensus step.

The reference's only parallelism is host threads over independent ZMWs
(kt_for, kthread.c:34-65).  The TPU design shards two axes:

  data axis — ZMW batches (each hole independent: pure data parallelism,
      no cross-device traffic in the hot loop);
  pass axis — MSA rows (passes) of each hole: each device aligns its rows
      against the shared draft and the column vote is a psum over the pass
      axis — the tensor/sequence-parallel analog for this workload, riding
      ICI.

The sharded step below is exercised by __graft_entry__.dryrun_multichip
and tests/test_sharded_round.py, both of which assert its four outputs
equal the unsharded per-hole star round BIT-EXACTLY (the vote is a pure
pass-axis reduction, so sharding must change nothing).  The production
batched runner (pipeline/batch.py) lays its rounds over the same
(data, pass) mesh via input NamedShardings (--mesh D,P; default pure
data) — GSPMD inserts the identical psums; its mesh path is pinned
bit-equal to the per-hole rounds in tests/test_batch.py.  This module's
explicit shard_map version remains the reference formulation and the
dryrun target.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ccsx_tpu.config import AlignParams
from ccsx_tpu.ops import banded, traceback


def shard_map_compat(f, mesh: Mesh, in_specs, out_specs):
    """jax.shard_map across the 0.4.x/0.6+ API split: the entry point
    moved from jax.experimental.shard_map to jax.shard_map and the
    replication check was renamed check_rep -> check_vma.  Both the
    (data, pass) sharded round below and the fused multi-chip packed
    dispatch (pipeline/batch.py) go through here, with the check
    disabled for the same reason: DP scan carries mix replicated init
    constants with varying values, and pcasting every carry component
    buys nothing."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


def build_slab_mesh(devices) -> Mesh:
    """A 1-D ('slab',) mesh over the given local devices — the fused
    multi-chip packed dispatch stacks same-shape slabs into a leading
    device dimension and shard_maps one executable over this mesh (one
    transfer + one dispatch per group per wave, vs one of each per slab
    per chip under the r7 round-robin)."""
    return Mesh(np.array(devices), axis_names=("slab",))


def build_mesh(shape: Optional[Tuple[int, ...]] = None,
               axis_names: Tuple[str, ...] = ("data", "pass"),
               devices=None) -> Mesh:
    """A (data, pass) mesh over `devices` (default: all available).

    Default split: the pass axis gets 2 devices when there are >= 4 devices,
    otherwise 1 (pure data parallelism).
    """
    devs = np.array(devices if devices is not None else jax.devices())
    n = len(devs)
    if shape is None:
        p = 2 if n >= 4 and n % 2 == 0 else 1
        shape = (n // p, p)
    return Mesh(devs.reshape(shape), axis_names=axis_names)


def make_sharded_round(mesh: Mesh, params: AlignParams, tmax: int,
                       max_ins: int = 4):
    """Jitted, mesh-sharded star-MSA round.

    Inputs (global shapes):
      qs       (Z, Pp, W) uint8 — Z ZMWs x Pp passes, padded
      qlens    (Z, Pp) int32
      ts       (Z, tmax) uint8 — per-ZMW draft (replicated over 'pass')
      tlens    (Z,) int32
      row_mask (Z, Pp) bool

    Output: cons (Z, tmax) uint8, ins_base (Z, tmax, R) uint8,
      ins_votes (Z, tmax, R) int32, ncov (Z, tmax) int32,
      nwin (Z, tmax) int32 — all sharded over 'data' only (vote results
      are replicated over 'pass' after the psum).
    """
    projector = traceback.make_projector(tmax, max_ins)

    align_one = functools.partial(
        banded.banded_align, mode="global", params=params, with_moves=True,
        with_stats=False)

    def local_round(qs, qlens, ts, tlens, row_mask):
        # vmap over local ZMWs and local passes
        f = jax.vmap(jax.vmap(align_one, in_axes=(0, 0, None, None)),
                     in_axes=(0, 0, 0, 0))
        _, moves, offs = f(qs, qlens, ts, tlens)
        proj = jax.vmap(jax.vmap(projector, in_axes=(0, 0, 0, 0, None)),
                        in_axes=(0, 0, 0, 0, 0))
        aligned, ins_cnt, ins_b, _lead = proj(moves, offs, qs, qlens, tlens)

        mask = row_mask[:, :, None]
        cnts = jnp.stack(
            [((aligned == c) & mask).sum(1) for c in range(5)], axis=1
        )  # (Zl, 5, T)
        cnts = jax.lax.psum(cnts, "pass")
        ncov = cnts.sum(1)
        nwin = cnts.max(1)
        cons = jnp.argmax(cnts, axis=1).astype(jnp.uint8)
        cons = jnp.where(ncov == 0, jnp.uint8(4), cons)

        bases, votes = [], []
        for r in range(max_ins):
            has = mask[:, :, 0][:, :, None] * 0  # placate linters
            has = (ins_cnt > r) & row_mask[:, :, None]
            votes_r = jax.lax.psum(has.sum(1), "pass")
            bc = jnp.stack(
                [((ins_b[:, :, :, r] == c) & has).sum(1) for c in range(4)],
                axis=1)
            bc = jax.lax.psum(bc, "pass")
            bases.append(jnp.argmax(bc, axis=1).astype(jnp.uint8))
            votes.append(votes_r)
        ins_base = jnp.stack(bases, axis=2)
        ins_votes = jnp.stack(votes, axis=2)
        return cons, ins_base, ins_votes, ncov, nwin

    in_specs = (P("data", "pass", None), P("data", "pass"),
                P("data", None), P("data"), P("data", "pass"))
    out_specs = (P("data", None), P("data", None, None),
                 P("data", None, None), P("data", None),
                 P("data", None))
    shard = shard_map_compat(local_round, mesh, in_specs, out_specs)
    return jax.jit(shard)


def shard_batch(mesh: Mesh, arrays, specs):
    """Device-put host arrays with NamedShardings."""
    return [
        jax.device_put(a, NamedSharding(mesh, s))
        for a, s in zip(arrays, specs)
    ]
