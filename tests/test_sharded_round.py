"""Bit-parity of the pass-axis sharded round vs the unsharded star round.

The column vote is a pure reduction over the pass axis (reference: the MSA
column scan at main.c:583-598 counts rows per column), so sharding passes
across devices and psum-ing the counts must change NOTHING: all five
outputs of parallel/mesh.make_sharded_round must equal the per-hole
StarMsa.round outputs exactly — same argmax tie-breaks, same counts.
A subtly wrong collective (wrong axis, double-count, dropped remainder)
fails these exact comparisons where an agreement-threshold check would
pass.

Runs on the 8-virtual-device CPU mesh (conftest).
"""

import jax
import numpy as np
import pytest

# under CCSX_TEST_TPU=1 the suite runs on the real chip (single device);
# these tests need the 8-device mesh
pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs >= 8 devices (virtual CPU mesh)")

from ccsx_tpu.config import AlignParams
from ccsx_tpu.consensus import star
from ccsx_tpu.ops import banded
from ccsx_tpu.parallel import mesh as mesh_mod
from ccsx_tpu.utils import synth

W = 256          # window / qmax / tmax (len_quant=W keeps buckets equal)
MAX_INS = 4


def _batch(rng, Z, P, dead_rows=True):
    """(Z, P) batch with varying tlens, error rates, and dead pass rows."""
    qs = np.full((Z, P, W), banded.PAD, np.uint8)
    qlens = np.zeros((Z, P), np.int32)
    ts = np.full((Z, W), banded.PAD, np.uint8)
    tlens = np.zeros(Z, np.int32)
    row_mask = np.zeros((Z, P), bool)
    for z in range(Z):
        tlen = int(rng.integers(120, 230))
        tpl = rng.integers(0, 4, tlen).astype(np.uint8)
        ts[z, :tlen] = tpl
        tlens[z] = tlen
        live = P if not dead_rows else int(rng.integers(3, P + 1))
        for p in range(live):
            e = 0.02 + 0.06 * rng.random()
            q = synth.mutate(rng, tpl, e, e, e)[:W]
            qs[z, p, : len(q)] = q
            qlens[z, p] = len(q)
            row_mask[z, p] = True
    return qs, qlens, ts, tlens, row_mask


def _unsharded_reference(qs, qlens, ts, tlens, row_mask):
    """Per-hole star rounds (the production per-hole path)."""
    sm = star.StarMsa(AlignParams(), max_ins=MAX_INS, len_quant=W)
    Z = qs.shape[0]
    cons = np.full((Z, W), 4, np.uint8)
    ins_base = np.zeros((Z, W, MAX_INS), np.uint8)
    ins_votes = np.zeros((Z, W, MAX_INS), np.int32)
    ncov = np.zeros((Z, W), np.int32)
    nwin = np.zeros((Z, W), np.int32)
    for z in range(Z):
        rr = sm.round(qs[z], qlens[z], row_mask[z],
                      ts[z, : int(tlens[z])])
        T = rr.cons.shape[0]
        cons[z, :T] = rr.cons
        ins_base[z, :T] = rr.ins_base
        ins_votes[z, :T] = rr.ins_votes
        ncov[z, :T] = rr.ncov
        nwin[z, :T] = rr.nwin
    return cons, ins_base, ins_votes, ncov, nwin


def _run_sharded(shape, qs, qlens, ts, tlens, row_mask):
    m = mesh_mod.build_mesh(shape=shape, devices=jax.devices()[: np.prod(shape)])
    step = mesh_mod.make_sharded_round(m, AlignParams(), tmax=W,
                                       max_ins=MAX_INS)
    out = jax.block_until_ready(step(qs, qlens, ts, tlens, row_mask))
    return [np.asarray(o) for o in out]


def test_pass_sharded_equals_unsharded_exact(rng):
    """(4,2) data x pass mesh == per-hole rounds, all outputs exact."""
    qs, qlens, ts, tlens, row_mask = _batch(rng, Z=8, P=8)
    got = _run_sharded((4, 2), qs, qlens, ts, tlens, row_mask)
    want = _unsharded_reference(qs, qlens, ts, tlens, row_mask)
    for g, w, name in zip(got, want, ("cons", "ins_base", "ins_votes",
                                      "ncov", "nwin")):
        # beyond each hole's tlen both paths carry frozen padding whose
        # value is tie-broken identically (verified by the exact compare
        # over the full tmax here — no masking applied)
        np.testing.assert_array_equal(g, w, err_msg=name)


def test_pass_axis_split_invariant(rng):
    """(8,1) vs (4,2) vs (2,4): the pass-axis split must not matter."""
    qs, qlens, ts, tlens, row_mask = _batch(rng, Z=8, P=8)
    outs = [_run_sharded(s, qs, qlens, ts, tlens, row_mask)
            for s in ((8, 1), (4, 2), (2, 4))]
    for other, shape in zip(outs[1:], ("(4,2)", "(2,4)")):
        for g, w, name in zip(other, outs[0],
                              ("cons", "ins_base", "ins_votes", "ncov", "nwin")):
            np.testing.assert_array_equal(
                g, w, err_msg=f"{name} differs between (8,1) and {shape}")


def test_sharded_round_dead_rows_on_one_device(rng):
    """A hole whose live passes all land on one pass-shard still votes
    correctly (the other shard contributes zero counts via psum)."""
    qs, qlens, ts, tlens, row_mask = _batch(rng, Z=4, P=8, dead_rows=False)
    # kill the second half of the pass rows: with a (2,4)... use (4,2)
    # mesh -> pass shards hold rows [0:4) and [4:8); shard 1 is all dead
    row_mask[:, 4:] = False
    qlens[:, 4:] = 0
    qs[:, 4:] = banded.PAD
    got = _run_sharded((4, 2), qs, qlens, ts, tlens, row_mask)
    want = _unsharded_reference(qs, qlens, ts, tlens, row_mask)
    for g, w, name in zip(got, want, ("cons", "ins_base", "ins_votes",
                                      "ncov", "nwin")):
        np.testing.assert_array_equal(g, w, err_msg=name)
    assert int(got[3].max()) <= 4
