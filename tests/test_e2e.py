"""End-to-end: CLI flows over synthetic FASTA/BAM; windowed consensus."""

import io

import numpy as np
import pytest

from ccsx_tpu import cli
from ccsx_tpu.config import CcsConfig
from ccsx_tpu.consensus import windowed
from ccsx_tpu.consensus.align_host import HostAligner
from ccsx_tpu.io import bam, fastx, zmw as zmw_mod
from ccsx_tpu.ops import encode as enc
from ccsx_tpu.utils import synth


def _zmw_from_synth(z):
    seqs = b"".join(enc.decode(p).encode() for p in z.passes)
    lens = np.array([len(p) for p in z.passes], np.int32)
    offs = np.zeros(len(lens), np.int32)
    np.cumsum(lens[:-1], out=offs[1:])
    return zmw_mod.Zmw(z.movie, z.hole, seqs, lens, offs)


# ---------- windowed consensus ----------

@pytest.mark.slow  # ~37s: 6kb whole-molecule windowed run
def test_windowed_matches_template_long_read(rng):
    """A >1-window molecule: the shred path must stitch windows correctly."""
    cfg = CcsConfig(is_bam=False, window_init=1024, window_add=1024,
                    window_minlen=512, max_window=4096)
    z = synth.make_zmw(rng, template_len=3000, n_passes=6,
                       sub_rate=0.02, ins_rate=0.04, del_rate=0.04)
    zz = _zmw_from_synth(z)
    cns, _ = windowed.ccs_windowed(zz, HostAligner(cfg.align), cfg)
    assert cns is not None
    idy = synth.identity_either(enc.encode(cns), z.template)
    assert idy > 0.985, f"windowed identity {idy:.4f}"
    assert abs(len(cns) - 3000) < 60


@pytest.mark.slow  # ~110s: 20kb molecule, ~10 windows
def test_windowed_long_molecule_many_windows(rng):
    """4kb molecule, ~8 windows at the test window size: cursor re-sync
    must hold across many breakpoints with no drift (identity stays
    high and the stitched length tracks the template), and the fused
    batched path must agree byte-for-byte — the long-context claim of
    the shred design (SURVEY.md §5.7) at depth.  Window 512 shares its
    compiled shapes with the other windowed tests."""
    cfg = CcsConfig(is_bam=False, window_init=512, window_add=512,
                    window_minlen=256, max_window=2048)
    z = synth.make_zmw(rng, template_len=4000, n_passes=6,
                       sub_rate=0.02, ins_rate=0.04, del_rate=0.04)
    zz = _zmw_from_synth(z)

    from ccsx_tpu.consensus import prepare as prep
    from ccsx_tpu.consensus.star import StarMsa, run_rounds
    from ccsx_tpu.consensus.windowed import windowed_gen
    from ccsx_tpu.pipeline.batch import BatchExecutor

    passes = prep.oriented_passes(zz, HostAligner(cfg.align), cfg)
    sm = StarMsa(cfg.align, cfg.max_ins_per_col, cfg.len_bucket_quant)
    want = run_rounds(windowed_gen(passes, cfg), sm)
    idy = synth.identity_either(want, z.template)
    assert idy > 0.985, f"long windowed identity {idy:.4f}"
    assert abs(len(want) - 4000) < 80

    ex = BatchExecutor(cfg)
    gen = windowed_gen(passes, cfg)
    req = next(gen)
    try:
        while True:
            req = gen.send(ex.run([req])[0])
    except StopIteration as e:
        got = e.value
    np.testing.assert_array_equal(want, got)


def test_windowed_short_molecule_single_flush(rng):
    """Molecules shorter than a window take the final-flush path only."""
    cfg = CcsConfig(is_bam=False)
    z = synth.make_zmw(rng, template_len=700, n_passes=5)
    zz = _zmw_from_synth(z)
    cns, _ = windowed.ccs_windowed(zz, HostAligner(cfg.align), cfg)
    idy = synth.identity_either(enc.encode(cns), z.template)
    assert idy > 0.97


# ---------- BAM ----------

def test_bam_roundtrip(tmp_path):
    p = tmp_path / "t.bam"
    recs = [("m0/1/0_8", b"ACGTACGT", b"IIIIIIII"),
            ("m0/1/8_12", b"GGGG", b"!!!!"),
            ("m0/2/0_4", b"TTTT", None)]
    bam.write_bam(p, recs)
    got = list(bam.read_bam_records(p))
    assert [r.name for r in got] == ["m0/1/0_8", "m0/1/8_12", "m0/2/0_4"]
    assert got[0].seq == b"ACGTACGT"
    assert got[0].qual == b"IIIIIIII"
    assert got[1].qual == b"!!!!"


def test_bam_bad_magic(tmp_path):
    p = tmp_path / "bad.bam"
    p.write_bytes(b"NOTBAM..")
    with pytest.raises(bam.BamError):
        list(bam.read_bam_records(p))


def test_bam_truncated(tmp_path):
    import gzip as _gz
    p = tmp_path / "t.bam"
    bam.write_bam(p, [("m0/1/0_8", b"ACGTACGT", None)])
    raw = _gz.decompress(p.read_bytes())
    q = tmp_path / "trunc.bam"
    q.write_bytes(_gz.compress(raw[:-5]))
    with pytest.raises(bam.BamError):
        list(bam.read_bam_records(q))


# ---------- CLI ----------

def _make_inputs(tmp_path, rng, n_holes=2):
    zs = [synth.make_zmw(rng, template_len=900, n_passes=5, movie="mv",
                         hole=str(100 + h)) for h in range(n_holes)]
    fa = tmp_path / "in.fa"
    fa.write_text(synth.make_fasta(zs))
    return zs, fa


def _parse_fasta(path):
    recs = list(fastx.read_fastx(str(path)))
    return {r.name: r.seq for r in recs}


def test_cli_fasta_to_fasta(tmp_path, rng):
    zs, fa = _make_inputs(tmp_path, rng)
    out = tmp_path / "out.fa"
    rc = cli.main(["-A", "-m", "1000", str(fa), str(out)])
    assert rc == 0
    got = _parse_fasta(out)
    assert set(got) == {"mv/100/ccs", "mv/101/ccs"}
    for z in zs:
        cns = enc.encode(got[f"mv/{z.hole}/ccs"])
        assert synth.identity_either(cns, z.template) > 0.97


def test_cli_whole_read_mode(tmp_path, rng):
    zs, fa = _make_inputs(tmp_path, rng, n_holes=1)
    out = tmp_path / "out.fa"
    rc = cli.main(["-A", "-P", "-m", "1000", str(fa), str(out)])
    assert rc == 0
    got = _parse_fasta(out)
    assert set(got) == {"mv/100/ccs"}


def test_cli_exclusion_and_filters(tmp_path, rng):
    zs, fa = _make_inputs(tmp_path, rng)
    out = tmp_path / "out.fa"
    rc = cli.main(["-A", "-m", "1000", "-X", "100", str(fa), str(out)])
    assert rc == 0
    assert set(_parse_fasta(out)) == {"mv/101/ccs"}


def test_cli_min_count_validation(capsys):
    rc = cli.main(["-c", "2", "x", "y"])
    assert rc == -1
    assert "min fulllen count" in capsys.readouterr().err


def test_cli_bam_input(tmp_path, rng):
    z = synth.make_zmw(rng, template_len=900, n_passes=5, movie="mv",
                       hole="7")
    p = tmp_path / "in.bam"
    recs = [(n, enc.decode(s).encode(), None)
            for n, s in zip(z.names, z.passes)]
    bam.write_bam(p, recs)
    out = tmp_path / "out.fa"
    rc = cli.main(["-m", "1000", str(p), str(out)])
    assert rc == 0
    got = _parse_fasta(out)
    assert set(got) == {"mv/7/ccs"}
    assert synth.identity_either(enc.encode(got["mv/7/ccs"]), z.template) > 0.97


def test_cli_threaded_output_order_matches_serial(tmp_path, rng):
    """-j N must preserve input-ordered output (kt_pipeline invariant)."""
    zs, fa = _make_inputs(tmp_path, rng, n_holes=3)
    out1 = tmp_path / "o1.fa"
    out2 = tmp_path / "o2.fa"
    assert cli.main(["-A", "-m", "1000", str(fa), str(out1)]) == 0
    assert cli.main(["-A", "-m", "1000", "-j", "3", str(fa), str(out2)]) == 0
    assert out1.read_text() == out2.read_text()


def test_cli_journal_resume(tmp_path, rng):
    """A resumed run skips already-written holes and appends the rest."""
    zs, fa = _make_inputs(tmp_path, rng, n_holes=3)
    full = tmp_path / "full.fa"
    assert cli.main(["-A", "-m", "1000", str(fa), str(full)]) == 0

    out = tmp_path / "o.fa"
    jp = tmp_path / "j.json"
    # simulate a crashed run that completed 2 holes
    import json
    jp.write_text(json.dumps({"input_id": str(fa), "holes_done": 2}))
    recs = list(fastx.read_fastx(str(full)))
    out.write_text("".join(f">{r.name}\n{r.seq.decode()}\n"
                           for r in recs[:2]))
    assert cli.main(["-A", "-m", "1000", "--journal", str(jp),
                     str(fa), str(out)]) == 0
    assert out.read_text() == full.read_text()
    assert json.loads(jp.read_text())["holes_done"] == 3


def test_cli_corrupt_bam_clean_error(tmp_path, capsys):
    p = tmp_path / "bad.bam"
    import gzip as _gz
    p.write_bytes(_gz.compress(b"NOTBAM" + b"\x00" * 50))
    rc = cli.main([str(p), str(tmp_path / "o.fa")])
    assert rc == 1
    assert "invalid input stream" in capsys.readouterr().err


def test_windowed_partial_end_passes(rng):
    """Real ZMWs have truncated first/last passes; the walk must drop the
    short out-of-group fragments without aligning them (main.c:380,416)
    and the consensus must still recover the template."""
    cfg = CcsConfig(is_bam=False)
    z = synth.make_zmw(rng, template_len=1200, n_passes=7,
                       partial_ends=True)
    assert len(z.passes[0]) < 1000 and len(z.passes[-1]) < 1000
    zz = _zmw_from_synth(z)

    calls = []
    from ccsx_tpu.consensus.align_host import HostAligner

    class CountingAligner(HostAligner):
        def strand_match(self, q, t, pct):
            calls.append(len(q))
            return super().strand_match(q, t, pct)

    from ccsx_tpu.consensus import prepare as prep
    passes = prep.oriented_passes(zz, CountingAligner(cfg.align), cfg)
    # 5 full passes kept, 2 partials dropped, no alignment dispatched
    assert len(passes) == 5
    assert calls == []

    cns, _ = windowed.ccs_windowed(zz, HostAligner(cfg.align), cfg)
    idy = synth.identity_either(enc.encode(cns), z.template)
    assert idy > 0.97


def test_usage_text_parity(capsys):
    """-h prints the reference-parity usage (main.c:723-749) and rc 1,
    including the -j [2] usage-vs-default quirk (main.c:740 vs 754)."""
    from ccsx_tpu import cli

    assert cli.main(["-h"]) == 1
    out = capsys.readouterr().out
    assert "Usage  : ccsx-tpu  [options] <INPUT> <OUTPUT>" in out
    assert "Number of threads to use. [2]" in out  # the quirk, verbatim
    assert "Minimum number of subreads required to generate CCS. [3]" in out
    # the actual default stays 1, like the reference's code (main.c:754)
    assert cli.build_parser().parse_args(["x", "y"]).threads == 1
