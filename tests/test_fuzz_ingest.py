"""Fuzz the native ingest parsers against the pure-Python oracles.

The hand-rolled native parsers (BGZF header walk io_native.cpp, BAM
record bounds, FASTQ state machine) previously had happy-path plus a few
targeted truncation tests; this corpus (VERDICT r3 item 7) runs >=50
deterministic mutations — bit flips, truncations at arbitrary offsets,
garbage splices, and targeted corruptions (BC subfield, oversized ISIZE,
mid-record EOF, malformed read names) — through BOTH readers and holds
them to a differential contract:

  * neither reader may crash the process (a native segfault kills
    pytest — that IS the detector);
  * every record the two readers both produce must be identical: the
    shorter record list must be a prefix of the longer (the readers may
    legitimately detect corruption at different points — e.g. the native
    BGZF layer is stricter: per-block CRC + EOF-marker truncation
    detection, io_native.cpp — but they must never DISAGREE about bytes
    they both parsed);
  * when both complete cleanly the outputs must be equal in full.

Reference semantics being pinned: bamlite.c:135-165 record parse,
kseq.h:177-218 FASTA/Q state machine, seqio.h:167-172 name splitting.
"""

from __future__ import annotations

import gzip

import numpy as np
import pytest

from ccsx_tpu import native
from ccsx_tpu.io import bam as bam_mod
from ccsx_tpu.io import fastx

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native library unavailable")


def _drain_native(path, is_bam):
    from ccsx_tpu.native.io import read_records_native

    recs, err = [], None
    try:
        for r in read_records_native(path, is_bam=is_bam):
            recs.append((r.name, r.seq, r.qual))
    except Exception as e:  # fuzzing: any clean Python error is fine
        err = e
    return recs, err


def _drain_python(path, is_bam):
    recs, err = [], None
    try:
        it = (bam_mod.read_bam_records(path) if is_bam
              else fastx.read_fastx(path))
        for r in it:
            recs.append((r.name, r.seq, r.qual))
    except Exception as e:
        err = e
    return recs, err


def _check_parity(path, is_bam, label):
    nat, nat_err = _drain_native(str(path), is_bam)
    py, py_err = _drain_python(str(path), is_bam)
    short, long_ = (nat, py) if len(nat) <= len(py) else (py, nat)
    assert long_[: len(short)] == short, (
        f"{label}: parsed-record divergence (native err={nat_err!r}, "
        f"python err={py_err!r})")
    if nat_err is None and py_err is None:
        assert nat == py, f"{label}: clean runs disagree"
    return nat_err, py_err


# ---- base fixtures -------------------------------------------------------


def _bam_records(n=24, seed=0):
    rng = np.random.default_rng(seed)
    recs = []
    for i in range(n):
        ln = int(rng.integers(40, 400))
        seq = rng.choice(list(b"ACGT"), ln).astype(np.uint8).tobytes()
        qual = bytes(33 + rng.integers(0, 60, ln, dtype=np.uint8))
        recs.append((f"mv/{i // 4}/{i}_{i + ln}", seq, qual,
                     (("np", "i", i), ("rq", "f", 0.99),
                      ("zm", "i", i // 4))))
    return recs


def _fastq_bytes(n=30, seed=1):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        ln = int(rng.integers(30, 300))
        seq = rng.choice(list(b"ACGT"), ln).astype(np.uint8).tobytes()
        qual = bytes(33 + rng.integers(0, 60, ln, dtype=np.uint8))
        out.append(b"@mv/%d/%d_%d extra comment\n%s\n+\n%s\n"
                   % (i // 3, i, i + ln, seq, qual))
    return b"".join(out)


def _fasta_bytes(n=20, seed=2):
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        ln = int(rng.integers(50, 500))
        seq = rng.choice(list(b"ACGT"), ln).astype(np.uint8).tobytes()
        # multi-line bodies exercise the kseq continuation path
        body = b"\n".join(seq[j: j + 70] for j in range(0, ln, 70))
        out.append(b">mv/%d/%d_%d\n%s\n" % (i // 3, i, i + ln, body))
    return b"".join(out)


# ---- corpus generators ---------------------------------------------------


def _bitflip(data: bytes, seed: int) -> bytes:
    rng = np.random.default_rng(seed)
    raw = bytearray(data)
    pos = int(rng.integers(0, len(raw)))
    raw[pos] ^= 1 << int(rng.integers(0, 8))
    return bytes(raw)


def _truncate(data: bytes, seed: int) -> bytes:
    rng = np.random.default_rng(seed)
    return data[: int(rng.integers(1, len(data)))]


def _splice(data: bytes, seed: int) -> bytes:
    rng = np.random.default_rng(seed)
    pos = int(rng.integers(0, len(data)))
    junk = rng.integers(0, 256, 4, dtype=np.uint8).tobytes()
    return data[:pos] + junk + data[pos:]


def test_fuzz_bgzf_bam_corpus(tmp_path):
    """36 mutated BGZF BAM files: bit flips, truncations, splices."""
    base = tmp_path / "base.bam"
    bam_mod.write_bam(str(base), _bam_records(), bgzf=True)
    data = base.read_bytes()
    n_err = 0
    for i in range(36):
        mut = (_bitflip, _truncate, _splice)[i % 3](data, 1000 + i)
        p = tmp_path / f"m{i}.bam"
        p.write_bytes(mut)
        nat_err, py_err = _check_parity(p, True, f"bgzf-bam[{i}]")
        n_err += nat_err is not None
    # sanity: the corpus actually stressed the error paths
    assert n_err >= 5


def test_fuzz_plain_gzip_bam_corpus(tmp_path):
    """Plain-gzip BAM container (bamlite.h:13-19 path): 12 mutations of
    the DECOMPRESSED payload re-gzipped, hitting the BAM record walk
    itself rather than the container CRC."""
    payload_src = tmp_path / "src.bam"
    bam_mod.write_bam(str(payload_src), _bam_records(n=16, seed=3),
                      bgzf=False)
    payload = gzip.decompress(payload_src.read_bytes())
    for i in range(12):
        mut = (_bitflip, _truncate, _splice)[i % 3](payload, 2000 + i)
        p = tmp_path / f"m{i}.bam"
        p.write_bytes(gzip.compress(mut))
        _check_parity(p, True, f"gz-bam[{i}]")


def test_fuzz_fastq_corpus(tmp_path):
    """18 mutated FASTQ files through the state machine (kseq.h
    semantics): flips corrupt bases/names, truncations produce
    mid-record EOF (including inside the '+' quality section)."""
    data = _fastq_bytes()
    for i in range(18):
        mut = (_bitflip, _truncate, _splice)[i % 3](data, 3000 + i)
        p = tmp_path / f"m{i}.fq"
        p.write_bytes(mut)
        _check_parity(p, False, f"fastq[{i}]")


def test_fuzz_fasta_corpus(tmp_path):
    """12 mutated multi-line FASTA files."""
    data = _fasta_bytes()
    for i in range(12):
        mut = (_bitflip, _truncate, _splice)[i % 3](data, 4000 + i)
        p = tmp_path / f"m{i}.fa"
        p.write_bytes(mut)
        _check_parity(p, False, f"fasta[{i}]")


def test_fuzz_targeted_bgzf_corruptions(tmp_path):
    """Targeted container attacks: BC subfield id/len garbage, BSIZE
    lies, oversized ISIZE, EOF-marker surgery."""
    base = tmp_path / "base.bam"
    bam_mod.write_bam(str(base), _bam_records(n=12, seed=5), bgzf=True)
    data = bytearray(base.read_bytes())

    cases = []
    # (a) BC subfield id corrupted in the first member header
    c = bytearray(data)
    c[12:14] = b"XX"
    cases.append(("bad-BC-id", bytes(c)))
    # (b) BSIZE smaller than the header itself
    c = bytearray(data)
    c[16:18] = (5).to_bytes(2, "little")
    cases.append(("tiny-BSIZE", bytes(c)))
    # (c) BSIZE pointing past EOF
    c = bytearray(data)
    c[16:18] = (0xFFFF).to_bytes(2, "little")
    cases.append(("huge-BSIZE", bytes(c)))
    # (d) oversized ISIZE in the first member (cap is 64KB)
    bsize = int.from_bytes(data[16:18], "little") + 1
    c = bytearray(data)
    c[bsize - 4: bsize] = (1 << 24).to_bytes(4, "little")
    cases.append(("huge-ISIZE", bytes(c)))
    # (e) EOF marker replaced by garbage
    c = bytearray(data)
    c[-len(bam_mod.BGZF_EOF):] = b"\x00" * len(bam_mod.BGZF_EOF)
    cases.append(("mangled-EOF", bytes(c)))
    # (f) duplicate EOF marker mid-file (empty block: legal BGZF)
    c = bytes(data[:bsize]) + bam_mod.BGZF_EOF + bytes(data[bsize:])
    cases.append(("empty-block-mid-file", c))

    for label, blob in cases:
        p = tmp_path / f"{label}.bam"
        p.write_bytes(blob)
        _check_parity(p, True, label)
    # (f) is legal: the native reader must parse it cleanly and fully
    nat, nat_err = _drain_native(str(tmp_path / "empty-block-mid-file.bam"),
                                 True)
    assert nat_err is None and len(nat) == 12


# ---- salvage-mode differential parity (ISSUE 10) -------------------------
#
# Salvage resync is a SHARED contract: io/corruption.py's reason codes,
# BGZF block-rescan rules, and plausible-record scan are implemented
# twice (Python + io_native.cpp) and must classify each mutant with the
# same reason buckets and salvage the SAME hole set.  These tests run a
# seeded mutant corpus through both stacks' full ZMW streamers with
# salvage on and hold them to exact equality — holes, passes, bytes,
# and per-reason corruption counts.


def _drain_salvage_native(path, cfg):
    from ccsx_tpu.native.io import stream_zmws_native
    from ccsx_tpu.utils.metrics import Metrics

    m = Metrics()
    holes = [(z.movie, z.hole, tuple(int(x) for x in z.lens), z.seqs)
             for z in stream_zmws_native(str(path), cfg, metrics=m)]
    return holes, m.corrupt_reasons, m.holes_corrupt


def _drain_salvage_python(path, cfg):
    from ccsx_tpu.io import zmw as zmw_mod
    from ccsx_tpu.io.corruption import SalvageSink
    from ccsx_tpu.utils.metrics import Metrics

    m = Metrics()
    sink = SalvageSink(m)
    if cfg.is_bam:
        records = bam_mod.read_bam_records(str(path), salvage=sink)
    else:
        records = fastx.read_fastx(str(path), salvage=sink)
    holes = [(z.movie, z.hole, tuple(int(x) for x in z.lens), z.seqs)
             for z in zmw_mod.stream_zmws(records, cfg, metrics=m,
                                          salvage=sink)]
    return holes, m.corrupt_reasons, m.holes_corrupt


def _salvage_parity_corpus(tmp_path, data, ext, is_bam, n, seed,
                           require_events=True):
    from ccsx_tpu.config import CcsConfig

    cfg = CcsConfig(min_subread_len=1, is_bam=is_bam, salvage=True)
    rng = np.random.default_rng(seed)
    n_events = 0
    for i in range(n):
        mut = bytearray(data)
        kind = i % 3
        if kind == 0:
            pos = int(rng.integers(0, len(data)))
            mut[pos] ^= 1 << int(rng.integers(0, 8))
        elif kind == 1:
            mut = mut[:int(rng.integers(1, len(data)))]
        else:
            pos = int(rng.integers(0, max(len(data) - 64, 1)))
            ln = int(rng.integers(4, 64))
            mut[pos:pos + ln] = b"\x00" * min(ln, len(mut) - pos)
        p = tmp_path / f"s{i}.{ext}"
        p.write_bytes(bytes(mut))
        nat = _drain_salvage_native(p, cfg)
        py = _drain_salvage_python(p, cfg)
        assert nat[0] == py[0], \
            f"salvaged hole sets diverge on mutant {i} ({ext})"
        assert nat[1] == py[1], \
            f"reason buckets diverge on mutant {i} ({ext}): " \
            f"native {nat[1]} python {py[1]}"
        assert nat[2] == py[2]
        n_events += nat[2]
    # the corpus must actually have exercised salvage, not parsed clean
    if require_events:
        assert n_events > 0


def test_salvage_parity_bgzf_bam(tmp_path):
    """18 seeded BGZF BAM mutants: both stacks salvage the same holes
    with the same reason buckets (block rescans + record scans)."""
    base = tmp_path / "base.bam"
    # 6 records/hole so holes clear the default pass filter
    recs = []
    rng = np.random.default_rng(11)
    for i in range(120):
        ln = int(rng.integers(150, 400))
        seq = rng.choice(list(b"ACGT"), ln).astype(np.uint8).tobytes()
        recs.append((f"mv/{i // 6}/{i}_{i + ln}", seq, b"I" * ln))
    bam_mod.write_bam(str(base), recs, bgzf=True)
    _salvage_parity_corpus(tmp_path, base.read_bytes(), "bam", True,
                           18, 5000)


def test_salvage_parity_fastq(tmp_path):
    """18 seeded FASTQ mutants: same salvage semantics on the text
    state machine (qual mismatch classification + line-anchored
    resync)."""
    _salvage_parity_corpus(tmp_path, _fastq_bytes(n=36, seed=6), "fq",
                           False, 18, 6000)


def test_salvage_parity_fasta(tmp_path):
    """12 seeded multi-line FASTA mutants + one deterministic bad-name
    mutant (plain FASTA has no checksums, so random damage often
    parses clean — the crafted mutant guarantees the zmw_bad_name
    path is compared)."""
    from ccsx_tpu.config import CcsConfig

    data = _fasta_bytes(n=30, seed=7)
    _salvage_parity_corpus(tmp_path, data, "fa", False, 12, 7000,
                           require_events=False)
    mut = data.replace(b">mv/4/", b">mvx4x", 1)
    p = tmp_path / "badname.fa"
    p.write_bytes(mut)
    cfg = CcsConfig(min_subread_len=1, is_bam=False, salvage=True)
    nat = _drain_salvage_native(p, cfg)
    py = _drain_salvage_python(p, cfg)
    assert nat == py
    assert nat[1] == {"zmw_bad_name": 1}


def test_salvage_resync_blank_line_before_header(tmp_path):
    """A blank line between a damaged quality section and the next
    record header: the line-anchored resync must skip it and keep the
    header (the native scan once swallowed the whole next line after a
    bare newline, silently dropping a healthy record — review find)."""
    from ccsx_tpu.config import CcsConfig

    fq = (b"@mv/1/0_8\nACGTACGT\n+\nIIIIIIIII\n"   # qual 9 > seq 8
          b"\n"                                     # blank line
          + b"".join(b"@mv/1/%d_%d\nACGTACGT\n+\nIIIIIIII\n"
                     % (i, i + 8) for i in range(8, 48, 8)))
    p = tmp_path / "blank.fq"
    p.write_bytes(fq)
    cfg = CcsConfig(min_subread_len=1, is_bam=False, salvage=True)
    nat = _drain_salvage_native(p, cfg)
    py = _drain_salvage_python(p, cfg)
    assert nat == py
    assert [(h[1], len(h[2])) for h in nat[0]] == [("1", 5)]
    assert nat[1] == {"fastx_qual_mismatch": 1}


def test_failfast_reason_codes_agree(tmp_path):
    """Fail-fast (salvage OFF) classification: when the native reader
    errors, its reason code is a member of the pinned taxonomy, and a
    clean-parse disagreement between the stacks is still forbidden."""
    from ccsx_tpu.io.corruption import REASONS
    from ccsx_tpu.native.io import NativeStreamError

    base = tmp_path / "base.bam"
    bam_mod.write_bam(str(base), _bam_records(n=24, seed=9), bgzf=True)
    data = base.read_bytes()
    n_classified = 0
    for i in range(12):
        mut = (_bitflip, _truncate, _splice)[i % 3](data, 9000 + i)
        p = tmp_path / f"f{i}.bam"
        p.write_bytes(mut)
        nat_err, py_err = _check_parity(p, True, f"failfast[{i}]")
        if isinstance(nat_err, NativeStreamError):
            assert nat_err.reason in REASONS, \
                f"unclassified native reason {nat_err.reason!r}"
            n_classified += 1
        if py_err is not None and hasattr(py_err, "reason"):
            assert py_err.reason in REASONS
    assert n_classified >= 3


def test_fuzz_zmw_name_edge_cases(tmp_path):
    """Malformed movie/hole/region names kill the stream in the
    reference (seqio.h:168-172, returns -1 mid-file); both ZMW streamers
    must agree on the holes parsed before the bad name."""
    from ccsx_tpu.config import CcsConfig
    from ccsx_tpu.io import zmw as zmw_mod
    from ccsx_tpu.native.io import stream_zmws_native

    # 5 subreads per hole: the default count filter keeps a hole iff it
    # has >= min_fulllen_count + 2 = 5 records (main.c:659)
    names = ([f"mv/1/{i}_{i + 100}" for i in range(0, 500, 100)]
             + [f"mv/2/{i}_{i + 100}" for i in range(0, 500, 100)]
             + ["no_slashes_at_all"]       # 1 field: fatal bad name
             + [f"mv/3/{i}_{i + 100}" for i in range(0, 500, 100)])
    rng = np.random.default_rng(7)
    out = []
    for nm in names:
        seq = rng.choice(list(b"ACGT"), 120).astype(np.uint8).tobytes()
        out.append(b">%s\n%s\n" % (nm.encode(), seq))
    p = tmp_path / "z.fa"
    p.write_bytes(b"".join(out))

    cfg = CcsConfig(min_subread_len=1, is_bam=False)

    def drain(stream):
        holes, err = [], None
        try:
            for z in stream:
                holes.append((z.movie, z.hole, z.total_len))
        except Exception as e:
            err = e
        return holes, err

    nat, nat_err = drain(stream_zmws_native(str(p), cfg))
    py, py_err = drain(zmw_mod.stream_zmws(
        fastx.read_fastx(str(p)), cfg))
    assert nat == py
    # the bad name is fatal in both (reference parity).  Only hole mv/1
    # survives: the error is raised while mv/2 is still accumulating
    # (the streamer's one-record lookahead hasn't seen mv/2's terminator
    # yet), so the in-progress hole is dropped with the stream — the
    # same mid-accumulation -1 behavior as seqio.h:168-172
    assert nat_err is not None and py_err is not None
    assert len(nat) == 1 and nat[0][:2] == ("mv", "1")
