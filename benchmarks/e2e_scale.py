"""Sustained end-to-end throughput at scale (VERDICT r3 item 2).

The per-config e2e bench (benchmarks/e2e.py) measures 8-16 holes — enough
for correctness, too small to say anything about SUSTAINED throughput:
compile amortization, admission-window packing, and the dispatch count
per hole all only settle with hundreds of holes in flight.  This bench
runs ONE large realistic job through the full CLI:

  * >= 256 holes (``--holes``), pass counts drawn from the lognormal
    Sequel-II-like distribution (benchmarks/quality.sample_pass_counts,
    5..30 passes), template lengths mixed 1-5 kb;
  * BGZF subreads.bam input (the production container), --batch on,
    --inflight 64 (the admission window the batched scheduler was
    designed for, pipeline/batch.py);
  * metrics JSONL captured: stage attribution (ingest/prep/compute/
    write), device dispatch count, window count, refine overflows.

It reports sustained ZMWs/sec and zmw-WINDOWS/sec, and — the honest
bridge to the round metric (bench.py) — the ratio of the e2e window rate
to a round-metric measurement taken in the same process right before the
run.  A small-batch run (``--floor-holes``) quantifies the latency floor
for contrast (reference overlap analog: the 3-stage pipeline keeps its
compute stage saturated, main.c:856).

Usage: python benchmarks/e2e_scale.py [--holes 256] [--json out.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "benchmarks"))

from ccsx_tpu import cli                                     # noqa: E402
from ccsx_tpu.io import bam, fastx                           # noqa: E402
from ccsx_tpu.ops import encode as enc                       # noqa: E402
from ccsx_tpu.utils import synth                             # noqa: E402
from quality import ERR, sample_pass_counts                  # noqa: E402


def make_big_bam(path, n_holes: int, rng, tlen_lo=1000, tlen_hi=5000):
    """A realistic subreads.bam: lognormal pass counts, mixed-length
    templates (default 1-5 kb), BGZF container."""
    counts = sample_pass_counts(rng, n_holes)
    tlens = rng.integers(tlen_lo, tlen_hi + 1, n_holes)
    zs = []
    recs = []
    for h in range(n_holes):
        # partial_ends: real polymerases start/end mid-molecule (the
        # reference SKIPS these short out-of-group fragments without
        # alignment, main.c:382 — parity says they cost nothing)
        z = synth.make_zmw(rng, int(tlens[h]), int(counts[h]),
                           movie="mv", hole=str(h), partial_ends=True,
                           **ERR)
        if h % 5 == 0:
            # adapter read-through: LONGER than the template group, so
            # the reference aligns it (strand_match + clip, main.c:
            # 392-406) and the parity break forces alignment-verified
            # strand for the following passes — this is what drives the
            # batched PairExecutor at scale
            z.passes.insert(len(z.passes) // 2,
                            synth.read_through(rng, z.template, **ERR))
            z.strands.insert(len(z.strands) // 2, 0)
        zs.append(z)
        for name, p in zip(z.names, z.passes):
            recs.append((name, enc.decode(p).encode(), None))
    bam.write_bam(path, recs, bgzf=True)
    return zs


def round_metric_inline(backend_ready: bool = True) -> dict:
    """The bench.py round measurement (Z=16 x P=8 x W=1024), run in this
    process so the e2e/round ratio compares the same chip minutes."""
    import bench

    t0 = time.perf_counter()
    value = bench.measure()
    cells = bench.P * bench.W * 128
    return {"zmw_windows_per_sec": round(value, 1),
            "dp_cells_per_sec": round(value * cells),
            "measure_seconds": round(time.perf_counter() - t0, 1)}


def _scrape_progress(port: int, stop, samples: list) -> None:
    """Poll the run's /progress endpoint (tolerating the auto-bump
    window above the requested port) once a second into ``samples`` —
    the live-ETA series the artifact's eta_accuracy recap grades."""
    import urllib.request

    from ccsx_tpu.utils.telemetry import PORT_TRIES

    while not stop.is_set():
        # cover the server's whole auto-bump window, or a busy base
        # port silently yields zero ETA samples
        for p in range(port, port + PORT_TRIES):
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{p}/progress",
                        timeout=0.5) as r:
                    snap = json.loads(r.read().decode())
                samples.append(snap.get("progress") or {})
                break
            except (OSError, ValueError):
                continue
        stop.wait(1.0)


def eta_accuracy(samples: list, actual_s: float):
    """Grade the live ETA against the actual wall: for every scrape
    that carried an ETA, |predicted finish - actual| / actual."""
    errs = sorted(
        abs((s["elapsed_s"] + s["eta_s"]) - actual_s) / actual_s
        for s in samples
        if s.get("eta_s") is not None and s.get("elapsed_s") is not None)
    if not errs:
        return None
    return {"eta_samples": len(errs),
            "median_abs_err_pct": round(errs[len(errs) // 2] * 100, 2),
            "worst_abs_err_pct": round(errs[-1] * 100, 2)}


def run_scale(n_holes: int, inflight: int, rng, device: str = "auto",
              tlen_lo=1000, tlen_hi=5000, cli_extra=(),
              telemetry_port: int = 0):
    import threading

    from ccsx_tpu.io import bamindex

    with tempfile.TemporaryDirectory() as tmp:
        in_path = os.path.join(tmp, "big.bam")
        zs = make_big_bam(in_path, n_holes, rng, tlen_lo, tlen_hi)
        # BGZF hole index sidecar: gives the run a knowable holes_total,
        # so the progress estimator reports pct/ETA (not rate-only) and
        # the report's ETA-vs-actual curve has data
        bamindex.build_index(in_path)
        out = os.path.join(tmp, "out.fa")
        mpath = os.path.join(tmp, "m.jsonl")
        extra = list(cli_extra)
        samples: list = []
        stop = threading.Event()
        scraper = None
        if telemetry_port:
            extra += ["--telemetry-port", str(telemetry_port)]
            scraper = threading.Thread(
                target=_scrape_progress,
                args=(telemetry_port, stop, samples), daemon=True)
        t0 = time.perf_counter()
        if scraper is not None:
            scraper.start()
        try:
            rc = cli.main(["--batch", "on", "--inflight", str(inflight),
                           "--metrics", mpath, "--device", device,
                           *extra, in_path, out])
        finally:
            stop.set()
            if scraper is not None:
                scraper.join(timeout=5.0)
        dt = time.perf_counter() - t0
        assert rc == 0, f"rc={rc}"
        got = {r.name: r.seq for r in fastx.read_fastx(out)}
        idys = []
        for z in zs:
            k = f"{z.movie}/{z.hole}/ccs"
            if k in got:
                idys.append(synth.identity_either(
                    enc.encode(got[k]), z.template))
        final = [json.loads(line) for line in open(mpath)][-1]
        assert final["event"] == "final"
        telemetry = None
        if telemetry_port:
            telemetry = {"port": telemetry_port,
                         "scrapes": len(samples),
                         "eta_accuracy": eta_accuracy(
                             samples, final["elapsed_s"])}
        import jax

        return {
            "telemetry": telemetry,
            "backend": jax.default_backend(),
            "holes_in": n_holes,
            "holes_out": len(got),
            "inflight": inflight,
            "seconds": round(dt, 2),
            "zmws_per_sec": round(len(got) / dt, 3),
            "windows": final["windows"],
            "zmw_windows_per_sec": round(final["windows"] / dt, 1),
            "device_dispatches": final["device_dispatches"],
            "dispatches_per_hole": round(
                final["device_dispatches"] / max(len(got), 1), 2),
            "refine_overflows": final["refine_overflows"],
            "pair_alignments": final["pair_alignments"],
            # prep plane (pipeline/prep_pool.py): the acceptance
            # counter prep_share = driver-blocked prep / wall (<= 0.10
            # bar, ISSUE 8), overlap quality, and the pool gauges.
            # prep_s remains the prep WORK seconds (summed across pool
            # threads when the pool is on)
            "prep_share": final.get("prep_share"),
            "prep_overlap_share": final.get("prep_overlap_share"),
            "prep_blocked_s": final.get("prep_blocked_s"),
            "prep_threads": final.get("prep_threads"),
            "prep_queue_peak": final.get("prep_queue_peak"),
            # padding accounting (SURVEY §7.3 item 2): the fraction of
            # dispatched DP fill cells that belong to real pass-rows at
            # true qlen — what pass/length/Z bucket tuning controls
            "dp_cells_real": final["dp_cells_real"],
            "dp_cells_padded": final["dp_cells_padded"],
            "dp_occupancy": final["dp_occupancy"],
            "dp_round_occupancy": final["dp_round_occupancy"],
            "dp_length_fill": final["dp_length_fill"],
            "dp_pass_fill": final["dp_pass_fill"],
            "dp_z_fill": final["dp_z_fill"],
            # ragged pass-packing counters (None on the --pass-buckets
            # bucketed control): real rows / slab rows dispatched, and
            # holes co-dispatched per slab
            "dp_row_fill": final.get("dp_row_fill"),
            "packed_holes_per_dispatch": final.get(
                "packed_holes_per_dispatch"),
            # compile-lean dispatch counters (r8): distinct packed slab
            # shapes dispatched (the canonical ladder bounds this),
            # compile seconds + share of wall, and the fused multi-chip
            # wave fill
            "distinct_slab_shapes": final.get("distinct_slab_shapes"),
            "compile_s": final.get("compile_s"),
            "compile_share": final.get("compile_share"),
            "fused_waves": final.get("fused_waves"),
            "fused_slot_fill": final.get("fused_slot_fill"),
            "stage_seconds": {k: final[k] for k in
                              ("ingest_s", "prep_s", "compute_s",
                               "write_s")},
            # per-shape-group compile/execute attribution + watchdog
            # verdict (utils/trace.py) — the artifact carries its own
            # evidence that the numbers are chip time, not RPC pings
            "groups": final.get("groups"),
            "degraded": final.get("degraded"),
            # resource gauges (r9): the OOM-ladder postmortems now have
            # a memory signal in every artifact
            "peak_rss_bytes": final.get("peak_rss_bytes"),
            "device_buffer_bytes": final.get("device_buffer_bytes"),
            "holes_filtered": final.get("holes_filtered"),
            "mean_identity": round(float(np.mean(idys)), 5) if idys else None,
        }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--holes", type=int, default=256)
    ap.add_argument("--inflight", type=int, default=64)
    ap.add_argument("--floor-holes", type=int, default=8,
                    help="small-batch contrast run (0 disables)")
    ap.add_argument("--device", default="auto",
                    choices=["auto", "tpu", "cpu"])
    ap.add_argument("--skip-round", action="store_true",
                    help="skip the inline round-metric measurement")
    ap.add_argument("--tlen", default="1000,5000",
                    help="template length range lo,hi (smoke runs can "
                         "shrink this)")
    ap.add_argument("--pass-buckets", default=None,
                    help="forwarded to the CLI: selects the BUCKETED "
                         "grouping control (disables pass packing)")
    ap.add_argument("--slab-rows", type=int, default=None,
                    help="forwarded to the CLI: pass-packing slab row "
                         "budget")
    ap.add_argument("--slab-shape-ladder", type=int, default=None,
                    dest="slab_shape_ladder",
                    help="forwarded to the CLI: canonical tail-slab "
                         "heights per packed shape group [2]")
    ap.add_argument("--no-warmup", action="store_true", dest="no_warmup",
                    help="forwarded to the CLI: disable the AOT warmup "
                         "precompiler (the warmup-on/off A/B arm)")
    ap.add_argument("--prep-threads", type=int, default=None,
                    dest="prep_threads",
                    help="forwarded to the CLI: overlapped prep plane "
                         "width (0 = inline prep, the A/B control) "
                         "[CLI auto]")
    ap.add_argument("--trace", default=None,
                    help="forwarded to the CLI: dispatch flight "
                         "recorder span JSONL (+ Chrome export); the "
                         "latency-floor run gets <PATH>.floor.jsonl")
    ap.add_argument("--stall-timeout", type=float, default=None,
                    dest="stall_timeout",
                    help="forwarded to the CLI: hang-watchdog timeout "
                         "seconds [CLI default 120]")
    ap.add_argument("--telemetry-port", type=int, default=0,
                    dest="telemetry_port",
                    help="serve the live telemetry plane during the "
                         "run AND scrape /progress from this process: "
                         "the artifact embeds the scraped-ETA accuracy "
                         "vs the actual wall (0 = off)")
    ap.add_argument("--json", default=None)
    a = ap.parse_args()
    tlen_lo, tlen_hi = (int(x) for x in a.tlen.split(","))

    from ccsx_tpu.utils.device import resolve_device

    resolve_device(a.device)
    res = {"holes": a.holes, "inflight": a.inflight}
    if not a.skip_round:
        res["round_metric"] = round_metric_inline()
    rng = np.random.default_rng(42)
    extra = (("--pass-buckets", a.pass_buckets)
             if a.pass_buckets else ())
    if a.pass_buckets:
        res["pass_buckets"] = a.pass_buckets
    if a.slab_rows:
        extra = extra + ("--slab-rows", str(a.slab_rows))
        res["slab_rows"] = a.slab_rows
    if a.slab_shape_ladder is not None:
        extra = extra + ("--slab-shape-ladder", str(a.slab_shape_ladder))
        res["slab_shape_ladder"] = a.slab_shape_ladder
    if a.no_warmup:
        extra = extra + ("--no-warmup",)
        res["warmup"] = False
    if a.prep_threads is not None:
        extra = extra + ("--prep-threads", str(a.prep_threads))
        res["prep_threads"] = a.prep_threads
    if a.stall_timeout is not None:
        extra = extra + ("--stall-timeout", str(a.stall_timeout))
        res["stall_timeout"] = a.stall_timeout
    scale_extra = extra
    if a.trace:
        scale_extra = extra + ("--trace", a.trace)
        res["trace"] = a.trace
    if a.telemetry_port:
        res["telemetry_port"] = a.telemetry_port
    res["scale"] = run_scale(a.holes, a.inflight, rng, a.device,
                             tlen_lo, tlen_hi, scale_extra,
                             telemetry_port=a.telemetry_port)
    if not a.skip_round:
        rm = res["round_metric"]["zmw_windows_per_sec"]
        ew = res["scale"]["zmw_windows_per_sec"]
        # the honest bridge: e2e window throughput as a fraction of the
        # round metric.  >= 0.5 means the pipeline is compute-bound at
        # scale (VERDICT r3 item 2's bar); the gap is ingest + prep +
        # write + scheduling.
        res["e2e_over_round"] = round(ew / rm, 3) if rm else None
    if a.floor_holes:
        rng2 = np.random.default_rng(7)
        floor_extra = extra
        if a.trace:
            floor_extra = extra + ("--trace", a.trace + ".floor.jsonl")
        res["latency_floor"] = run_scale(a.floor_holes, a.inflight, rng2,
                                         a.device, tlen_lo, tlen_hi,
                                         floor_extra)
    print(json.dumps(res, indent=1))
    if a.json:
        with open(a.json, "w") as f:
            json.dump(res, f, indent=1)


if __name__ == "__main__":
    main()
