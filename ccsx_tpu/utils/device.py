"""Backend selection.

The runtime environment may register a TPU plugin that is not always
reachable (tunnelled).  Resolve the backend once, up front, with a clean
CPU fallback — a backend-init failure must abort clearly (or fall back),
not surface as a per-hole error storm in the quarantine path.
"""

from __future__ import annotations

import sys


def resolve_device(requested: str = "auto") -> str:
    """Initialize JAX's backend per the request; returns the backend name.

    requested: 'auto' (prefer the default, fall back to CPU),
               'tpu' (require an accelerator), 'cpu' (force CPU).
    """
    import jax

    if requested == "cpu":
        jax.config.update("jax_platforms", "cpu")
        return jax.default_backend()
    try:
        backend = jax.default_backend()
        jax.devices()
        return backend
    except RuntimeError as e:
        if requested == "tpu":
            raise
        print(f"[ccsx-tpu] accelerator unavailable ({e}); using CPU",
              file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")
        return jax.default_backend()
