"""Reference-parity harness (VERDICT Missing #1).

Runs the five BASELINE configs (benchmarks/e2e.py make_input — the
synthetic stand-ins for the baseline plan's workloads) through BOTH
tools — this repo's ``ccsx-tpu`` and a *built reference binary*
(``110allan/ccsx``) — and reports, per hole:

* ``identity_cross``  — global-alignment identity between the two
  tools' consensus sequences (the headline parity number);
* ``identity_tpu`` / ``identity_ref`` — each tool's consensus vs the
  TRUE synthetic template (the oracle the reference never has on real
  data, and the tie-breaker when the tools disagree);
* Q20 yield — for each tool, the fraction of holes whose EMPIRICAL
  per-base error vs the template is <= 1e-2 (Q20-equivalent accuracy).
  The reference emits FASTA only (main.c:714), so predicted-QV yield
  exists for our side alone (``q20_pred_tpu``, from a --fastq run) and
  the cross-tool delta is taken on the empirical yields
  (``q20_yield_delta = ours - reference``).

The reference binary is NOT buildable in this container (its bsalign
dependency clones at build time — no network), so this harness takes
the binary as an argument and is shipped with a STUB-binary test
(tests/test_parity.py) that proves the mechanics run end-to-end the
first day a real ``ccsx`` is available:

    python benchmarks/parity.py --ccsx /path/to/ccsx \
        [--holes 8] [--configs 1,2,3,4,5] [--json parity.json]

Binary contract assumed (SURVEY §2.1 row 1): ``ccsx [options] INPUT
OUTPUT`` with the same short flags (-A -P -m -M -c), FASTA output with
``movie/hole/ccs`` record names.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
sys.path.insert(0, os.path.join(_REPO, "benchmarks"))

from ccsx_tpu import cli                                     # noqa: E402
from ccsx_tpu.io import fastx                                # noqa: E402
from ccsx_tpu.ops import encode as enc                       # noqa: E402
from ccsx_tpu.utils import synth                             # noqa: E402

Q20_ERR = 1e-2   # empirical per-base error at Q20


def _read_consensus(path: str) -> dict:
    """{movie/hole: 2-bit codes} from a FASTA/FASTQ output."""
    out = {}
    for r in fastx.read_fastx(path):
        name = r.name[:-4] if r.name.endswith("/ccs") else r.name
        out[name] = enc.encode(r.seq)
    return out


def _read_quals(path: str) -> dict:
    """{movie/hole: np.uint8 phred} from a FASTQ output."""
    out = {}
    for r in fastx.read_fastx(path):
        if r.qual is None:
            continue
        name = r.name[:-4] if r.name.endswith("/ccs") else r.name
        out[name] = np.frombuffer(r.qual, np.uint8) - 33
    return out


def _identity(a, b) -> float:
    """Orientation-agnostic global identity (consensus strand follows
    the chosen template pass — an arbitrary strand in both tools)."""
    if a is None or b is None or len(a) == 0 or len(b) == 0:
        return 0.0
    return synth.identity_either(a, b)


def _err_rate(cons, template) -> float:
    """Empirical per-base error of a consensus vs the true template
    (best orientation): 1 - identity, on the aligned columns."""
    return max(1.0 - _identity(cons, template), 0.0)


def run_config_parity(config: int, ccsx_bin: str, n_holes: int,
                      seed: int = 0, timeout_s: float = 600.0) -> dict:
    from e2e import make_input

    rng = np.random.default_rng(seed)
    with tempfile.TemporaryDirectory() as tmp:
        in_path, args, zs = make_input(config, n_holes, rng, tmp)
        templates = {f"{z.movie}/{z.hole}": z.template for z in zs}
        ours = os.path.join(tmp, "ours.fa")
        ours_fq = os.path.join(tmp, "ours.fq")
        theirs = os.path.join(tmp, "ref.fa")
        rc = cli.main([*args, "--batch", "on", in_path, ours])
        assert rc == 0, f"ccsx-tpu config {config} rc={rc}"
        # predicted-QV side ride-along (FASTA configs only; the
        # reference has no quality output to mirror)
        rc = cli.main([*args, "--batch", "on", "--fastq", in_path,
                       ours_fq])
        pred_quals = _read_quals(ours_fq) if rc == 0 else {}
        r = subprocess.run([ccsx_bin, *args, in_path, theirs],
                           capture_output=True, text=True,
                           timeout=timeout_s)
        if r.returncode != 0:
            return {"config": config, "error":
                    f"reference binary rc={r.returncode}: "
                    f"{(r.stderr or '')[-500:]}"}
        a = _read_consensus(ours)
        b = _read_consensus(theirs)
        holes = []
        for name, template in templates.items():
            ca, cb = a.get(name), b.get(name)
            if ca is None and cb is None:
                continue   # both tools filtered/skipped it: agreement
            pq = pred_quals.get(name)
            holes.append({
                "hole": name,
                "emitted_tpu": ca is not None,
                "emitted_ref": cb is not None,
                "identity_cross": round(_identity(ca, cb), 5),
                "identity_tpu": round(_identity(ca, template), 5),
                "identity_ref": round(_identity(cb, template), 5),
                "err_tpu": round(_err_rate(ca, template), 6),
                "err_ref": round(_err_rate(cb, template), 6),
                # predicted Q20 yield: fraction of OUR bases called
                # at predicted Q >= 20 (reference: no quals exist)
                "q20_pred_tpu": (round(float((pq >= 20).mean()), 4)
                                 if pq is not None and len(pq) else None),
            })
        n = len(holes)
        q20_tpu = (sum(h["emitted_tpu"] and h["err_tpu"] <= Q20_ERR
                       for h in holes) / n) if n else None
        q20_ref = (sum(h["emitted_ref"] and h["err_ref"] <= Q20_ERR
                       for h in holes) / n) if n else None
        return {
            "config": config,
            "holes": holes,
            "n_holes": n,
            "n_identical": sum(h["identity_cross"] >= 1.0
                               for h in holes),
            "mean_identity_cross": round(float(np.mean(
                [h["identity_cross"] for h in holes])), 5) if n else None,
            "mean_identity_tpu": round(float(np.mean(
                [h["identity_tpu"] for h in holes])), 5) if n else None,
            "mean_identity_ref": round(float(np.mean(
                [h["identity_ref"] for h in holes])), 5) if n else None,
            # empirical Q20-equivalent yield per tool + the delta the
            # VERDICT asked for (ours - reference; positive = we call
            # more holes at Q20-accuracy than the reference does)
            "q20_yield_tpu": round(q20_tpu, 4) if n else None,
            "q20_yield_ref": round(q20_ref, 4) if n else None,
            "q20_yield_delta": (round(q20_tpu - q20_ref, 4)
                                if n else None),
        }


def run_parity(ccsx_bin: str, n_holes: int, configs, seed: int = 0,
               timeout_s: float = 600.0) -> dict:
    if not (os.path.isfile(ccsx_bin)
            and os.access(ccsx_bin, os.X_OK)):
        raise FileNotFoundError(
            f"reference binary {ccsx_bin!r} missing or not executable")
    results = [run_config_parity(c, ccsx_bin, n_holes, seed=seed,
                                 timeout_s=timeout_s) for c in configs]
    usable = [r for r in results if "error" not in r and r["n_holes"]]
    return {
        "ccsx_bin": os.path.abspath(ccsx_bin),
        "holes_per_config": n_holes,
        "seed": seed,
        "configs": results,
        "mean_identity_cross": round(float(np.mean(
            [r["mean_identity_cross"] for r in usable])), 5)
            if usable else None,
        "q20_yield_delta": round(float(np.mean(
            [r["q20_yield_delta"] for r in usable
             if r["q20_yield_delta"] is not None])), 4)
            if usable else None,
    }


def main():
    ap = argparse.ArgumentParser(
        description="Reference-parity harness: run the five BASELINE "
                    "configs through ccsx-tpu AND a built ccsx binary, "
                    "report per-hole identity + Q20-yield deltas")
    ap.add_argument("--ccsx", required=True,
                    help="path to a built reference ccsx binary")
    ap.add_argument("--holes", type=int, default=8)
    ap.add_argument("--configs", default="1,2,3,4,5")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--json", default=None)
    a = ap.parse_args()
    configs = [int(x) for x in a.configs.split(",") if x]
    summary = run_parity(a.ccsx, a.holes, configs, seed=a.seed)
    print(json.dumps(summary, indent=1))
    if a.json:
        with open(a.json, "w") as f:
            json.dump(summary, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
