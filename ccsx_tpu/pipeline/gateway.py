"""Replica fleet plane, part 1: the job spool protocol + the gateway.

PR 13 made the shard RANGE a leased unit of work; PR 15 made one box a
resident multi-tenant server.  This module composes them: N `ccsx-tpu
serve --fleet <spool>` replicas share ONE spool directory as a *lease
domain* (utils/lease.py), and `ccsx-tpu gateway` is the thin balancer
in front of them.

**The spool protocol.**  A job is three files in the shared spool:

  job.<jid>.json    the submission record (input path, overrides,
                    cancel/deadline marks) — created with the
                    EXCLUSIVE write idiom (utils/journal.py
                    ``write_json_exclusive``), which is also how job
                    ids are allocated: the first submitter to link
                    ``job.j00042.json`` owns id j00042, kernel-
                    arbitrated, no coordinator.
  lease.<jid>       the work-in-progress lease (acquire/renew/expire/
                    kill-before-steal — the same audited machinery as
                    fleet ranges), carrying the holder replica's
                    identity and telemetry address.
  done.<jid>.json   the EXCLUSIVE retirement marker: terminal state,
                    rc, output path.  Exactly one of any number of
                    racing finishers commits it — a zombie replica
                    that survived lease expiry cannot double-emit.

A job's state is DERIVED, never stored mutable: done marker present →
its terminal state; lease present → running; cancel mark and no lease
→ cancelling (a scanning replica retires it); else queued.  Replica
death is therefore requeue-by-construction: the lease expires (or the
supervisor reclaims it), the record and the job's journal survive in
the spool, and the next replica to scan acquires and RESUMES it.

**Replica discovery** (the port-collision fix): each replica holds a
slot lease ``lease.r<k>`` (first free slot wins) and serves HTTP on
``base_port + k`` — deterministic — with the ACTUAL bound address
refreshed into the slot record at every heartbeat, so the gateway and
``top`` discover replicas by scanning slot leases, never by probing a
port range.

**The gateway** health-routes on the replicas' existing ``/readyz``:
submissions are accepted (written straight into the spool — the spool
IS the queue, so the gateway never proxies job bytes to a replica)
only while some replica is ready, 503 + Retry-After when all drain,
429 + Retry-After at the spool-depth cap.  ``/metrics`` exposes the
fleet-aggregate autoscale signals (``ccsx_fleet_*``: spool depth,
leases held, per-replica FairWindow pressure) — the numbers an
autoscaler needs to turn the box count into a knob.

No jax import anywhere on this path: the gateway must keep answering
while every replica's accelerator is wedged.
"""

from __future__ import annotations

import json
import os
import re
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import List, Optional, Tuple

from ccsx_tpu.utils import lease as leaselib
from ccsx_tpu.utils.journal import write_json_atomic, write_json_exclusive

JOB_KEY_RE = re.compile(r"^j\d{5,}$")
SLOT_PREFIX = "r"
# terminal states a done marker may carry
MARKER_STATES = ("done", "failed", "cancelled")


# ---- the spool protocol ---------------------------------------------------

def job_record_path(spool: str, jid: str) -> str:
    return os.path.join(spool, f"job.{jid}.json")


def done_marker_path(spool: str, jid: str) -> str:
    return os.path.join(spool, f"done.{jid}.json")


def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path, encoding="utf-8") as f:
            return json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, ValueError):
        return {}


def read_job_record(spool: str, jid: str) -> Optional[dict]:
    return _read_json(job_record_path(spool, jid))


def read_done_marker(spool: str, jid: str) -> Optional[dict]:
    return _read_json(done_marker_path(spool, jid))


def list_job_ids(spool: str) -> List[str]:
    out = []
    try:
        names = os.listdir(spool)
    except OSError:
        return out
    for name in names:
        if (name.startswith("job.") and name.endswith(".json")
                and ".tmp" not in name):
            jid = name[len("job."):-len(".json")]
            if JOB_KEY_RE.match(jid):
                out.append(jid)
    return sorted(out)


def job_view(spool: str, jid: str) -> Optional[dict]:
    """The DERIVED state of one spooled job (see module doc): None for
    an unknown id, else a status dict safe to serve from any process
    (gateway, replica, top) without coordination."""
    rec = read_job_record(spool, jid)
    if rec is None:
        return None
    marker = read_done_marker(spool, jid)
    hold = leaselib.read_lease(spool, jid)
    view = {
        "id": jid,
        "input": rec.get("input"),
        "overrides": rec.get("overrides") or {},
        "submitted_at": rec.get("submitted_at"),
        "cancel": bool(rec.get("cancel")),
        "fanout": rec.get("fanout"),
        "cid": rec.get("cid"),
    }
    if marker:
        view.update({
            "state": marker.get("state") or "done",
            "rc": marker.get("rc"),
            "error": marker.get("error"),
            "output": marker.get("output"),
            "replica": marker.get("replica"),
            "finished_at": marker.get("finished_at"),
        })
    elif hold is not None:
        view.update({"state": "running",
                     "replica": (hold or {}).get("replica")
                     or (hold or {}).get("worker")})
    elif rec.get("cancel"):
        view["state"] = "cancelling"
    else:
        view["state"] = "queued"
    return view


def spool_counts(spool: str) -> dict:
    """One scan of the job queue: the fleet-aggregate autoscale
    numbers (same shape as fleet.queue_state for ranges)."""
    queued = leased = retired = cancelling = 0
    for jid in list_job_ids(spool):
        if os.path.exists(done_marker_path(spool, jid)):
            retired += 1
        elif leaselib.read_lease(spool, jid) is not None:
            leased += 1
        elif (read_job_record(spool, jid) or {}).get("cancel"):
            cancelling += 1
        else:
            queued += 1
    return {"queued": queued, "leased": leased, "retired": retired,
            "cancelling": cancelling}


def submit_job(spool: str, input_path: Optional[str] = None,
               body_stream=None, body_len: int = 0,
               overrides: Optional[dict] = None) -> str:
    """Write one job into the spool; returns the allocated id.

    A streamed body is spooled to a submitter-unique upload file and
    fsynced BEFORE the job record exists (a torn upload must never
    leave an acquirable half-job); the record itself is the id
    allocation — ``write_json_exclusive`` on ``job.<jid>.json`` admits
    exactly one claimant per id, so concurrent submitters (N gateway
    threads, N replicas) allocate disjoint ids with no coordinator."""
    overrides = dict(overrides or {})
    os.makedirs(spool, exist_ok=True)
    if body_stream is not None:
        fmt = str(overrides.get("format") or "").lower() or "bam"
        input_path = os.path.join(
            spool, f"upload.{os.getpid()}.{time.monotonic_ns()}.{fmt}")
        with open(input_path, "wb") as f:
            left = int(body_len)
            while left > 0:
                chunk = body_stream.read(min(left, 1 << 16))
                if not chunk:
                    raise ValueError("short request body")
                f.write(chunk)
                left -= len(chunk)
            f.flush()
            os.fsync(f.fileno())
    if not input_path:
        raise ValueError("job needs an input path or a request body")
    # the fleet-wide correlation id is minted HERE, at submission: the
    # one writer that exists before any replica touches the job.  It
    # rides the spool record -> the job lease -> the fan-out fleet
    # state -> every span/metrics event any process emits for this job
    # (utils/trace.cid_scope), and is what `ccsx-tpu report --fleet`
    # stitches the per-process timelines by.
    cid = f"c{os.urandom(6).hex()}"
    rec = {"version": 1, "input": input_path, "overrides": overrides,
           "submitted_at": time.time(), "submitter": os.getpid(),
           "cid": cid}
    existing = list_job_ids(spool)
    seq = (max((int(j[1:]) for j in existing), default=0)) + 1
    while True:
        jid = f"j{seq:05d}"
        if write_json_exclusive(job_record_path(spool, jid), rec):
            return jid
        seq += 1


def mark_cancel(spool: str, jid: str) -> Tuple[str, bool]:
    """Cross-replica cancel: mark the spool record; the holder's next
    heartbeat renewal observes the mark and aborts through its drain
    guard (the PR 15 blast-radius path).  -> (state, changed);
    KeyError for an unknown id."""
    view = job_view(spool, jid)
    if view is None:
        raise KeyError(jid)
    if view["state"] in MARKER_STATES:
        return view["state"], False
    rec = read_job_record(spool, jid) or {}
    changed = not rec.get("cancel")
    if changed:
        rec["cancel"] = True
        rec["cancel_at"] = time.time()
        write_json_atomic(job_record_path(spool, jid), rec)
    state = "cancelling" if view["state"] != "queued" else "cancelled"
    return state, changed


def mark_deadline(spool: str, jid: str, deadline_s: float) -> bool:
    """Set/tighten a job's wall-clock deadline after submission; the
    holder observes it at its next renewal (same channel as cancel)."""
    rec = read_job_record(spool, jid)
    if rec is None:
        raise KeyError(jid)
    rec.setdefault("overrides", {})["deadline_s"] = float(deadline_s)
    write_json_atomic(job_record_path(spool, jid), rec)
    return True


def retire_job(spool: str, jid: str, state: str, rc: Optional[int],
               replica: str, error: Optional[str] = None,
               output: Optional[str] = None, attempts: int = 0) -> bool:
    """Commit a job's terminal state with the EXCLUSIVE marker fence.
    Returns False when another finisher already retired it — the
    caller (a zombie that survived expiry) must yield to that marker,
    never overwrite it."""
    return write_json_exclusive(done_marker_path(spool, jid), {
        "version": 1, "id": jid, "state": state, "rc": rc,
        "error": error, "output": output, "replica": replica,
        "attempts": attempts, "finished_at": time.time()})


# ---- replica slots (deterministic ports, discovery) -----------------------

def acquire_replica_slot(spool: str, worker: str,
                         extra: Optional[dict] = None,
                         lease_timeout: float = 10.0,
                         max_slots: int = 256) -> Tuple[int, dict]:
    """Claim the first free replica slot ``r<k>`` (expiring stale slot
    leases on the way — a SIGKILLed replica's slot is reusable after
    one timeout).  The slot number IS the port assignment: a replica
    serves on base_port + k, so co-hosted replicas never collide and
    the fleet's ports are knowable from the spool alone."""
    os.makedirs(spool, exist_ok=True)
    for k in range(max_slots):
        key = f"{SLOT_PREFIX}{k}"
        leaselib.expire_lease(spool, key, lease_timeout, kill=False,
                              seq=k)
        rec = leaselib.try_acquire(spool, key, worker,
                                   extra=dict(extra or {}, slot=k),
                                   kind="slot")
        if rec is not None:
            return k, rec
    raise RuntimeError(f"no free replica slot in {spool} "
                       f"(max {max_slots})")


def discover_replicas(spool: str) -> List[dict]:
    """Scan slot leases -> live replica descriptors (the no-guessing
    discovery path for gateway and top)."""
    out = []
    for key, rec in leaselib.list_leases(spool, SLOT_PREFIX):
        if not rec:
            continue  # torn slot lease: a replica died mid-acquire
        out.append({
            "slot": rec.get("slot"),
            "name": rec.get("worker"),
            "addr": rec.get("addr") or "127.0.0.1",
            "port": rec.get("port"),
            "host": rec.get("host"),
            "pid": rec.get("pid"),
            "ready": rec.get("ready"),
            "reason": rec.get("reason"),
            "pressure": rec.get("pressure"),
            "leases": rec.get("leases"),
            "renewed": rec.get("renewed"),
        })
    return out


def replica_endpoints(spool: str) -> List[str]:
    """``addr:port`` for every replica advertising a port — what `top`
    aggregates (any-degraded, like ranks)."""
    return [f"{r['addr']}:{r['port']}" for r in discover_replicas(spool)
            if r.get("port")]


# ---- fleet-aggregate gauges -----------------------------------------------

def fleet_summary(spool: str, replicas: Optional[List[dict]] = None,
                  stale_s: float = 30.0) -> dict:
    """The autoscale signal set: spool/queue depth, leases held, and
    per-replica pressure, aggregated from the spool + slot leases (a
    replica whose heartbeat is older than ``stale_s`` is not counted
    alive).  Rendered as ``ccsx_fleet_*`` by telemetry.
    render_fleet_series — the schema-guarded serve-fleet family."""
    counts = spool_counts(spool)
    if replicas is None:
        replicas = discover_replicas(spool)
    now = time.time()
    alive = [r for r in replicas
             if now - float(r.get("renewed") or 0) < stale_s]
    summary = {
        "fleet_spool_depth": counts["queued"] + counts["cancelling"],
        "fleet_jobs_leased": counts["leased"],
        "fleet_jobs_retired": counts["retired"],
        "fleet_replicas": len(alive),
        "fleet_replicas_ready": sum(1 for r in alive if r.get("ready")),
    }
    per = {}
    for r in alive:
        name = str(r.get("name") or f"slot{r.get('slot')}")
        per[name] = {
            "fleet_window_pressure": float(r.get("pressure") or 0.0),
            "fleet_leases_held": int(r.get("leases") or 0),
        }
    summary["replicas"] = per
    return summary


# ---- the balancer ---------------------------------------------------------

class Gateway:
    """Routing + aggregation state for `ccsx-tpu gateway`.  Readiness
    probes hit each discovered replica's /readyz, cached for
    ``probe_s`` so a scrape storm cannot melt the fleet."""

    def __init__(self, spool: str, max_queue: int = 64,
                 probe_s: float = 1.0, timeout: float = 2.0):
        self.spool = spool
        self.max_queue = max(1, int(max_queue))
        self.probe_s = max(0.05, float(probe_s))
        self.timeout = timeout
        self._lock = threading.Lock()
        self._probed_at = 0.0
        self._probed: List[dict] = []

    def replicas(self) -> List[dict]:
        with self._lock:
            if time.monotonic() - self._probed_at < self.probe_s:
                return list(self._probed)
        reps = discover_replicas(self.spool)
        for r in reps:
            r["reachable"] = False
            if not r.get("port"):
                r["ready"] = False
                continue
            url = f"http://{r['addr']}:{r['port']}/readyz"
            try:
                with urllib.request.urlopen(
                        url, timeout=self.timeout) as resp:
                    body = json.loads(resp.read() or b"{}")
                r["reachable"] = True
            except urllib.error.HTTPError as e:
                # a draining/warming replica answers 503 WITH a body:
                # reachable, just not routable
                try:
                    body = json.loads(e.read() or b"{}")
                except (OSError, ValueError):
                    body = {}
                body.setdefault("ready", False)
                body.setdefault("reason", f"http {e.code}")
                r["reachable"] = True
            except (OSError, ValueError):
                body = {"ready": False, "reason": "unreachable"}
            r["ready"] = bool(body.get("ready"))
            r["reason"] = body.get("reason")
        with self._lock:
            self._probed = reps
            self._probed_at = time.monotonic()
        return list(reps)

    def readiness(self) -> Tuple[bool, str]:
        reps = self.replicas()
        if not reps:
            return False, "no replicas"
        ready = [r for r in reps if r.get("ready")]
        if not ready:
            return False, "all replicas draining or unready"
        return True, f"{len(ready)}/{len(reps)} replicas ready"

    def summary(self) -> dict:
        return fleet_summary(self.spool, replicas=self.replicas())

    def fleet_hist(self) -> dict:
        """Fleet-merged latency histograms: every reachable replica's
        /progress snapshot carries its ``hist`` families; per-`le`
        counts are SUMMED per (family, label) — quantiles do not
        compose, buckets do (utils/metrics.merge_hist).  The merged
        set is what the gateway's /metrics exposes next to the
        ccsx_fleet_* autoscale gauges, so one scrape sees fleet-wide
        queue-wait/job-wall distributions and their SLO burn."""
        from ccsx_tpu.utils.metrics import merge_hist

        per: dict = {}
        for r in self.replicas():
            if not (r.get("reachable") and r.get("port")):
                continue
            url = f"http://{r['addr']}:{r['port']}/progress"
            try:
                with urllib.request.urlopen(
                        url, timeout=self.timeout) as resp:
                    snap = json.loads(resp.read() or b"{}")
            except (OSError, ValueError):
                continue
            hist = snap.get("hist")
            if not isinstance(hist, dict):
                continue
            for fam, series in hist.items():
                if not isinstance(series, dict):
                    continue
                for label, s in series.items():
                    per.setdefault(fam, {}).setdefault(
                        label, []).append(s)
        return {fam: {label: merge_hist(snaps)
                      for label, snaps in series.items()}
                for fam, series in per.items()}

    def submit(self, input_path=None, body_stream=None, body_len=0,
               overrides=None) -> str:
        ready, reason = self.readiness()
        if not ready:
            raise NotReady(reason)
        counts = spool_counts(self.spool)
        depth = counts["queued"] + counts["cancelling"]
        if depth >= self.max_queue:
            raise SpoolFull(
                f"spool depth cap ({depth}/{self.max_queue})")
        return submit_job(self.spool, input_path=input_path,
                          body_stream=body_stream, body_len=body_len,
                          overrides=overrides)


class NotReady(Exception):
    """No replica can take traffic (HTTP 503 + Retry-After)."""


class SpoolFull(Exception):
    """Spool depth cap reached (HTTP 429 + Retry-After)."""


# ---- the HTTP layer -------------------------------------------------------

def _gateway_handler():
    from ccsx_tpu.utils import telemetry

    class _GatewayHandler(telemetry._Handler):
        server_version = "ccsx-tpu-gateway"

        def _gw(self) -> Gateway:
            return self.server.ccsx_gateway  # type: ignore

        def _send_json(self, code: int, obj, extra=None) -> None:
            data = json.dumps(obj, default=str).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            for k, v in (extra or {}).items():
                self.send_header(k, str(v))
            self.end_headers()
            self.wfile.write(data)

        def _send_file(self, path: str) -> None:
            try:
                size = os.path.getsize(path)
                f = open(path, "rb")
            except OSError as e:
                self._send_json(404, {"error": f"no output: {e}"})
                return
            with f:
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; charset=utf-8")
                self.send_header("Content-Length", str(size))
                self.end_headers()
                while True:
                    chunk = f.read(1 << 16)
                    if not chunk:
                        break
                    self.wfile.write(chunk)

        def do_GET(self):  # noqa: N802
            from ccsx_tpu.utils import telemetry

            gw = self._gw()
            path, _, _q = self.path.partition("?")
            try:
                if path == "/healthz":
                    reps = gw.replicas()
                    self._send_json(200, {
                        "status": "alive", "replicas": len(reps),
                        "ready": sum(1 for r in reps if r.get("ready")),
                        **spool_counts(gw.spool)})
                elif path == "/readyz":
                    ready, reason = gw.readiness()
                    self._send_json(200 if ready else 503,
                                    {"ready": ready, "reason": reason})
                elif path == "/metrics":
                    body = telemetry.render_fleet_series(gw.summary())
                    hist = gw.fleet_hist()
                    hlines = (telemetry.hist_lines(hist)
                              + telemetry.slo_burn_lines(hist))
                    if hlines:
                        body += "\n".join(hlines) + "\n"
                    self._send(200, body,
                               "text/plain; version=0.0.4; "
                               "charset=utf-8")
                elif path == "/replicas":
                    self._send_json(200, {"replicas": gw.replicas()})
                elif path == "/jobs":
                    jobs = [job_view(gw.spool, jid)
                            for jid in list_job_ids(gw.spool)]
                    self._send_json(200, {"jobs": jobs})
                elif path.startswith("/jobs/"):
                    parts = path.split("/")
                    view = job_view(gw.spool, parts[2])
                    if view is None:
                        self._send_json(404, {"error": "unknown job"})
                    elif len(parts) == 3:
                        self._send_json(200, view)
                    elif len(parts) == 4 and parts[3] == "output":
                        if view["state"] != "done":
                            self._send_json(
                                409, {"error": "job not done",
                                      "state": view["state"]})
                        else:
                            self._send_file(view.get("output") or "")
                    else:
                        self._send_json(404, {"error": "unknown path"})
                else:
                    self._send_json(404, {"error": "unknown path"})
            except (BrokenPipeError, ConnectionResetError):
                pass

        def do_POST(self):  # noqa: N802
            gw = self._gw()
            path, _, query = self.path.partition("?")
            try:
                if path != "/jobs":
                    self._send_json(404, {"error": "unknown path"})
                    return
                import urllib.parse

                params = {k: v[-1] for k, v in
                          urllib.parse.parse_qs(query).items()}
                length = int(self.headers.get("Content-Length") or 0)
                ctype = (self.headers.get("Content-Type") or
                         "").split(";")[0].strip().lower()
                try:
                    if ctype == "application/json":
                        raw = self.rfile.read(length)
                        body = json.loads(raw or b"{}")
                        if not isinstance(body, dict):
                            raise ValueError(
                                "JSON body must be an object")
                        params.update(body)
                        input_path = params.pop("input", None)
                        jid = gw.submit(input_path=input_path,
                                        overrides=params)
                    else:
                        jid = gw.submit(body_stream=self.rfile,
                                        body_len=length,
                                        overrides=params)
                except NotReady as e:
                    self._send_json(503, {"error": str(e)},
                                    extra={"Retry-After": 5})
                    return
                except SpoolFull as e:
                    self._send_json(429, {"error": str(e)},
                                    extra={"Retry-After": 5})
                    return
                except (ValueError, OSError) as e:
                    self._send_json(400, {"error": str(e)})
                    return
                self._send_json(201, {"id": jid, "state": "queued",
                                      "status": f"/jobs/{jid}",
                                      "output": f"/jobs/{jid}/output"})
            except (BrokenPipeError, ConnectionResetError):
                pass

        def do_DELETE(self):  # noqa: N802
            gw = self._gw()
            path, _, _q = self.path.partition("?")
            try:
                parts = path.split("/")
                if len(parts) != 3 or parts[1] != "jobs":
                    self._send_json(404, {"error": "unknown path"})
                    return
                try:
                    state, changed = mark_cancel(gw.spool, parts[2])
                except KeyError:
                    self._send_json(404, {"error": "unknown job"})
                    return
                self._send_json(200 if changed else 409,
                                {"id": parts[2], "state": state,
                                 "cancelled": changed})
            except (BrokenPipeError, ConnectionResetError):
                pass

    return _GatewayHandler


# ---- the subcommand -------------------------------------------------------

def gateway_main(argv) -> int:
    """`ccsx-tpu gateway`: the thin balancer over one serve-fleet
    spool.  No jax, no compute — it keeps routing while every
    replica's backend is wedged."""
    import argparse

    from ccsx_tpu.utils import telemetry
    from ccsx_tpu.utils.drain import DrainGuard
    from ccsx_tpu.utils.metrics import Metrics

    ap = argparse.ArgumentParser(
        prog="ccsx-tpu gateway",
        description="Balancer/aggregator for `ccsx-tpu serve --fleet` "
                    "replicas sharing one job spool: health-routed "
                    "submission, fleet job API, ccsx_fleet_* autoscale "
                    "gauges.")
    ap.add_argument("--spool", required=True,
                    help="the shared fleet spool directory (same "
                         "--fleet the replicas serve)")
    ap.add_argument("--port", type=int, default=8850,
                    help="HTTP port (auto-bumps when taken; 0 = "
                         "ephemeral) [8850]")
    ap.add_argument("--gw-host", default="",
                    help="bind host [CCSX_TELEMETRY_HOST or 0.0.0.0]")
    ap.add_argument("--max-queue", type=int, default=64,
                    help="spool-depth cap; submissions beyond it get "
                         "429 + Retry-After [64]")
    ap.add_argument("--probe", type=float, default=1.0,
                    help="replica /readyz probe cache seconds [1.0]")
    a = ap.parse_args(argv)
    gw = Gateway(a.spool, max_queue=a.max_queue, probe_s=a.probe)
    guard = DrainGuard.install()
    try:
        srv = telemetry.TelemetryServer(
            Metrics(verbose=0, stream=None), a.port, host=a.gw_host,
            handler=_gateway_handler(),
            attrs={"ccsx_gateway": gw, "ccsx_ready": gw.readiness})
    except OSError as e:
        print(f"Error: gateway: {e}", file=sys.stderr)
        guard.restore()
        return 1
    print(f"[ccsx-tpu] gateway: http://{srv.host}:{srv.port} "
          f"(spool {a.spool}; POST /jobs, /readyz, /metrics, "
          "/replicas)", file=sys.stderr)
    try:
        while not guard.requested:
            time.sleep(0.2)
    finally:
        srv.close()
        guard.restore()
    return 0
