"""Jax-free direct driver for the native layer's threaded paths.

Exists because pytest under TSAN blows the time budget before collecting
a single test: the jax import in tests/conftest.py runs 10-20x slower
instrumented (R10_NOTES.md).  This script imports only numpy + the
ccsx_tpu IO/native modules and drives every lock/condvar/atomic path the
native layer has, so the sanitizer battery is:

    make -C ccsx_tpu/native tsan
    LD_PRELOAD=$(g++ -print-file-name=libtsan.so) \
      TSAN_OPTIONS=exitcode=66 CCSX_BGZF_THREADS=4 \
      python benchmarks/tsan_native_drive.py
    make -C ccsx_tpu/native clean all   # ALWAYS restore (see R10_NOTES.md)

(Also valid under ASAN with ASAN_OPTIONS=detect_leaks=0.)  Paths driven:

- BGZF-MT block-parallel inflate (worker pool + prefetch producer thread
  + consumer) over a 240-record BGZF BAM at CCSX_BGZF_THREADS=4;
- the salvage resync path: two corrupt-payload BGZF blocks classified by
  the PRODUCER while the consumer polls the atomic event counter;
- the budget-exempt bgzf_missing_eof atomic (EOF marker stripped);
- the plain (non-prefetch) native streamer as the single-thread oracle;
- 500 records through the async ordered NativeFastaWriter (fwrite on a
  C++ thread off the GIL);
- encode/revcomp round-trips.

rc 0 + "OK" line = clean; any TSAN warning fails via exitcode=66.
"""

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from ccsx_tpu.config import CcsConfig          # noqa: E402
from ccsx_tpu.io import bam as bam_mod         # noqa: E402
from ccsx_tpu.native import available, build_error  # noqa: E402
from ccsx_tpu.native.io import (encode_native, revcomp_codes_native,  # noqa: E402
                                stream_zmws_native, stream_zmws_prefetch,
                                NativeFastaWriter)

BGZF_MAGIC = b"\x1f\x8b\x08\x04"
# static BGZF EOF marker (SAM spec 4.1.2): an empty member, 28 bytes
BGZF_EOF = bytes.fromhex(
    "1f8b08040000000000ff0600424302001b0003000000000000000000")


def _mk_records(n=240, seqlen=1000):
    rng = np.random.default_rng(20)
    out = []
    for i in range(n):
        seq = rng.choice(list(b"ACGT"), seqlen).astype(np.uint8).tobytes()
        # 6 subreads per hole clears the count filter (>= min_fulllen_count+2)
        out.append((f"mv/{i // 6}/{i}_{i + seqlen}", seq, b"\x20" * seqlen))
    return out


def _corrupt_two_blocks(raw: bytes) -> bytes:
    offs = []
    p = raw.find(BGZF_MAGIC)
    while p != -1:
        offs.append(p)
        p = raw.find(BGZF_MAGIC, p + 1)
    assert len(offs) >= 4, f"need a multi-block BGZF, got {len(offs)} members"
    buf = bytearray(raw)
    for o in (offs[1], offs[len(offs) // 2]):
        buf[o + 40] ^= 0xFF  # inside the deflate payload -> CRC mismatch
    return bytes(buf)


def main() -> int:
    assert available(), f"native library unavailable: {build_error()}"
    cfg = CcsConfig(min_subread_len=1, is_bam=True)
    cfg_s = CcsConfig(min_subread_len=1, is_bam=True, salvage=True)
    recs = _mk_records()
    n_holes = len({r[0].split("/")[1] for r in recs})

    with tempfile.TemporaryDirectory() as td:
        clean = os.path.join(td, "clean.bam")
        bam_mod.write_bam(clean, recs, bgzf=True)
        raw = open(clean, "rb").read()

        # 1) single-thread oracle, then the prefetch/pool stack on the
        #    same clean file: same holes either way
        plain = [z.hole for z in stream_zmws_native(clean, cfg)]
        pool = [z.hole for z in stream_zmws_prefetch(clean, cfg)]
        assert plain == pool and len(plain) == n_holes, (
            len(plain), len(pool), n_holes)

        # 2) salvage resync through the prefetch stack: producer
        #    classifies the two bad blocks + books the atomic event
        #    counter while the consumer polls it per yield
        dirty = os.path.join(td, "dirty.bam")
        with open(dirty, "wb") as f:
            f.write(_corrupt_two_blocks(raw))
        salvaged = [z.hole for z in stream_zmws_prefetch(dirty, cfg_s)]
        assert 0 < len(salvaged) < n_holes + 1, len(salvaged)

        # 3) the budget-exempt bgzf_missing_eof atomic: strip the EOF
        #    marker, stream with salvage on
        noeof = os.path.join(td, "noeof.bam")
        assert raw.endswith(BGZF_EOF), "writer did not emit the EOF marker"
        with open(noeof, "wb") as f:
            f.write(raw[: -len(BGZF_EOF)])
        ne = [z.hole for z in stream_zmws_prefetch(noeof, cfg_s)]
        assert ne == plain, (len(ne), len(plain))

        # 4) async ordered writer: 500 records, fwrite off the GIL
        out = os.path.join(td, "w.fa")
        w = NativeFastaWriter(out)
        for i in range(500):
            w.put(f"ccs/{i}", b"ACGTAC" * 50)
        w.close()
        assert open(out, "rb").read().count(b">") == 500

        # 5) encode/revcomp round-trips
        seq = b"ACGTNACGT" * 100
        codes = encode_native(seq)
        rc2 = revcomp_codes_native(revcomp_codes_native(codes))
        assert np.array_equal(codes, rc2)

    print(f"OK: {len(plain)} holes plain==prefetch, "
          f"{len(salvaged)} salvaged past 2 corrupt blocks, "
          f"missing-EOF stream intact, 500 async writes, "
          f"encode/revcomp round-trip")
    return 0


if __name__ == "__main__":
    sys.exit(main())
