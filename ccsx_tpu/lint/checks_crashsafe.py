"""bare-write: crash-domain writes that bypass the atomic helpers.

The r13/r16 invariant: any file a lease/journal/spool/fleet domain
reads back after a SIGKILL must be published atomically —
``write_json_atomic`` (tmp + fsync + ``os.replace`` + dir fsync),
``write_json_exclusive`` (``os.link`` O_EXCL publish), or an
``os.open(..., O_CREAT | O_EXCL)`` acquire.  A bare
``open(path, "w")`` + ``json.dump`` in those domains is a torn-state
bug waiting for the chaos suite to find it.

Rule: inside a crash-domain context — the domain modules by basename
(fleet/gateway/serve/lease/journal/blackbox), or any file when the
path expression itself names a domain artifact (lease/journal/spool/
fleet/done-marker/job-record) — flag ``open`` with a ``w``/``x``/``a``
mode and ``json.dump``, UNLESS the enclosing function also performs
the atomic publish (``write_json_atomic``/``write_json_exclusive``/
``os.replace``/``os.rename``/``os.link``/``O_EXCL``).  The exemption
is the idiom itself: a staged write followed by an atomic commit in
the same function IS the crash-safe pattern (journal.py's helpers,
gateway's upload-then-admit submit).
"""

from __future__ import annotations

import ast
import re
from pathlib import PurePosixPath
from typing import Iterable, List, Optional, Sequence

from ccsx_tpu.lint.core import Finding

CHECK = "bare-write"

DOMAIN_BASENAMES = {"fleet.py", "gateway.py", "serve.py", "lease.py",
                    "journal.py", "blackbox.py"}
MARKER_RE = re.compile(r"lease|journal|spool|fleet|done_marker|job_record",
                       re.I)
ATOMIC_NAMES = {"write_json_atomic", "write_json_exclusive",
                "replace", "rename", "link"}

MESSAGE = ("bare write in a crash domain without an atomic publish in "
           "the same function — a SIGKILL here leaves a torn file; use "
           "utils.journal.write_json_atomic / write_json_exclusive or "
           "stage to a tmp and os.replace")


def _call_name(node: ast.Call) -> Optional[str]:
    if isinstance(node.func, ast.Name):
        return node.func.id
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    return None


def _open_write_mode(node: ast.Call) -> bool:
    """builtin open() with a mode literal containing w/x/a."""
    if _call_name(node) != "open":
        return False
    if isinstance(node.func, ast.Attribute):
        # os.open has flag ints, not mode strings; gzip.open etc. on a
        # domain artifact would be its own policy — out of scope here
        base = node.func.value
        if not (isinstance(base, ast.Name) and base.id == "builtins"):
            return False
    mode = None
    if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
        mode = node.args[1].value
    for kw in node.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    return isinstance(mode, str) and any(c in mode for c in "wxa")


def _is_json_dump(node: ast.Call) -> bool:
    return (isinstance(node.func, ast.Attribute)
            and node.func.attr == "dump"
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == "json")


def _has_atomic_publish(fn: ast.AST) -> bool:
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Call) and _call_name(sub) in ATOMIC_NAMES:
            return True
        if isinstance(sub, ast.Attribute) and sub.attr == "O_EXCL":
            return True
        if isinstance(sub, ast.Name) and sub.id == "O_EXCL":
            return True
    return False


def _path_arg_text(node: ast.Call) -> str:
    if not node.args:
        return ""
    try:
        return ast.unparse(node.args[0])
    except Exception:
        return ""


def _line_text(lines: Sequence[str], lineno: int) -> str:
    return lines[lineno - 1].strip() if 1 <= lineno <= len(lines) else ""


def check(tree: ast.AST, src: str, lines: Sequence[str],
          relpath: str) -> Iterable[Finding]:
    domain_file = PurePosixPath(relpath).name in DOMAIN_BASENAMES
    out: List[Finding] = []

    def visit(node: ast.AST, fn: Optional[ast.AST]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn = node
        for child in ast.iter_child_nodes(node):
            visit(child, fn)
        if not isinstance(node, ast.Call):
            return
        flagged = False
        if _open_write_mode(node):
            flagged = domain_file or bool(
                MARKER_RE.search(_path_arg_text(node)))
        elif _is_json_dump(node):
            flagged = domain_file
        if not flagged:
            return
        scope = fn if fn is not None else tree
        if _has_atomic_publish(scope):
            return
        out.append(Finding(CHECK, relpath, node.lineno, node.col_offset,
                           MESSAGE, _line_text(lines, node.lineno)))

    visit(tree, None)
    return out
