"""Seeded corruption fuzzer: hostile-input mutants through the full CLI.

The salvage invariant (ISSUE 10): for any corrupted input, the run must
*never crash or hang*, its rc must come from the pinned exit-code
taxonomy, and under ``--salvage`` every hole whose bytes are UNDAMAGED
must emit byte-identical to the clean run — damage degrades per-hole,
never per-file.  This harness makes that claim testable:

* ``build_corpus`` writes a clean synthetic corpus per format (BGZF
  BAM / FASTA / FASTQ) and records the byte LAYOUT — each hole's span
  in the record stream, plus the BGZF block table for BAM — so a
  mutation's blast radius can be mapped to the exact hole set it may
  legally affect.
* ``make_mutant`` applies one seeded mutation — bit flip, truncation,
  or zero-run, at container-random, block, record, or field
  granularity — and returns the damaged-hole set via the layout:
  text formats map the mutated range onto hole spans directly; BGZF
  maps it through the block table (a damaged block damages every hole
  whose records overlap that block's inflated bytes; a truncation
  damages everything from the first affected block on).
* ``run_mutant`` drives the mutant through the full CLI and
  ``check_invariant`` enforces the contract: rc from the taxonomy and,
  with salvage on, per-hole byte identity for every undamaged hole.

The fast deterministic slice runs in tier-1
(tests/test_corrupt_fuzz.py, `make fuzz`); the full >= 50-mutants-per-
format sweep is the `slow` mark and this CLI:

    python benchmarks/corrupt.py --seed 0 --mutants 50 \
        --json benchmarks/corrupt_rNN.json
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import struct
import sys
import tempfile
import time
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from ccsx_tpu import cli                                     # noqa: E402
from ccsx_tpu.io import bam as bam_mod                       # noqa: E402
from ccsx_tpu.ops import encode as enc                       # noqa: E402
from ccsx_tpu.utils import synth                             # noqa: E402

FORMATS = ("bam", "fasta", "fastq")

# rcs the taxonomy allows a corrupted-input run to exit with
# (exitcodes.py): 0 = completed (possibly degraded/salvaged),
# 1 = clean fail-fast refusal, 2 = failed-hole budget
ALLOWED_RCS = (0, 1, 2)


@dataclasses.dataclass
class Corpus:
    fmt: str
    path: str
    data: bytes
    # hole name "movie/hole" -> (lo, hi) byte span.  Text formats: the
    # file itself; BAM: the INFLATED record stream (4-byte length ints
    # included), mapped through `blocks`
    hole_spans: Dict[str, Tuple[int, int]]
    # BGZF only: (c0, c1, u0, u1) per block — compressed file span ->
    # inflated stream span
    blocks: List[Tuple[int, int, int, int]]


# ---- corpus builders -----------------------------------------------------


def _zmws(rng, holes: int, template_len: int, n_passes: int):
    return [synth.make_zmw(rng, template_len=template_len,
                           n_passes=n_passes, movie="mv",
                           hole=str(100 + h)) for h in range(holes)]


def build_corpus(tmp: str, fmt: str, rng, holes: int = 4,
                 template_len: int = 300, n_passes: int = 5) -> Corpus:
    zs = _zmws(rng, holes, template_len, n_passes)
    if fmt == "bam":
        recs = []
        for z in zs:
            for name, p in zip(z.names, z.passes):
                seq = enc.decode(p).encode()
                recs.append((name, seq, b"I" * len(seq)))
        path = os.path.join(tmp, "in.bam")
        bam_mod.write_bam(path, recs, bgzf=True)
        data = open(path, "rb").read()
        blocks = _bgzf_blocks(data)
        spans = _bam_hole_spans(blocks, data)
        return Corpus(fmt, path, data, spans, blocks)
    out = []
    spans: Dict[str, Tuple[int, int]] = {}
    off = 0
    for z in zs:
        start = off
        for name, p in zip(z.names, z.passes):
            seq = enc.decode(p).encode()
            if fmt == "fasta":
                rec = b">%s\n%s\n" % (name.encode(), seq)
            else:
                rec = b"@%s\n%s\n+\n%s\n" % (name.encode(), seq,
                                             b"I" * len(seq))
            out.append(rec)
            off += len(rec)
        spans[f"{z.movie}/{z.hole}"] = (start, off)
    path = os.path.join(tmp, "in." + ("fa" if fmt == "fasta" else "fq"))
    data = b"".join(out)
    with open(path, "wb") as f:
        f.write(data)
    return Corpus(fmt, path, data, spans, [])


def _bgzf_blocks(data: bytes) -> List[Tuple[int, int, int, int]]:
    blocks = []
    c = u = 0
    while c < len(data):
        (xlen,) = struct.unpack_from("<H", data, c + 10)
        (bs,) = struct.unpack_from("<H", data, c + 16)   # BC is first
        bsize = bs + 1
        (isize,) = struct.unpack_from("<I", data, c + bsize - 4)
        blocks.append((c, c + bsize, u, u + isize))
        c += bsize
        u += isize
    return blocks


def _bam_hole_spans(blocks, data: bytes) -> Dict[str, Tuple[int, int]]:
    import zlib

    inflated = b"".join(
        zlib.decompress(data[c0 + 12 + struct.unpack_from(
            "<H", data, c0 + 10)[0]:c1 - 8], -15)
        for c0, c1, _, _ in blocks)
    # walk header then records, grouping spans by hole
    (l_text,) = struct.unpack_from("<i", inflated, 4)
    off = 8 + l_text
    (n_ref,) = struct.unpack_from("<i", inflated, off)
    off += 4
    for _ in range(n_ref):
        (l_name,) = struct.unpack_from("<i", inflated, off)
        off += 8 + l_name
    spans: Dict[str, Tuple[int, int]] = {}
    while off < len(inflated):
        start = off
        (bs,) = struct.unpack_from("<i", inflated, off)
        lrn = inflated[off + 12]
        name = inflated[off + 36:off + 36 + lrn - 1].decode()
        off += 4 + bs
        hole = "/".join(name.split("/")[:2])
        lo, hi = spans.get(hole, (start, start))
        spans[hole] = (min(lo, start), off)
    return spans


# ---- mutation + damage mapping -------------------------------------------


@dataclasses.dataclass
class Mutation:
    kind: str          # flip | truncate | zeros
    lo: int            # file-coordinate damage range [lo, hi)
    hi: int
    label: str


def make_mutant(corpus: Corpus, rng) -> Tuple[bytes, Mutation]:
    """One seeded mutation at a seeded granularity.  Returns the mutant
    bytes + the Mutation (file coordinates, for damaged_holes)."""
    data = bytearray(corpus.data)
    kind = ("flip", "truncate", "zeros")[int(rng.integers(3))]
    gran = ("anywhere", "record", "field")[int(rng.integers(3))]
    if gran == "anywhere" or not corpus.hole_spans:
        pos = int(rng.integers(0, len(data)))
    else:
        # inside a (seeded) hole's span — record/field granularity.
        # BAM spans are in inflated coordinates: map onto a compressed
        # offset inside one of the hole's covering blocks
        hole = sorted(corpus.hole_spans)[
            int(rng.integers(len(corpus.hole_spans)))]
        lo, hi = corpus.hole_spans[hole]
        upos = int(rng.integers(lo, hi))
        if corpus.fmt == "bam":
            blk = next(b for b in corpus.blocks if b[2] <= upos < b[3])
            # field granularity: aim at the block's payload start (the
            # deflate stream — any hit corrupts the whole block, which
            # is exactly BGZF's blast radius); record: anywhere in it
            c0, c1 = blk[0], blk[1]
            pos = int(rng.integers(c0 + 18, c1)) if gran == "record" \
                else int(rng.integers(c0, c0 + 18))
        else:
            pos = upos
    if kind == "flip":
        data[pos] ^= 1 << int(rng.integers(0, 8))
        lo_hi = (pos, pos + 1)
    elif kind == "truncate":
        pos = max(1, pos)
        del data[pos:]
        lo_hi = (pos, len(corpus.data))
    else:
        n = int(rng.integers(4, 64))
        data[pos:pos + n] = b"\x00" * min(n, len(data) - pos)
        lo_hi = (pos, min(pos + n, len(corpus.data)))
    return bytes(data), Mutation(kind, lo_hi[0], lo_hi[1],
                                 f"{kind}@{lo_hi[0]}-{lo_hi[1]}:{gran}")


def damaged_holes(corpus: Corpus, mut: Mutation) -> Set[str]:
    """The hole set a mutation may legally affect.  Every hole OUTSIDE
    this set must emit byte-identical to the clean run under
    --salvage."""
    lo, hi = mut.lo, mut.hi
    if mut.kind == "truncate":
        hi = len(corpus.data)
    if corpus.fmt == "bam":
        # damaged compressed range -> union of affected blocks'
        # inflated spans (a corrupt block is dropped whole); a
        # truncation additionally kills everything after its block
        ulo = uhi = None
        for c0, c1, u0, u1 in corpus.blocks:
            if c0 < hi and lo < c1:
                ulo = u0 if ulo is None else min(ulo, u0)
                uhi = u1 if uhi is None else max(uhi, u1)
        if ulo is None:
            return set()
        if mut.kind == "truncate":
            uhi = corpus.blocks[-1][3]
        return {h for h, (s0, s1) in corpus.hole_spans.items()
                if s0 < uhi and ulo < s1}
    return {h for h, (s0, s1) in corpus.hole_spans.items()
            if s0 < hi and lo < s1}


# ---- the CLI drive + invariant -------------------------------------------


def _cli_args(corpus_fmt: str, in_path: str, out: str,
              salvage: bool, extra=()) -> list:
    args = ["-m", "100", "--batch", "on",
            "--dispatch-deadline", "30", "--stall-timeout", "15"]
    if corpus_fmt != "bam":
        args.append("-A")
    if salvage:
        args.append("--salvage")
    return [*args, *extra, in_path, out]


def by_hole(fasta_bytes: bytes) -> Dict[str, str]:
    """Output FASTA -> {"movie/hole": record text} (names are
    movie/hole/ccs)."""
    out = {}
    for chunk in fasta_bytes.decode(errors="replace").split(">")[1:]:
        name = chunk.split("\n", 1)[0]
        out["/".join(name.split("/")[:2])] = chunk
    return out


def run_mutant(corpus: Corpus, mut_bytes: bytes, mut: Mutation,
               tmp: str, ref: Dict[str, str], i: int,
               salvage: bool) -> dict:
    ext = {"bam": "bam", "fasta": "fa", "fastq": "fq"}[corpus.fmt]
    mp = os.path.join(tmp, f"mut{i}.{ext}")
    with open(mp, "wb") as f:
        f.write(mut_bytes)
    out = os.path.join(tmp, f"out{i}.fa")
    t0 = time.monotonic()
    rc = cli.main(_cli_args(corpus.fmt, mp, out, salvage))
    wall = time.monotonic() - t0
    got = by_hole(open(out, "rb").read()) if os.path.exists(out) else {}
    dam = damaged_holes(corpus, mut)
    bad = []
    if rc not in ALLOWED_RCS:
        bad.append(f"rc {rc} outside the pinned taxonomy")
    if salvage:
        if rc != 0:
            bad.append(f"salvage run exited rc {rc}")
        for h in ref:
            if h in dam:
                continue
            if got.get(h) != ref[h]:
                bad.append(f"undamaged hole {h} not byte-identical")
    return {"i": i, "mutation": mut.label, "salvage": salvage,
            "rc": rc, "wall_s": round(wall, 2),
            "damaged": sorted(dam), "emitted": len(got),
            "ok": not bad, "bad": bad}


def run_sweep(seed: int, mutants: int, formats=FORMATS,
              salvage_share: float = 0.7, holes: int = 4,
              tmp: Optional[str] = None) -> dict:
    """``mutants`` seeded mutants per format through the full CLI;
    ~``salvage_share`` of them with --salvage (full invariant), the
    rest fail-fast (rc taxonomy only).  Returns the summary dict;
    ``summary["ok"]`` is the verdict."""
    rng = np.random.default_rng(seed)
    own = tmp is None
    tmp = tmp or tempfile.mkdtemp(prefix="ccsx_corrupt_")
    results = []
    t0 = time.monotonic()
    try:
        for fmt in formats:
            corpus = build_corpus(tmp, fmt, rng, holes=holes)
            refp = os.path.join(tmp, f"ref_{fmt}.fa")
            rc = cli.main(_cli_args(fmt, corpus.path, refp, False))
            assert rc == 0, f"clean {fmt} reference run failed rc={rc}"
            ref = by_hole(open(refp, "rb").read())
            # zero-overhead-when-healthy: salvage on the CLEAN input
            svp = os.path.join(tmp, f"ref_{fmt}_sv.fa")
            rc = cli.main(_cli_args(fmt, corpus.path, svp, True))
            clean_ok = (rc == 0 and open(svp, "rb").read()
                        == open(refp, "rb").read())
            results.append({"i": -1, "mutation": f"{fmt}:clean",
                            "salvage": True, "rc": rc, "wall_s": 0,
                            "damaged": [], "emitted": len(ref),
                            "ok": clean_ok,
                            "bad": [] if clean_ok else
                            ["salvage-on clean run not byte-identical"]})
            for i in range(mutants):
                mut_bytes, mut = make_mutant(corpus, rng)
                salvage = rng.random() < salvage_share
                r = run_mutant(corpus, mut_bytes, mut, tmp, ref, i,
                               salvage)
                r["fmt"] = fmt
                results.append(r)
    finally:
        if own:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
    bad = [r for r in results if not r["ok"]]
    return {"seed": seed, "mutants_per_format": mutants,
            "formats": list(formats), "n_trials": len(results),
            "n_failed": len(bad), "failed": bad, "ok": not bad,
            "elapsed_s": round(time.monotonic() - t0, 1)}


def main():
    ap = argparse.ArgumentParser(
        description="Seeded corruption fuzzer: mutants through the "
                    "full CLI with the salvage invariant as oracle")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--mutants", type=int, default=50,
                    help="mutants per format [50]")
    ap.add_argument("--formats", default=",".join(FORMATS))
    ap.add_argument("--holes", type=int, default=4)
    ap.add_argument("--json", default=None)
    a = ap.parse_args()
    summary = run_sweep(a.seed, a.mutants,
                        formats=tuple(a.formats.split(",")),
                        holes=a.holes)
    print(json.dumps({k: v for k, v in summary.items()
                      if k != "failed"} | {"failed": summary["failed"]},
                     indent=1))
    if a.json:
        with open(a.json, "w") as f:
            json.dump(summary, f, indent=1)
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
