"""Differential tests: Pallas banded kernel vs the scan implementation.

The lax.scan aligner (ops/banded.py) is the spec; the Pallas kernel
(ops/banded_pallas.py) must be bit-exact in global+moves mode: same scores,
same stats, same band offsets, and identical move bytes for every live row
(rows beyond qlen carry frozen garbage in both — not compared).

On CPU (the default test mesh) the kernel runs in interpret mode, so
shapes are kept small.  Run with CCSX_TEST_TPU=1 on a TPU host and the
kernel runs Mosaic-compiled (interpret=False) on the chip — last done
2026-07-29 on v5e, all green.
"""

import numpy as np
import pytest

import jax

from ccsx_tpu.config import AlignParams
from ccsx_tpu.ops import banded, banded_pallas
from ccsx_tpu.utils import synth

# interpret only off-TPU: Mosaic-compile the kernel when the chip is real
INTERPRET = jax.default_backend() != "tpu"


def _random_case(rng, Qmax, Tmax, tmin=40, tspan=160):
    tl = int(rng.integers(tmin, tmin + tspan))
    tpl = rng.integers(0, 4, tl).astype(np.uint8)
    q = synth.mutate(rng, tpl, 0.03, 0.05, 0.05)[:Qmax]
    qs = np.full(Qmax, banded.PAD, np.uint8)
    qs[: len(q)] = q
    ts = np.full(Tmax, banded.PAD, np.uint8)
    ts[:tl] = tpl
    return qs, np.int32(len(q)), ts, np.int32(tl)


def _compare(qs, qlens, ts, tlens, params):
    scan_f = banded.make_batched("global", params, with_moves=True)
    r1, m1, o1 = scan_f(qs, qlens, ts, tlens)
    r2, m2, o2 = banded_pallas.batched_align_global_moves(
        qs, qlens, ts, tlens, params, interpret=INTERPRET)
    np.testing.assert_array_equal(np.asarray(r1.score), np.asarray(r2.score))
    np.testing.assert_array_equal(np.asarray(r1.mat), np.asarray(r2.mat))
    np.testing.assert_array_equal(np.asarray(r1.aln), np.asarray(r2.aln))
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    m1, m2 = np.asarray(m1), np.asarray(m2)
    for i in range(len(qlens)):
        ql = int(qlens[i])
        np.testing.assert_array_equal(
            m1[i, :ql], m2[i, :ql], err_msg=f"moves mismatch, problem {i}")


def test_bit_exact_random_batch():
    rng = np.random.default_rng(7)
    Qmax, Tmax, N = 256, 256, 5
    cases = [_random_case(rng, Qmax, Tmax) for _ in range(N)]
    qs = np.stack([c[0] for c in cases])
    qlens = np.array([c[1] for c in cases], np.int32)
    ts = np.stack([c[2] for c in cases])
    tlens = np.array([c[3] for c in cases], np.int32)
    _compare(qs, qlens, ts, tlens, AlignParams())


@pytest.mark.slow  # ~15s edge sweep; bit_exact_random_batch and
# gblock/qmax siblings keep the kernel's tier-1 pin (r13 audit)
def test_empty_and_extreme_rows():
    """Padding rows (qlen=0), very short queries, and full-length queries."""
    rng = np.random.default_rng(11)
    Qmax, Tmax = 128, 128
    tl = 100
    tpl = rng.integers(0, 4, tl).astype(np.uint8)
    ts_row = np.full(Tmax, banded.PAD, np.uint8)
    ts_row[:tl] = tpl
    qs = np.full((3, Qmax), banded.PAD, np.uint8)
    qlens = np.zeros(3, np.int32)
    # row 0: empty (padding row); row 1: tiny query; row 2: qlen == Qmax
    qs[1, :5] = tpl[:5]
    qlens[1] = 5
    full = synth.mutate(rng, tpl, 0.02, 0.3, 0.02)
    full = np.concatenate([full, rng.integers(0, 4, Qmax).astype(np.uint8)])
    qs[2] = full[:Qmax]
    qlens[2] = Qmax
    ts = np.broadcast_to(ts_row, (3, Tmax)).copy()
    tlens = np.full(3, tl, np.int32)
    _compare(qs, qlens, ts, tlens, AlignParams())


@pytest.mark.slow  # ~43s: interpret-mode kernel at an extra batch shape
def test_leading_batch_dims():
    """(Z, P, Qmax) nested batching reshapes correctly."""
    rng = np.random.default_rng(3)
    Qmax, Tmax = 128, 128
    cases = [_random_case(rng, Qmax, Tmax, tmin=40, tspan=60)
             for _ in range(4)]
    qs = np.stack([c[0] for c in cases]).reshape(2, 2, Qmax)
    qlens = np.array([c[1] for c in cases], np.int32).reshape(2, 2)
    ts = np.stack([c[2] for c in cases]).reshape(2, 2, Tmax)
    tlens = np.array([c[3] for c in cases], np.int32).reshape(2, 2)
    r, moves, offs = banded_pallas.batched_align_global_moves(
        qs, qlens, ts, tlens, AlignParams(), interpret=INTERPRET)
    assert r.score.shape == (2, 2)
    assert moves.shape == (2, 2, Qmax, 128)
    assert offs.shape == (2, 2, Qmax)
    flat = banded_pallas.batched_align_global_moves(
        qs.reshape(4, Qmax), qlens.reshape(4), ts.reshape(4, Tmax),
        tlens.reshape(4), AlignParams(), interpret=INTERPRET)
    np.testing.assert_array_equal(
        np.asarray(r.score).ravel(), np.asarray(flat[0].score))


def test_with_stats_false_same_moves_and_score():
    """The slim kernel (with_stats=False — the consensus-round config,
    star._aligner) must emit bit-identical moves/offs/score; mat/aln are
    zeros by contract, as in ops/banded.py's with_stats=False."""
    rng = np.random.default_rng(19)
    Qmax, Tmax, N = 256, 256, 5
    cases = [_random_case(rng, Qmax, Tmax) for _ in range(N)]
    qs = np.stack([c[0] for c in cases])
    qlens = np.array([c[1] for c in cases], np.int32)
    ts = np.stack([c[2] for c in cases])
    tlens = np.array([c[3] for c in cases], np.int32)
    # compare the slim kernel against the scan spec's slim mode directly
    # (the full-mode kernel is pinned by the _compare tests above; not
    # re-run here to keep suite runtime down)
    r2, m2, o2 = banded_pallas.batched_align_global_moves(
        qs, qlens, ts, tlens, AlignParams(), interpret=INTERPRET,
        with_stats=False)
    assert not np.asarray(r2.mat).any() and not np.asarray(r2.aln).any()
    scan_f = banded.make_batched("global", AlignParams(), with_moves=True,
                                 with_stats=False)
    r3, m3, o3 = scan_f(qs, qlens, ts, tlens)
    np.testing.assert_array_equal(np.asarray(r3.score), np.asarray(r2.score))
    np.testing.assert_array_equal(np.asarray(o3), np.asarray(o2))
    m2, m3 = np.asarray(m2), np.asarray(m3)
    for i in range(N):
        ql = int(qlens[i])
        np.testing.assert_array_equal(
            m3[i, :ql], m2[i, :ql], err_msg=f"moves mismatch, problem {i}")


def test_gblock_override_bit_exact():
    """A non-default problem block (gblock=16, the A/B sweep knob) must
    not change any output."""
    rng = np.random.default_rng(23)
    Qmax, Tmax, N = 128, 128, 18   # N % 16 != 0 to exercise padding
    cases = [_random_case(rng, Qmax, Tmax, tmin=40, tspan=60)
             for _ in range(N)]
    qs = np.stack([c[0] for c in cases])
    qlens = np.array([c[1] for c in cases], np.int32)
    ts = np.stack([c[2] for c in cases])
    tlens = np.array([c[3] for c in cases], np.int32)
    r1, m1, o1 = banded_pallas.batched_align_global_moves(
        qs, qlens, ts, tlens, AlignParams(), interpret=INTERPRET,
        with_stats=False)
    r2, m2, o2 = banded_pallas.batched_align_global_moves(
        qs, qlens, ts, tlens, AlignParams(), interpret=INTERPRET,
        with_stats=False, gblock=16)
    np.testing.assert_array_equal(np.asarray(r1.score), np.asarray(r2.score))
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    m1, m2 = np.asarray(m1), np.asarray(m2)
    for i in range(N):
        ql = int(qlens[i])
        np.testing.assert_array_equal(m1[i, :ql], m2[i, :ql])


def test_qmax_cap():
    with pytest.raises(ValueError):
        banded_pallas.batched_align_global_moves(
            np.zeros((1, banded_pallas.PALLAS_MAX_QMAX + 8), np.uint8),
            np.zeros(1, np.int32),
            np.zeros((1, 128), np.uint8),
            np.zeros(1, np.int32),
            AlignParams(), interpret=INTERPRET)
