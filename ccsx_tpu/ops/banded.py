"""Batched banded affine-gap alignment in JAX (TPU-native DP).

This replaces the role of bsalign's banded-striped SIMD kernels
(kmer_striped_seqedit_pairwise at main.c:264; BSPOA's banded DP fill used via
end_bspoa at main.c:492) with an idiomatic TPU design:

* the band is a fixed 128-lane vector (the reference's bandwidth=128,
  main.c:849, conveniently equals the TPU lane width);
* the fill is a ``lax.scan`` over query rows; all per-row work is elementwise
  VPU math over the band;
* the horizontal (within-row) affine gap is resolved with an associative
  max-plus prefix scan instead of a serial loop:
      F[j] = max_{j'<j} (Hd[j'] + O + E*(j-j'))
           = E*j + cummax_{j'<j}(Hd[j'] + O - E*j')
  which is exact for affine gaps because re-opening a horizontal gap from a
  horizontal-gap cell is dominated when O <= 0 (Gotoh);
* the band follows a deterministic nominal line from (i0, j0) to (i1, j1)
  (defaults: the global corners), with shifts bounded by ``maxshift`` so
  previous-row values align via a dynamic slice.  Off-diagonal alignments
  (clipped passes, border checks) pass a seeded diagonal hint from the
  host-side k-mer voting stage (ops/seed.py), mirroring the reference's
  k-mer-seeded pairwise (kmer_striped_seqedit_pairwise, main.c:264).
  Score-argmax band adaptation was tried and rejected: under low signal the
  argmax follows noise, and with the monotone-offset constraint the band
  ratchets ahead of the true path;
* path statistics (matches, columns, query start) are carried *through* the
  recurrence as extra channels selected by the same argmax decisions, so
  strand_match-style queries (score/identity/clip span, main.c:280) need no
  traceback at all;
* ``mode='global'`` can emit a packed move byte per cell for the consensus
  traceback (ops/traceback.py).

Everything is static-shape: sequences are padded to (Qmax,), (Tmax,) with the
PAD code and true lengths passed as scalars; rows beyond qlen freeze the
carry, so the final carry holds row qlen exactly.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from ccsx_tpu.config import AlignParams

NEG = -(2 ** 28)
PAD = 5

# move byte layout (global mode): bits 0-1 = H choice (0 diag, 1 E/up, 2 F/left)
# bit 2 = E reached by gap-extend (else gap-open); bit 3 = same for F.
MOVE_DIAG, MOVE_UP, MOVE_LEFT = 0, 1, 2
EBIT_EXT = 4
FBIT_EXT = 8


class BandedResult(NamedTuple):
    score: jnp.ndarray
    qb: jnp.ndarray
    qe: jnp.ndarray
    tb: jnp.ndarray
    te: jnp.ndarray
    aln: jnp.ndarray
    mat: jnp.ndarray


def _combine_rightmax(a, b):
    """Associative combiner: pick the tuple with the larger score (ties: right)."""
    take_b = b[0] >= a[0]
    return tuple(jnp.where(take_b, xb, xa) for xa, xb in zip(a, b))


def _shift_right(x, fill):
    return jnp.concatenate([jnp.full((1,), fill, x.dtype), x[:-1]])


def _pad_prev(row, maxshift):
    """[NEG, row, NEG*maxshift] so diag/up lanes are a dynamic slice at d, d+1."""
    return jnp.concatenate(
        [jnp.full((1,), NEG, row.dtype), row,
         jnp.full((maxshift,), NEG, row.dtype)]
    )


def _line_interp(ip, span, denom):
    """Exact ``floor(ip * span / denom)`` in pure int32 ops.

    The naive int32 product overflows once ``row * line_span`` crosses
    2^31 — i.e. on EVERY near-square pair past ~46341 bases (2^31 =
    46341^2), which silently froze the band offset mid-template and
    truncated every >=47kb pair alignment to its first ~2^31/tlen rows
    (the pre-r11 ultra-long bug: a 100kb identical pair "aligned" 21537
    bases).  jnp.int64 is not an option (jax_enable_x64 is off, the
    cast silently stays int32), so the 40-bit product is built from
    8-bit limbs of |ip| with an interleaved division (after reducing
    ``span`` modulo ``denom``) that keeps every intermediate below
    2^31: exact while ``|ip| * denom`` fits in 2^39 — near-square
    pairs up to ~740kb a side, far beyond any ZMW.
    Bit-equal to the old expression wherever the old one did not
    overflow (pinned by tests), so pre-r11 outputs are unchanged.

    ``span`` and ``denom`` must be >= 0 and >= 1 respectively (line
    ends are ordered); ``ip`` may be negative (rows before the line
    start), handled with exact floor semantics.  The RESULT must also
    fit int32 — guaranteed for every real line (seed hints are slope-1
    with span == denom; default corner lines have span/denom ==
    tlen/qlen), where |result| <= ~|ip| * slope stays near sequence
    scale.
    """
    # span = slope*denom + s2 with s2 < denom; the slope term
    # multiplies out exactly (ip*slope is result-scale), leaving a
    # sub-denom remainder product for the limb path
    slope = span // denom
    s2 = span - slope * denom
    aa = jnp.abs(ip)
    hi = (aa >> 8) * s2              # < (|ip|/256) * denom  < 2^31
    lo = (aa & 255) * s2             # <= 255 * denom        < 2^31
    q1 = hi // denom
    num = (hi - q1 * denom) * 256 + lo   # r1*256 + lo < 2^31
    q2 = num // denom
    mag = q1 * 256 + q2              # == floor(|ip| * s2 / denom)
    rem = num - q2 * denom
    return ip * slope + jnp.where(ip >= 0, mag,
                                  -(mag + jnp.where(rem > 0, 1, 0)))


@functools.partial(
    jax.jit,
    static_argnames=("mode", "params", "band", "maxshift", "with_moves",
                     "with_debug", "with_stats"),
)
def banded_align(
    q: jnp.ndarray,
    qlen: jnp.ndarray,
    t: jnp.ndarray,
    tlen: jnp.ndarray,
    mode: str = "global",
    params: AlignParams = AlignParams(),
    band: int | None = None,
    maxshift: int = 4,
    with_moves: bool = False,
    with_debug: bool = False,
    with_stats: bool = True,
    line: tuple | None = None,
):
    """Align one (padded) query against one (padded) template.

    Args:
      q: (Qmax,) uint8 codes, PAD beyond qlen.
      qlen: scalar int32 true length.
      t: (Tmax,) uint8 codes, PAD beyond tlen.
      tlen: scalar int32 true length.
      mode: 'global' | 'qfree' (query ends free, template end-to-end)
            | 'local' (both ends free, scores clamped at 0).
      with_moves: in global mode, also return (moves, offs) for traceback.
      line: optional (4,) int32 array (i0, j0, i1, j1) — the nominal
            alignment line the band is centered on; defaults to the global
            corners (0, 0, qlen, tlen).  Pass a seeded diagonal here for
            off-diagonal local alignments (e.g. (qb_hint, tb_hint,
            qb_hint+L, tb_hint+L)).

    Returns:
      BandedResult, or (BandedResult, moves (Qmax, band) uint8,
      offs (Qmax,) int32) when with_moves.

    Batch by ``jax.vmap`` over leading axes of (q, qlen, t, tlen).
    """
    if with_moves and mode != "global":
        raise ValueError("moves only supported in global mode")
    if not with_stats and mode != "global":
        raise ValueError("with_stats=False only supported in global mode")
    # the consensus hot path (global+moves) discards BandedResult entirely —
    # only (moves, offs) feed the traceback.  with_stats=False drops the
    # mat/aln/qb/tb channels and the per-row best tracker from the carry:
    # 3 dynamic slices per row instead of 14, a 1-channel prefix scan
    # instead of 5, no per-row gather.  Bitwise-identical moves/offs
    # (tests/test_banded.py::test_with_stats_false_same_moves).
    track_bt = mode != "global"          # qb/tb channels meaningful
    track_stats = with_stats or track_bt  # mat/aln channels carried
    M, X = params.match, params.mismatch
    O, Eext = params.gap_open, params.gap_extend
    B = band if band is not None else params.band
    Qmax = q.shape[0]
    qlen = qlen.astype(jnp.int32)
    tlen = tlen.astype(jnp.int32)

    q = q.astype(jnp.int32)
    # tpad[off + k] == t[off + k - 1] (the base entering column j = off + k)
    tpad = jnp.concatenate(
        [jnp.full((1,), PAD, jnp.int32), t.astype(jnp.int32),
         jnp.full((B + maxshift,), PAD, jnp.int32)]
    )
    karr = jnp.arange(B, dtype=jnp.int32)
    tcap = jnp.maximum(tlen - B + 1, 0)  # max feasible band offset

    if line is None:
        # global: corner-to-corner.  qfree: slope-1 from the origin — the
        # template is assumed prefix-anchored in the query; a query with a
        # junk *prefix* needs a seeded `line` hint or the band misses the
        # path entirely.  local: corner-to-corner (similar-length pairs);
        # off-diagonal local alignments also need a seeded hint.
        if mode == "qfree":
            li0, lj0, li1, lj1 = (
                jnp.int32(0), jnp.int32(0), tlen, tlen,
            )
        else:
            li0, lj0, li1, lj1 = (
                jnp.int32(0), jnp.int32(0), qlen, tlen,
            )
    else:
        line = jnp.asarray(line, dtype=jnp.int32)
        li0, lj0, li1, lj1 = line[0], line[1], line[2], line[3]

    # ---- row 0 ----
    j0 = karr  # off = 0
    if mode == "local":
        H0 = jnp.where(j0 <= tlen, 0, NEG)
    else:
        H0 = jnp.where(j0 <= tlen, jnp.where(j0 == 0, 0, O + Eext * j0), NEG)
    E0 = jnp.full((B,), NEG, jnp.int32)
    mat0 = jnp.zeros((B,), jnp.int32)
    if mode == "local":
        aln0 = jnp.zeros((B,), jnp.int32)
    else:
        aln0 = j0  # leading template-gap columns count toward aln
    qb0 = jnp.zeros((B,), jnp.int32)
    tb0 = j0 if mode == "local" else jnp.zeros((B,), jnp.int32)

    carry0 = dict(H=H0, E=E0, off=jnp.int32(0))
    if track_stats:
        carry0.update(mat=mat0, aln=aln0, Emat=mat0, Ealn=aln0)
    if track_bt:
        carry0.update(qb=qb0, tb=tb0, Eqb=qb0, Etb=tb0)
        # best-tracker: (score, qe, mat, aln, qb, tb, te)
        carry0["best"] = (
            jnp.int32(NEG), jnp.int32(0), jnp.int32(0), jnp.int32(0),
            jnp.int32(0), jnp.int32(0), jnp.int32(0),
        )

    def body(carry, xs):
        i, qi = xs  # i in 1..Qmax; qi = q[i-1]
        H_prev, E_prev, off_prev = carry["H"], carry["E"], carry["off"]

        # --- band offset for this row (nominal line, monotone, coverage-safe;
        # --- overflow-exact interpolation: see _line_interp) ---
        nom_j = lj0 + _line_interp(i - li0, lj1 - lj0,
                                   jnp.maximum(li1 - li0, 1))
        desired = nom_j - B // 2
        if mode == "local":
            lo = jnp.int32(0)
        else:
            # guarantee the band can reach column tlen by row qlen
            lo = jnp.maximum(0, tcap - (qlen - i) * maxshift)
        off = jnp.clip(
            jnp.maximum(desired, lo), off_prev,
            jnp.minimum(off_prev + maxshift, tcap),
        )
        off = jnp.maximum(off, off_prev)  # monotone even if tcap < off_prev
        d = off - off_prev

        j = off + karr
        tb_band = jax.lax.dynamic_slice(tpad, (off,), (B,))
        sub = jnp.where((qi == tb_band) & (qi < 4) & (tb_band < 4), M, X)
        ismatch = (qi == tb_band) & (qi < 4) & (tb_band < 4)

        def shifted(row, ofs):
            return jax.lax.dynamic_slice(_pad_prev(row, maxshift), (d + ofs,), (B,))

        Hd_diag = shifted(H_prev, 0)
        H_up = shifted(H_prev, 1)
        E_up = shifted(E_prev, 1)
        if track_stats:
            mat_diag = shifted(carry["mat"], 0)
            aln_diag = shifted(carry["aln"], 0)
            mat_up = shifted(carry["mat"], 1)
            aln_up = shifted(carry["aln"], 1)
            Emat_up = shifted(carry["Emat"], 1)
            Ealn_up = shifted(carry["Ealn"], 1)
        if track_bt:
            qb_diag = shifted(carry["qb"], 0)
            tb_diag = shifted(carry["tb"], 0)
            qb_up = shifted(carry["qb"], 1)
            tb_up = shifted(carry["tb"], 1)
            Eqb_up = shifted(carry["Eqb"], 1)
            Etb_up = shifted(carry["Etb"], 1)

        # --- E (vertical: consume query base, gap in template) ---
        e_ext = E_up + Eext
        e_open = H_up + O + Eext
        e_is_open = e_open >= e_ext
        Enew = jnp.maximum(e_ext, e_open)
        if track_stats:
            Emat = jnp.where(e_is_open, mat_up, Emat_up)
            Ealn = jnp.where(e_is_open, aln_up, Ealn_up) + 1
        if track_bt:
            Eqb = jnp.where(e_is_open, qb_up, Eqb_up)
            Etb = jnp.where(e_is_open, tb_up, Etb_up)

        # --- Hd = best of diag / E ---
        diag_term = Hd_diag + sub
        d_wins = diag_term >= Enew
        Hd = jnp.maximum(diag_term, Enew)
        if track_stats:
            Hmat = jnp.where(d_wins, mat_diag + ismatch, Emat)
            Haln = jnp.where(d_wins, aln_diag, Ealn - 1) + 1
        if track_bt:
            Hqb = jnp.where(d_wins, qb_diag, Eqb)
            Htb = jnp.where(d_wins, tb_diag, Etb)

        # --- boundary lane j == 0 (only if off == 0) ---
        at0 = j == 0
        if mode == "global":
            b_H = O + Eext * i
            Hd = jnp.where(at0, b_H, Hd)
            Enew = jnp.where(at0, b_H, Enew)
            if track_stats:
                Hmat = jnp.where(at0, 0, Hmat)
                Haln = jnp.where(at0, i, Haln)
                Emat = jnp.where(at0, 0, Emat)
                Ealn = jnp.where(at0, i, Ealn)
        elif mode == "qfree":
            Hd = jnp.where(at0, 0, Hd)
            Enew = jnp.where(at0, NEG, Enew)
            Hmat = jnp.where(at0, 0, Hmat)
            Haln = jnp.where(at0, 0, Haln)
            Hqb = jnp.where(at0, i, Hqb)
            Htb = jnp.where(at0, 0, Htb)

        # --- invalid lanes (beyond template) ---
        invalid = j > tlen
        Hd = jnp.where(invalid, NEG, Hd)
        Enew = jnp.where(invalid, NEG, Enew)

        # --- F (horizontal) via associative max-plus prefix scan ---
        v = Hd + O - Eext * karr
        elems = (v,)
        if track_stats:
            elems += (Hmat, Haln - karr)
        if track_bt:
            elems += (Hqb, Htb)
        cum = jax.lax.associative_scan(_combine_rightmax, elems)
        sh = tuple(
            _shift_right(x, NEG if idx == 0 else 0)
            for idx, x in enumerate(cum)
        )
        F = sh[0] + Eext * karr

        # --- H = max(Hd, F) ---
        hd_wins = Hd >= F
        Hnew = jnp.maximum(Hd, F)
        if track_stats:
            mat_new = jnp.where(hd_wins, Hmat, sh[1])
            aln_new = jnp.where(hd_wins, Haln, sh[2] + karr)
        if track_bt:
            qb_new = jnp.where(hd_wins, Hqb, sh[3])
            tb_new = jnp.where(hd_wins, Htb, sh[4])

        if mode == "local":
            clamp = Hnew < 0
            Hnew = jnp.where(clamp, 0, Hnew)
            mat_new = jnp.where(clamp, 0, mat_new)
            aln_new = jnp.where(clamp, 0, aln_new)
            qb_new = jnp.where(clamp, i, qb_new)
            tb_new = jnp.where(clamp, j, tb_new)
            Hnew = jnp.where(invalid, NEG, Hnew)

        # --- moves byte (global traceback) ---
        if with_moves:
            choice = jnp.where(
                hd_wins & d_wins, MOVE_DIAG,
                jnp.where(hd_wins, MOVE_UP, MOVE_LEFT),
            ).astype(jnp.uint8)
            ebit = jnp.where(e_is_open, 0, EBIT_EXT).astype(jnp.uint8)
            H_left = _shift_right(Hnew, NEG)
            f_is_open = F == (H_left + O + Eext)
            fbit = jnp.where(f_is_open, 0, FBIT_EXT).astype(jnp.uint8)
            moves_row = choice | ebit | fbit
        else:
            moves_row = jnp.zeros((B,), jnp.uint8)

        # --- trackers (the global result reads the final carry instead) ---
        live = i <= qlen
        if mode == "qfree":
            best = carry["best"]
            laneT = tlen - off
            ok = live & (laneT >= 0) & (laneT < B)
            laneTc = jnp.clip(laneT, 0, B - 1)
            val = jnp.where(ok, Hnew[laneTc], NEG)
            cand = (
                val, i, mat_new[laneTc], aln_new[laneTc],
                qb_new[laneTc], tb_new[laneTc], tlen,
            )
            take = cand[0] > best[0]
            best = tuple(jnp.where(take, c, b) for c, b in zip(cand, best))
        elif mode == "local":
            best = carry["best"]
            masked = jnp.where(j <= tlen, Hnew, NEG)
            lane = jnp.argmax(masked).astype(jnp.int32)
            val = jnp.where(live, masked[lane], NEG)
            cand = (
                val, i, mat_new[lane], aln_new[lane],
                qb_new[lane], tb_new[lane], off + lane,
            )
            take = cand[0] > best[0]
            best = tuple(jnp.where(take, c, b) for c, b in zip(cand, best))

        # --- freeze rows beyond qlen ---
        def frz(new, old):
            return jnp.where(live, new, old)

        new_carry = dict(
            H=frz(Hnew, H_prev), E=frz(Enew, E_prev), off=frz(off, off_prev),
        )
        if track_stats:
            new_carry.update(
                mat=frz(mat_new, carry["mat"]), aln=frz(aln_new, carry["aln"]),
                Emat=frz(Emat, carry["Emat"]), Ealn=frz(Ealn, carry["Ealn"]),
            )
        if track_bt:
            new_carry.update(
                qb=frz(qb_new, carry["qb"]), tb=frz(tb_new, carry["tb"]),
                Eqb=frz(Eqb, carry["Eqb"]), Etb=frz(Etb, carry["Etb"]),
                best=best,
            )
        if with_moves:
            ys = (moves_row, frz(off, off_prev))
        elif with_debug:
            dbg_max = jnp.max(jnp.where(j <= tlen, Hnew, NEG))
            dbg_arg = jnp.argmax(jnp.where(j <= tlen, Hnew, NEG)).astype(jnp.int32)
            ys = (frz(off, off_prev), dbg_max, dbg_arg)
        else:
            ys = None
        return new_carry, ys

    xs = (jnp.arange(1, Qmax + 1, dtype=jnp.int32), q)
    carry, ys = jax.lax.scan(body, carry0, xs)

    if mode == "global":
        laneT = tlen - carry["off"]
        reachable = (laneT >= 0) & (laneT < B)  # band covered column tlen
        lane = jnp.clip(laneT, 0, B - 1)
        zero = jnp.int32(0)
        res = BandedResult(
            score=jnp.where(reachable, carry["H"][lane], NEG),
            qb=jnp.int32(0), qe=qlen, tb=jnp.int32(0), te=tlen,
            aln=jnp.where(reachable, carry["aln"][lane], 0)
            if track_stats else zero,
            mat=jnp.where(reachable, carry["mat"][lane], 0)
            if track_stats else zero,
        )
    else:
        s, qe, mat, aln, qb, tb, te = carry["best"]
        res = BandedResult(score=s, qb=qb, qe=qe, tb=tb, te=te,
                           aln=aln, mat=mat)
    if with_moves:
        moves, offs = ys
        return res, moves, offs
    if with_debug:
        return res, ys
    return res


# Batched variant ------------------------------------------------------------


def make_batched(mode: str, params: AlignParams, band: int | None = None,
                 maxshift: int = 4, with_moves: bool = False,
                 with_line: bool = False, with_stats: bool = True):
    """A jitted, vmapped aligner with static config baked in.

    With ``with_line``, the batched function takes a fifth argument:
    (batch, 4) int32 nominal-line hints (see banded_align's ``line``).
    """
    f = functools.partial(
        banded_align, mode=mode, params=params, band=band,
        maxshift=maxshift, with_moves=with_moves, with_stats=with_stats,
    )
    if with_line:
        return jax.jit(jax.vmap(lambda q, ql, t, tl, line: f(q, ql, t, tl, line=line)))
    return jax.jit(jax.vmap(f))
