"""Resident consensus service: multi-tenant `ccsx-tpu serve`.

The CLI pays its startup tax — jax import, backend init, and the AOT
warmup compiles — once PER RUN; a lab submitting many small jobs pays
it once per JOB.  This module keeps one warm process resident and runs
jobs through the SAME batched driver the CLI uses
(pipeline/batch.run_pipeline_batched), so a served job's output is
byte-identical to the CLI run of the same input by construction, while
job 2..N skip every XLA compile job 1 booked (the module-level jitted
step factories are process-wide, the WarmupCompiler below is
server-lifetime, and the zero-steady-state-recompile criterion is
enforced by tests/test_serve.py against the server tracer's group
table).

**Job API** (mounted on the existing telemetry HTTP stack,
utils/telemetry.py — one server, one port):

  POST   /jobs            submit: JSON {"input": path, ...overrides}
                          or a streamed BAM/FASTQ request body
                          (?format=bam|fastq|fasta); 201 {"id": ...},
                          429 + Retry-After at the queue-depth cap
  GET    /jobs            all jobs (id, state, rc, counters)
  GET    /jobs/<id>        one job's status + fault-domain metrics
  GET    /jobs/<id>/output stream the finished FASTA/FASTQ
  DELETE /jobs/<id>        cancel (running jobs drain via their guard)
  GET    /healthz          LIVENESS: 200 while the process serves
  GET    /readyz           READINESS: 503 {"ready": false, reason}
                          while warming (cold compiles pending),
                          draining, or at the queue cap
  GET    /metrics          server Prometheus series + per-job
                          ccsx_job_*{job="..."} series
  GET    /progress         the server Metrics snapshot (cumulative
                          group compile table across all jobs)

**Per-job fault domains under shared capacity.**  Each job gets its
own journal, its own Metrics (labeled ``job=<id>``), its own failure
budget / corruption accounting, its own Resilience (so a
tenant-induced device hang trips only that job's breaker to the host
rung), its own drain guard (utils/drain.FlagGuard — cancel, deadline,
and server drain all route through the drivers' existing rc-75 drain
path), and its own fault-injection scope
(utils/faultinject.scope_arm: a job's ``faults`` spec fires only on
that job's thread family).  What jobs SHARE is capacity: the
FairWindow below splits the device admission window (cfg.
zmw_microbatch slots) round-robin-fairly — a tenant at its fair share
is denied further slots while another tenant wants them — and the
window-size invariance the batched driver pins (output bytes identical
across admission windows) is exactly what makes fair sharing safe for
byte identity.

**Lifecycle.**  Transient failures (rc 1: ENOSPC, torn writes) retry
with exponential backoff up to --job-retries — the per-job journal
makes a retry resume, not recompute.  rc 2 (failure budget) and
cancellation are terminal.  --job-deadline bounds a job's wall clock
across attempts (exceeding it drains the job and fails it).  SIGTERM
drains the SERVER: stop accepting, drain running jobs (their journals
settle), persist the queue to <spool>/state.json, exit rc 75
(EX_TEMPFAIL) — restarting the same command requeues unfinished jobs
and completes them byte-identically.  benchmarks/serve_chaos.py is the
seeded soak that proves the blast radius of each fault class stays in
the faulted job.

**Fleet mode (r16).**  ``serve --fleet <spool>`` makes this process
one REPLICA of a fleet sharing <spool> as a job LEASE DOMAIN
(pipeline/gateway.py is the spool protocol, utils/lease.py the
machinery): a queued job is acquired with the kernel-arbitrated O_EXCL
lease + heartbeat renewal, cross-replica cancel/deadline marks are
observed at each renewal tick, and the terminal state commits through
an EXCLUSIVE done marker — marker before lease release, so a zombie
replica that survived expiry can never double-emit.  Replica death is
requeue-by-construction: the lease expires, the job's journal survives
in the spool, and the next scanning replica RESUMES it.  Jobs with at
least --fanout-holes holes fan out across replicas through the PR 13
range queue (helpers pull ranges into their warm runtime; a mid-fan-out
kill costs about one range).  Each replica claims slot ``r<k>`` and
serves on base_port + k, advertising the actual bound port in its slot
heartbeat; `ccsx-tpu gateway` balances on the replicas' /readyz, and
`shepherd --serve-replicas N` supervises the whole fleet.
benchmarks/serve_fleet_chaos.py is the churn soak (SIGKILL mid-wave,
mid-run join: zero lost, zero duplicated, byte-identical).
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import socket
import sys
import threading
import time
from typing import Dict, List, Optional

from ccsx_tpu import exitcodes
from ccsx_tpu.config import CcsConfig
from ccsx_tpu.pipeline import gateway as spoolproto
from ccsx_tpu.utils import faultinject
from ccsx_tpu.utils import lease as leaselib
from ccsx_tpu.utils.drain import FlagGuard
from ccsx_tpu.utils.journal import write_json_atomic
from ccsx_tpu.utils.metrics import Metrics, size_class

STATE_FILE = "state.json"
# terminal-for-this-process states ("interrupted" is resumable by a
# server restart, but this process will not touch the job again)
TERMINAL = ("done", "failed", "cancelled", "interrupted")
# job cfg overrides accepted from a submission, with their coercions —
# every one is journal-non-semantic (pipeline/journal _NON_SEMANTIC) or
# consumed before the journal fingerprint, so an override can never
# poison a resume
_CFG_OVERRIDES = {
    "salvage": ("salvage", lambda v: _truthy(v)),
    "max_failed_holes": ("max_failed_holes", float),
    "dispatch_deadline_s": ("dispatch_deadline_s", float),
    "breaker_strikes": ("breaker_strikes", int),
    "prep_threads": ("prep_threads", int),
}
# job-level (non-cfg) override keys
_JOB_OVERRIDES = ("format", "output", "deadline_s", "faults", "inflight")


def _truthy(v) -> bool:
    if isinstance(v, str):
        return v.strip().lower() in ("1", "true", "yes", "on")
    return bool(v)


class QueueFull(Exception):
    """Submission refused at the queue-depth cap (HTTP 429)."""


class Draining(Exception):
    """Submission refused because the server is draining (HTTP 503)."""


# ---- fair shared admission ------------------------------------------------

class FairWindow:
    """The device admission window as a shared resource: ``capacity``
    slots (cfg.zmw_microbatch — the same cap a solo run's window grows
    to) split fairly across registered jobs.

    Fairness rule: a job may always take a free slot UNLESS it already
    holds its fair share (ceil(capacity / registered jobs)) while some
    OTHER job is wanting (was denied and has not succeeded since) — a
    lone tenant gets the whole window, and a second tenant's first
    denial immediately caps the first at half.  Slots track holes that
    are admitted AND still computing (pipeline/batch.drive_batched
    releases on hole completion, before emission), so a job with an
    out-of-order emission tail is not charged for holes the device is
    done with.

    A stale "wanting" mark (a job denied once that then stopped
    asking) can cap siblings below the full window until that job
    releases to zero or unregisters — a bounded throughput nick, never
    a correctness issue: output bytes are invariant to window size
    (the pinned invariance that makes fair sharing safe at all)."""

    def __init__(self, capacity: int):
        self.capacity = max(1, int(capacity))
        self._cv = threading.Condition()
        self._held: Dict[str, int] = {}
        self._want: set = set()

    def register(self, jid: str) -> None:
        with self._cv:
            self._held.setdefault(jid, 0)
            self._cv.notify_all()

    def unregister(self, jid: str) -> None:
        with self._cv:
            self._held.pop(jid, None)
            self._want.discard(jid)
            self._cv.notify_all()

    def try_acquire(self, jid: str) -> bool:
        with self._cv:
            held = self._held.get(jid, 0)
            if sum(self._held.values()) >= self.capacity:
                self._want.add(jid)
                return False
            share = -(-self.capacity // max(1, len(self._held)))
            if held >= share and any(j != jid for j in self._want):
                self._want.add(jid)
                return False
            self._held[jid] = held + 1
            self._want.discard(jid)
            return True

    def release(self, jid: str) -> None:
        with self._cv:
            n = self._held.get(jid, 0)
            if n > 0:
                self._held[jid] = n - 1
            self._cv.notify_all()

    def release_all(self, jid: str) -> None:
        with self._cv:
            if self._held.get(jid):
                self._held[jid] = 0
            self._want.discard(jid)
            self._cv.notify_all()

    def wait(self, timeout: Optional[float]) -> None:
        with self._cv:
            self._cv.wait(timeout)

    def pressure(self) -> float:
        """Held fraction of the admission window [0, 1] — the per-
        replica autoscale gauge the slot lease advertises (a fleet
        whose replicas all sit near 1.0 wants more boxes)."""
        with self._cv:
            return round(sum(self._held.values())
                         / float(self.capacity), 4)


class JobAdmission:
    """One job's handle on the FairWindow — the duck-typed
    ``admission`` attribute drive_batched consumes (try_acquire /
    release / wait / reset)."""

    def __init__(self, window: FairWindow, jid: str):
        self._w = window
        self._jid = jid
        window.register(jid)

    def try_acquire(self) -> bool:
        return self._w.try_acquire(self._jid)

    def release(self) -> None:
        self._w.release(self._jid)

    def wait(self, timeout: Optional[float] = None) -> None:
        self._w.wait(timeout)

    def reset(self) -> None:
        self._w.release_all(self._jid)

    def close(self) -> None:
        self._w.unregister(self._jid)


class _JobRuntime:
    """The ``shared`` object handed to drive_batched: the server-owned
    pieces (warm, warm_cache) plus the job-owned ones (guard,
    admission)."""

    def __init__(self, warm, warm_cache, guard, admission):
        self.warm = warm
        self.warm_cache = warm_cache
        self.guard = guard
        self.admission = admission


# ---- jobs -----------------------------------------------------------------

class Job:
    def __init__(self, jid: str, in_path: str, out_path: str,
                 journal_path: str, cfg: CcsConfig,
                 overrides: Optional[dict] = None):
        self.id = jid
        self.in_path = in_path
        self.out_path = out_path
        self.journal_path = journal_path
        self.cfg = cfg
        self.raw_overrides = dict(overrides or {})
        self.state = "queued"
        self.rc: Optional[int] = None
        self.error: Optional[str] = None
        self.attempts = 0
        self.deadline_s = 0.0
        self.faults: Optional[str] = None
        self.inflight: Optional[int] = None
        self.submitted_at = time.time()
        self.started_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.stop_reason: Optional[str] = None
        self.metrics: Optional[Metrics] = None
        self.snap: Optional[dict] = None
        self.guard: Optional[FlagGuard] = None
        self.thread: Optional[threading.Thread] = None
        self._stop_ev = threading.Event()
        # fleet mode: the spool job lease this replica holds for the
        # job (utils/lease.py record), lost-lease flag, and the hole
        # count that triggered cross-replica fan-out (0 = solo)
        self.lease: Optional[dict] = None
        self.lease_lost = False
        self.fanout_holes_n = 0
        # the fleet-wide correlation id (minted at submission —
        # gateway.submit_job for spooled jobs, ServeCore.submit for
        # solo ones); every span/metrics event this job causes in any
        # process carries it
        self.cid: Optional[str] = None

    def info(self) -> dict:
        snap = self.snap
        if snap is None and self.metrics is not None:
            snap = self.metrics.snapshot()
        d = {
            "id": self.id, "state": self.state, "rc": self.rc,
            "input": self.in_path, "output": self.out_path,
            "journal": self.journal_path, "error": self.error,
            "attempts": self.attempts, "stop_reason": self.stop_reason,
            "submitted_at": self.submitted_at,
            "started_at": self.started_at,
            "finished_at": self.finished_at,
            "cid": self.cid,
        }
        if snap:
            d["metrics"] = {k: snap.get(k) for k in (
                "holes_in", "holes_out", "holes_failed", "holes_corrupt",
                "holes_filtered", "device_hangs", "breaker_trips",
                "host_fallbacks", "zmws_per_sec", "elapsed_s",
                "degraded")}
        return d


class ServeCore:
    """The resident server: one warm runtime, N tenant jobs.

    Owns the process-global pieces exactly one owner may hold — the
    installed tracer (ONE compile table across jobs: its group stats
    accrue into ``self.metrics``, and "no group's compile count grows
    after warmup" is the steady-state-recompile criterion), the
    server-lifetime WarmupCompiler + inline-warm dedupe set, and the
    FairWindow.  Jobs run on daemon threads (at most ``max_active``
    concurrently) through run_pipeline_batched with a _JobRuntime.

    The HTTP layer (_ServeHandler) is a thin client of this object;
    tests drive ServeCore directly for the byte-identity and isolation
    cases and through HTTP for the API cases."""

    def __init__(self, cfg: CcsConfig, spool: str,
                 max_queue: int = 16, max_active: int = 2,
                 retries: int = 1, backoff_s: float = 0.5,
                 job_deadline_s: float = 0.0,
                 fleet: bool = False, replica: Optional[str] = None,
                 lease_timeout: float = 10.0, fanout_holes: int = 0,
                 fanout_ranges: int = 0, poll_s: float = 0.25):
        from ccsx_tpu.utils import trace

        self.cfg = cfg
        self.spool = spool
        os.makedirs(spool, exist_ok=True)
        self.max_queue = max(1, int(max_queue))
        self.max_active = max(1, int(max_active))
        self.retries = max(0, int(retries))
        self.backoff_s = max(0.0, float(backoff_s))
        self.job_deadline_s = max(0.0, float(job_deadline_s))
        # fleet mode: the spool is a SHARED lease domain (pipeline/
        # gateway.py spool protocol) — jobs are leased, not owned, and
        # state.json is replaced by per-job records + markers
        self.fleet = bool(fleet)
        self.replica = replica or f"s{os.getpid()}"
        self.lease_timeout = max(0.2, float(lease_timeout))
        self.fanout_holes = max(0, int(fanout_holes))
        self.fanout_ranges = max(0, int(fanout_ranges))
        self.poll_s = max(0.05, float(poll_s))
        self.hostname = socket.gethostname()
        self.addr = os.environ.get("CCSX_ADVERTISE_HOST", "127.0.0.1")
        self.advertised_port = 0
        self._slot: Optional[int] = None
        self._slot_rec: Optional[dict] = None
        self._expiry_seq = 0
        self._helpers: Dict[str, threading.Thread] = {}
        self.metrics = Metrics(verbose=0, stream=None)
        self._lock = threading.RLock()
        self._persist_lock = threading.Lock()
        self._jobs: Dict[str, Job] = {}
        self._queue: List[Job] = []
        self._seq = 0
        self._n_running = 0
        self.accepting = True
        self.draining = False
        self._completed_any = False
        self._closed = False
        # the server-lifetime warm plane (satellite: one sketch/screen/
        # pair executable cache across jobs — WarmupCompiler dedupes on
        # key, warm_cache dedupes the inline path)
        self.warm = None
        if getattr(cfg, "warmup_compile", True):
            from ccsx_tpu.pipeline.warmup import WarmupCompiler

            self.warm = WarmupCompiler()
        self.warm_cache: set = set()
        self.window = FairWindow(int(getattr(cfg, "zmw_microbatch", 64)))
        # the server tracer: installed for the process lifetime, group
        # table in self.metrics — /progress exposes the cumulative
        # compile counters the zero-recompile test reads.  A --trace
        # path makes it a per-PROCESS span JSONL (every job's spans,
        # cid-stamped) — give each fleet replica its own path and
        # `ccsx-tpu report --fleet <spool>` stitches them into one
        # timeline per job
        self._tracer = trace.Tracer(cfg.trace_path or None,
                                    stall_timeout=cfg.stall_timeout_s,
                                    metrics=self.metrics)
        trace.install(self._tracer)
        if not self.fleet:
            self._restore_state()
        self._mon_stop = threading.Event()
        self._mon = threading.Thread(target=self._monitor, daemon=True,
                                     name="ccsx-serve-monitor")
        self._mon.start()
        self._scan_stop = threading.Event()
        self._scan: Optional[threading.Thread] = None
        if self.fleet:
            self._scan = threading.Thread(target=self._spool_scan,
                                          daemon=True,
                                          name="ccsx-serve-spool")
            self._scan.start()
        self._pump()

    # ---- fleet plumbing ---------------------------------------------------

    def register_replica(self) -> int:
        """Claim a replica slot lease (``r<k>``) in the shared spool:
        the deterministic port assignment (serve on base_port + k) and
        the discovery record gateway/top scan.  The scan loop renews
        it with readiness + load refreshed each heartbeat."""
        slot, rec = spoolproto.acquire_replica_slot(
            self.spool, self.replica,
            extra={"addr": self.addr, "host": self.hostname,
                   "port": self.advertised_port,
                   "replica": self.replica},
            lease_timeout=self.lease_timeout)
        self._slot, self._slot_rec = slot, rec
        return slot

    def set_advertised(self, port: int,
                       addr: Optional[str] = None) -> None:
        self.advertised_port = int(port)
        if addr:
            self.addr = addr

    # ---- submission -------------------------------------------------------

    def submit(self, input_path: Optional[str] = None,
               body_stream=None, body_len: int = 0,
               overrides: Optional[dict] = None):
        overrides = dict(overrides or {})
        unknown = [k for k in overrides
                   if k not in _CFG_OVERRIDES and k not in _JOB_OVERRIDES]
        if unknown:
            raise ValueError(f"unknown job option(s): {unknown}")
        if self.fleet:
            return self._submit_fleet(input_path, body_stream,
                                      body_len, overrides)
        with self._lock:
            if not self.accepting:
                raise Draining("server is draining")
            queued = sum(1 for j in self._jobs.values()
                         if j.state == "queued")
            if queued >= self.max_queue:
                raise QueueFull(
                    f"job queue full ({queued}/{self.max_queue})")
            self._seq += 1
            jid = f"j{self._seq:04d}"
        fmt = str(overrides.get("format") or "").lower()
        if fmt and fmt not in ("bam", "fastq", "fasta"):
            raise ValueError(f"unknown input format {fmt!r}")
        if body_stream is not None:
            # streamed submission: spool the body before the job exists
            # (a torn upload must not leave a half-readable queued job)
            suffix = fmt or "bam"
            input_path = os.path.join(self.spool, f"{jid}.input.{suffix}")
            with open(input_path, "wb") as f:
                left = int(body_len)
                while left > 0:
                    chunk = body_stream.read(min(left, 1 << 16))
                    if not chunk:
                        raise ValueError("short request body")
                    f.write(chunk)
                    left -= len(chunk)
        if not input_path:
            raise ValueError("job needs an input path or a request body")
        job = self._build_job(jid, input_path, overrides)
        # solo jobs never pass through the gateway: mint their
        # correlation id here, at the same point in the lifecycle
        job.cid = f"c{os.urandom(6).hex()}"
        with self._lock:
            self._jobs[jid] = job
            self._queue.append(job)
        self._persist()
        self._pump()
        return job

    def _submit_fleet(self, input_path, body_stream, body_len,
                      overrides):
        """Fleet-mode submit: write the job into the SHARED spool (the
        spool is the queue — any replica, this one included, may lease
        it) and return a lightweight queued handle.  Validation
        matches solo submit; capacity is the fleet-wide spool depth,
        not a local queue."""
        fmt = str(overrides.get("format") or "").lower()
        if fmt and fmt not in ("bam", "fastq", "fasta"):
            raise ValueError(f"unknown input format {fmt!r}")
        with self._lock:
            if not self.accepting:
                raise Draining("server is draining")
        counts = spoolproto.spool_counts(self.spool)
        depth = counts["queued"] + counts["cancelling"]
        if depth >= self.max_queue:
            raise QueueFull(
                f"fleet spool full ({depth}/{self.max_queue})")
        # overrides are validated here but coerced by whichever
        # replica acquires the job (_build_job) — fail fast on the
        # obviously bad ones so the submitter gets the 400, not a
        # failed job
        self._build_job("probe", input_path or "unspooled", overrides)
        jid = spoolproto.submit_job(self.spool, input_path=input_path,
                                    body_stream=body_stream,
                                    body_len=body_len,
                                    overrides=overrides)
        class _Handle:
            pass

        h = _Handle()
        h.id, h.state = jid, "queued"
        return h

    def _build_job(self, jid: str, input_path: str,
                   overrides: dict) -> Job:
        cfg_kw = {}
        for key, (field, coerce) in _CFG_OVERRIDES.items():
            if key in overrides and overrides[key] is not None:
                try:
                    cfg_kw[field] = coerce(overrides[key])
                except (TypeError, ValueError):
                    raise ValueError(f"bad value for {key!r}: "
                                     f"{overrides[key]!r}")
        fmt = str(overrides.get("format") or "").lower()
        if fmt:
            cfg_kw["is_bam"] = fmt == "bam"
        # the job must not fight the server for process-global planes:
        # no second telemetry server, no second metrics stream, no
        # per-job trace file (the server tracer records every job)
        cfg = dataclasses.replace(self.cfg, telemetry_port=0,
                                  metrics_path=None, trace_path=None,
                                  **cfg_kw)
        out = str(overrides.get("output") or
                  os.path.join(self.spool, f"{jid}.out.fasta"))
        job = Job(jid, input_path, out,
                  os.path.join(self.spool, f"{jid}.journal"), cfg,
                  overrides=overrides)
        job.deadline_s = float(overrides.get("deadline_s")
                               or self.job_deadline_s or 0.0)
        job.faults = overrides.get("faults") or None
        if overrides.get("inflight") is not None:
            job.inflight = int(overrides["inflight"])
        return job

    # ---- scheduling -------------------------------------------------------

    def _pump(self) -> None:
        with self._lock:
            if self.draining:
                return
            while self._n_running < self.max_active and self._queue:
                job = self._queue.pop(0)
                if job.state != "queued":
                    continue
                job.state = "running"
                if job.started_at is None:
                    job.started_at = time.time()
                    self.metrics.observe(
                        "queue_wait_s",
                        max(0.0, job.started_at - job.submitted_at),
                        size_class(job.fanout_holes_n))
                self._n_running += 1
                t = threading.Thread(target=self._job_main, args=(job,),
                                     daemon=True,
                                     name=f"ccsx-job-{job.id}")
                job.thread = t
                t.start()

    def _job_main(self, job: Job) -> None:
        from ccsx_tpu.utils import blackbox, trace

        stop: Optional[threading.Event] = None
        try:
            if self.fleet and job.lease is not None:
                stop = threading.Event()
                t = threading.Thread(target=self._job_renewer,
                                     args=(job, stop), daemon=True,
                                     name=f"ccsx-renew-{job.id}")
                t.start()
            # every span/metrics record the job causes carries its
            # correlation id; the black-box inflight/done pair is what
            # names this job in a SIGKILLed replica's dump
            with trace.cid_scope(job.cid):
                blackbox.note("inflight", what="job", id=job.id,
                              **({"cid": job.cid} if job.cid else {}))
                err = True
                try:
                    self._run_job(job)
                    err = False
                finally:
                    # pair the note even when _run_job raises: only a
                    # genuine process death may leave the job open in
                    # a live replica's ring
                    blackbox.note("done", what="job", id=job.id,
                                  **({"error": True} if err else {}))
        finally:
            if stop is not None:
                stop.set()
            with self._lock:
                self._n_running -= 1
            self._persist()
            self._pump()

    # ---- the fleet scan loop ----------------------------------------------

    def _fleet_capacity(self) -> int:
        with self._lock:
            active = sum(1 for j in self._jobs.values()
                         if j.state in ("queued", "running"))
        active += sum(1 for t in self._helpers.values()
                      if t.is_alive())
        return self.max_active - active

    def _spool_scan(self) -> None:
        while not self._scan_stop.wait(self.poll_s):
            try:
                self._scan_once()
            except Exception as e:  # the scan loop must survive churn
                print(f"[ccsx-tpu] serve: spool scan error: "
                      f"{type(e).__name__}: {e}", file=sys.stderr)

    def _scan_once(self) -> None:
        self._renew_slot()
        jids = spoolproto.list_job_ids(self.spool)
        for jid in jids:
            if os.path.exists(
                    spoolproto.done_marker_path(self.spool, jid)):
                continue
            rec = spoolproto.read_job_record(self.spool, jid) or {}
            hold = leaselib.read_lease(self.spool, jid)
            if hold is not None:
                if (hold and hold.get("pid") == os.getpid()
                        and hold.get("worker") == self.replica):
                    continue  # ours: its renewer observes the record
                # stale foreign holder: KILL-BEFORE-STEAL, but only
                # when the lease names OUR host — a pid from another
                # box must never be shot here
                self._expiry_seq += 1
                kill = (hold or {}).get("host") in (None, self.hostname)
                evicted = leaselib.expire_lease(
                    self.spool, jid, self.lease_timeout, kill=kill,
                    seq=self._expiry_seq)
                if evicted is not None:
                    print(f"[ccsx-tpu] serve: {self.replica} requeued "
                          f"job {jid} from "
                          f"{evicted.get('worker') or 'unknown'} "
                          "(lease expired)", file=sys.stderr)
                continue
            if rec.get("cancel"):
                # cancelled while queued: any replica may retire it
                # (the exclusive marker arbitrates racers)
                if spoolproto.retire_job(self.spool, jid, "cancelled",
                                         exitcodes.RC_INTERRUPTED,
                                         self.replica):
                    print(f"[ccsx-tpu] serve: {self.replica} retired "
                          f"cancelled queued job {jid}",
                          file=sys.stderr)
                continue
            with self._lock:
                accepting = self.accepting and not self.draining
            if not accepting or self._fleet_capacity() <= 0:
                continue
            lease_rec = leaselib.try_acquire(
                self.spool, jid, self.replica,
                extra={"replica": self.replica, "host": self.hostname,
                       "addr": self.addr,
                       "port": self.advertised_port,
                       "cid": rec.get("cid")},
                kind="job")
            if lease_rec is not None:
                self._admit_fleet_job(jid, rec, lease_rec)
        if self._fleet_capacity() > 0:
            self._maybe_help_fanout(jids)

    def _renew_slot(self) -> None:
        if self._slot_rec is None:
            return
        ready, reason = self.readiness()
        with self._lock:
            held = sum(1 for j in self._jobs.values()
                       if j.state in ("queued", "running"))
        ok = leaselib.renew(
            self.spool, f"{spoolproto.SLOT_PREFIX}{self._slot}",
            self._slot_rec,
            extra={"addr": self.addr, "host": self.hostname,
                   "port": self.advertised_port,
                   "replica": self.replica, "ready": ready,
                   "reason": reason,
                   "pressure": self.window.pressure(),
                   "leases": held})
        if not ok:
            # evicted as presumed dead: re-register rather than serve
            # undiscoverable (the bound port stays valid; the fresh
            # slot record advertises it)
            try:
                self.register_replica()
                self._renew_slot()
            except RuntimeError as e:
                print(f"[ccsx-tpu] serve: {self.replica} lost its "
                      f"slot and could not re-register: {e}",
                      file=sys.stderr)
                self._slot_rec = None

    def _admit_fleet_job(self, jid: str, rec: dict,
                         lease_rec: dict) -> None:
        try:
            job = self._build_job(jid, rec.get("input") or "",
                                  rec.get("overrides") or {})
        except ValueError as e:
            spoolproto.retire_job(self.spool, jid, "failed", 1,
                                  self.replica, error=str(e))
            leaselib.release(self.spool, jid, lease_rec)
            return
        job.lease = lease_rec
        job.cid = rec.get("cid")
        try:
            # queue-wait must measure from SUBMISSION, not from this
            # replica's admit tick
            job.submitted_at = float(rec["submitted_at"])
        except (KeyError, TypeError, ValueError):
            pass
        if self.fanout_holes > 0:
            try:
                from ccsx_tpu.pipeline.run import count_raw_holes

                n = count_raw_holes(job.in_path, job.cfg)
            except (OSError, RuntimeError, ValueError):
                n = 0
            if n >= self.fanout_holes:
                job.fanout_holes_n = n
        with self._lock:
            self._jobs[jid] = job
            self._queue.append(job)
        self._pump()

    def _job_renewer(self, job: Job, stop: threading.Event) -> None:
        """Heartbeat-renew the job lease; every renewal also OBSERVES
        the spool record — the cross-replica control channel: a cancel
        (or tightened deadline) marked at the gateway lands here and
        aborts through the job's own guard, the PR 15 blast-radius
        path.  A failed renewal means we were expired as presumed
        dead: stop emitting (the exclusive done marker stays the last
        fence against a zombie double-commit)."""
        interval = max(0.05, self.lease_timeout / 3.0)
        while not stop.wait(interval):
            rec = spoolproto.read_job_record(self.spool, job.id) or {}
            if rec.get("cancel"):
                with self._lock:
                    if job.state == "running" and not job.stop_reason:
                        self._signal_locked(job, "cancel")
            dl = (rec.get("overrides") or {}).get("deadline_s")
            if dl is not None:
                try:
                    job.deadline_s = float(dl)
                except (TypeError, ValueError):
                    pass
            if not leaselib.renew(self.spool, job.id, job.lease):
                job.lease_lost = True
                with self._lock:
                    if not job.stop_reason:
                        self._signal_locked(job, "drain")
                return

    # ---- cross-replica fan-out --------------------------------------------

    def _fanout_dir(self, jid: str) -> str:
        return os.path.join(self.spool, f"fanout.{jid}")

    def _run_fanout(self, job: Job) -> None:
        """Run one big job through the PR 13 range queue INSIDE the
        spool: the holder splits the input into M leased ranges
        (fleet.init_fleet — re-opening after a holder death RESUMES
        the same table, so a mid-fan-out kill costs ~one range), pulls
        ranges alongside any helping sibling replicas, and merges
        under the range-table fence.  Ranges run with the replica's
        warm runtime (shared=) so fan-out costs no recompiles."""
        from ccsx_tpu.parallel import distributed
        from ccsx_tpu.pipeline import fleet

        guard = FlagGuard()
        with self._lock:
            job.attempts += 1
            job.guard = guard
            if job.stop_reason:
                guard.request(job.stop_reason)
        n = job.fanout_holes_n
        m = self.fanout_ranges or min(n, max(2, 2 * self.max_active))
        d = self._fanout_dir(job.id)
        metrics = Metrics(verbose=0, stream=None)
        metrics.job = job.id
        metrics.cid = job.cid
        job.metrics = metrics
        try:
            state = fleet.init_fleet(d, job.in_path, job.out_path, n,
                                     m, self.lease_timeout,
                                     cid=job.cid)
        except (OSError, ValueError) as e:
            job.error = f"fan-out init failed: {e}"
            self._finish(job, "failed", 1)
            return
        rec = spoolproto.read_job_record(self.spool, job.id) or {}
        if rec.get("fanout") != m:
            # advertise the fan-out so sibling replicas pull ranges
            rec["fanout"] = m
            write_json_atomic(
                spoolproto.job_record_path(self.spool, job.id), rec)
        adm = JobAdmission(self.window, job.id)
        rt = _JobRuntime(self.warm, self.warm_cache, guard, adm)
        renew_s = max(0.05, self.lease_timeout / 3.0)
        rc = 0
        try:
            while True:
                if guard.requested:
                    rc = exitcodes.RC_INTERRUPTED
                    break
                progressed = pending = False
                for i in range(m):
                    if guard.requested:
                        break
                    if os.path.exists(
                            distributed.done_path(job.out_path, i)):
                        continue
                    pending = True
                    lr = fleet.try_acquire(d, i, self.replica,
                                           cid=job.cid)
                    if lr is None:
                        # a helper (or a dead helper) holds it: expiry
                        # keeps a killed sibling from pinning a range
                        self._expiry_seq += 1
                        fleet.expire_lease(d, i, self.lease_timeout,
                                           seq=self._expiry_seq)
                        continue
                    stop = threading.Event()
                    t = threading.Thread(
                        target=fleet._renewer,
                        args=(d, i, lr, renew_s, stop), daemon=True)
                    t.start()
                    try:
                        rrc = fleet.run_range(d, state, job.cfg, i,
                                              self.replica,
                                              inflight=job.inflight,
                                              shared=rt)
                    finally:
                        stop.set()
                        t.join(timeout=1.0)
                    fleet.release(d, i, lr)
                    if rrc != 0:
                        rc = rrc
                        break
                    progressed = True
                if rc:
                    break
                if not pending:
                    break
                if not progressed:
                    time.sleep(0.2)  # helpers hold the remaining ranges
            if rc == 0:
                try:
                    distributed.merge_shards(
                        job.out_path, m, expect_table=state["table"])
                except (OSError, ValueError) as e:
                    job.error = f"fan-out merge failed: {e}"
                    rc = 1
        finally:
            adm.close()
            job.snap = metrics.snapshot()
        if rc == 0:
            self._finish(job, "done", exitcodes.RC_OK)
            shutil.rmtree(d, ignore_errors=True)
        elif rc == exitcodes.RC_INTERRUPTED:
            reason = job.stop_reason or guard.reason or "drain"
            if reason == "cancel":
                self._finish(job, "cancelled", rc)
            elif reason == "deadline":
                job.error = (f"job deadline "
                             f"({job.deadline_s:g}s) exceeded")
                self._finish(job, "failed", rc)
            else:
                self._finish(job, "interrupted", rc)
        elif rc == exitcodes.RC_FAILED_HOLES:
            job.error = job.error or "failure budget exceeded"
            self._finish(job, "failed", rc)
        else:
            job.error = job.error or f"rc {rc}"
            self._finish(job, "failed", rc)

    def _maybe_help_fanout(self, jids: List[str]) -> None:
        """Idle capacity pulls ranges of ANOTHER replica's fan-out job
        — the cross-replica half of the fan-out story.  At most one
        new helper per scan tick keeps admission fair."""
        for jid in jids:
            if os.path.exists(
                    spoolproto.done_marker_path(self.spool, jid)):
                continue
            rec = spoolproto.read_job_record(self.spool, jid) or {}
            if not rec.get("fanout") or rec.get("cancel"):
                continue
            hold = leaselib.read_lease(self.spool, jid)
            if not hold or hold.get("pid") == os.getpid():
                continue
            t = self._helpers.get(jid)
            if t is not None and t.is_alive():
                continue
            t = threading.Thread(target=self._help_fanout, args=(jid,),
                                 daemon=True, name=f"ccsx-help-{jid}")
            self._helpers[jid] = t
            t.start()
            return

    def _help_fanout(self, jid: str) -> None:
        from ccsx_tpu.parallel import distributed
        from ccsx_tpu.pipeline import fleet

        d = self._fanout_dir(jid)
        state = fleet.load_fleet(d)
        if state is None:
            return
        m = len(state["ranges"])
        rec = spoolproto.read_job_record(self.spool, jid) or {}
        try:
            # the record's overrides rebuild the HOLDER's exact cfg —
            # identical fingerprint, so helper shards interleave with
            # holder shards under one table
            job = self._build_job(jid, rec.get("input")
                                  or state["input"],
                                  rec.get("overrides") or {})
        except ValueError:
            return
        guard = FlagGuard()
        adm = JobAdmission(self.window, f"{jid}/help")
        rt = _JobRuntime(self.warm, self.warm_cache, guard, adm)
        renew_s = max(0.05, self.lease_timeout / 3.0)
        try:
            while True:
                with self._lock:
                    if self.draining:
                        return
                cur = spoolproto.read_job_record(self.spool, jid) or {}
                if (cur.get("cancel") or os.path.exists(
                        spoolproto.done_marker_path(self.spool, jid))):
                    return
                got = False
                for i in range(m):
                    if os.path.exists(
                            distributed.done_path(state["output"], i)):
                        continue
                    try:
                        lr = fleet.try_acquire(d, i, self.replica,
                                               cid=state.get("cid"))
                    except FileNotFoundError:
                        return  # holder merged and cleaned up: done
                    if lr is None:
                        continue
                    stop = threading.Event()
                    t = threading.Thread(
                        target=fleet._renewer,
                        args=(d, i, lr, renew_s, stop), daemon=True)
                    t.start()
                    try:
                        rrc = fleet.run_range(d, state, job.cfg, i,
                                              self.replica,
                                              inflight=job.inflight,
                                              shared=rt)
                    finally:
                        stop.set()
                        t.join(timeout=1.0)
                    fleet.release(d, i, lr)
                    if rrc != 0:
                        return  # recovery belongs to the holder
                    got = True
                    break  # recheck cancel/drain between ranges
                if not got:
                    return  # nothing free: the holder is finishing
        finally:
            adm.close()

    def _run_job(self, job: Job) -> None:
        from ccsx_tpu.pipeline.batch import run_pipeline_batched

        if self.fleet and job.fanout_holes_n:
            self._run_fanout(job)
            return
        while True:
            guard = FlagGuard()
            with self._lock:
                job.attempts += 1
                job.guard = guard
                if job.stop_reason:
                    # a cancel/drain that raced the attempt start
                    guard.request(job.stop_reason)
            # the job's fault domain: its own spec (or an EMPTY scope —
            # even a faultless job must be isolated from any
            # server-level global plan)
            token = faultinject.scope_arm(job.faults)
            metrics = Metrics(verbose=0, stream=None)
            metrics.job = job.id
            metrics.cid = job.cid
            job.metrics = metrics
            adm = JobAdmission(self.window, job.id)
            rt = _JobRuntime(self.warm, self.warm_cache, guard, adm)
            rc: Optional[int] = None
            try:
                rc = run_pipeline_batched(
                    job.in_path, job.out_path, job.cfg,
                    journal_path=job.journal_path,
                    inflight=job.inflight, metrics=metrics, shared=rt)
            except SystemExit as e:  # argparse-style refusals downstream
                rc = int(e.code or 0) or 1
            except BaseException as e:
                job.error = f"{type(e).__name__}: {e}"
            finally:
                adm.close()
                faultinject.scope_reset(token)
                job.snap = metrics.snapshot()
            if rc == exitcodes.RC_OK:
                self._finish(job, "done", rc)
                return
            if rc == exitcodes.RC_INTERRUPTED:
                reason = job.stop_reason or guard.reason or "drain"
                if reason == "cancel":
                    self._finish(job, "cancelled", rc)
                elif reason == "deadline":
                    job.error = (f"job deadline "
                                 f"({job.deadline_s:g}s) exceeded")
                    self._finish(job, "failed", rc)
                else:
                    # server drain: journal settled, resumable by the
                    # next server process
                    self._finish(job, "interrupted", rc)
                return
            if rc == exitcodes.RC_FAILED_HOLES:
                job.error = job.error or "failure budget exceeded"
                self._finish(job, "failed", rc)
                return
            # rc 1 / unexpected exception: the transient class (ENOSPC,
            # torn write, wedged backend refusal).  The journal makes a
            # retry a RESUME — completed holes are not recomputed and
            # the final bytes stay identical — so bounded
            # retry-and-backoff is cheap and safe.
            if job.attempts > self.retries or job.stop_reason:
                job.error = job.error or f"rc {rc}"
                self._finish(job, "failed",
                             rc if rc is not None else 1)
                return
            delay = self.backoff_s * (2 ** (job.attempts - 1))
            print(f"[ccsx-tpu] serve: job {job.id} attempt "
                  f"{job.attempts} failed ({job.error or f'rc {rc}'}); "
                  f"retrying in {delay:g}s", file=sys.stderr)
            job.error = None
            if job._stop_ev.wait(delay):
                reason = job.stop_reason or "cancel"
                if reason == "cancel":
                    self._finish(job, "cancelled",
                                 exitcodes.RC_INTERRUPTED)
                elif reason == "drain":
                    self._finish(job, "interrupted",
                                 exitcodes.RC_INTERRUPTED)
                else:
                    job.error = (f"job deadline "
                                 f"({job.deadline_s:g}s) exceeded")
                    self._finish(job, "failed",
                                 exitcodes.RC_INTERRUPTED)
                return

    def _finish(self, job: Job, state: str, rc: Optional[int]) -> None:
        with self._lock:
            job.state = state
            job.rc = rc
            job.finished_at = time.time()
            if state == "done":
                self._completed_any = True
            wall = (job.finished_at - job.started_at
                    if job.started_at is not None else None)
        if wall is not None:
            self.metrics.observe("job_wall_s", max(0.0, wall),
                                 size_class(job.fanout_holes_n))
        if job.snap and job.snap.get("hist"):
            # fold the job's fault-domain observations (first dispatch
            # etc.) into the server-lifetime families /metrics serves
            self.metrics.merge_hists(job.snap["hist"])
        if self.fleet and job.lease is not None:
            self._retire_fleet_job(job, state, rc)

    def _retire_fleet_job(self, job: Job, state: str,
                          rc: Optional[int]) -> None:
        """Commit the terminal state to the spool (marker BEFORE lease
        release — the same crash-window ordering as range retirement:
        a kill between the two leaves a done job with a releasable
        lease, never a lost one).  'interrupted' writes NO marker: the
        journal is durable and a survivor resumes the job."""
        if state in spoolproto.MARKER_STATES:
            committed = spoolproto.retire_job(
                self.spool, job.id, state, rc, self.replica,
                error=job.error, output=job.out_path,
                attempts=job.attempts)
            if not committed:
                # the exclusive fence lost: a survivor already retired
                # this job while we were presumed dead — its marker
                # vouches, ours must not
                print(f"[ccsx-tpu] serve: job {job.id} was already "
                      "retired by another replica; yielding to its "
                      "marker", file=sys.stderr)
                with self._lock:
                    job.state = "interrupted"
        leaselib.release(self.spool, job.id, job.lease)

    # ---- control plane ----------------------------------------------------

    def _signal_locked(self, job: Job, reason: str) -> None:
        if not job.stop_reason:
            job.stop_reason = reason
        job._stop_ev.set()
        if job.guard is not None:
            job.guard.request(reason)

    def cancel(self, jid: str):
        """-> (state, changed).  KeyError for an unknown id.  In fleet
        mode a job this replica does NOT hold is cancelled by marking
        the shared spool record — the holder's next heartbeat renewal
        observes the mark and aborts (the cross-replica cancel path
        the gateway uses too)."""
        with self._lock:
            job = self._jobs.get(jid)
            if job is None:
                pass  # fall through to the spool mark below
            elif job.state in TERMINAL:
                return job.state, False
            elif job.state == "queued":
                if job in self._queue:
                    self._queue.remove(job)
                job.state = "cancelled"
                job.rc = exitcodes.RC_INTERRUPTED
                job.finished_at = time.time()
            else:
                self._signal_locked(job, "cancel")
        if job is None:
            if self.fleet:
                return spoolproto.mark_cancel(self.spool, jid)
            raise KeyError(jid)
        if job.state == "cancelled" and self.fleet and job.lease:
            # cancelled before its thread started: retire + release
            # here — no _run_job will do it for us
            self._retire_fleet_job(job, "cancelled", job.rc)
        self._persist()
        return job.state, True

    def _monitor(self) -> None:
        # the deadline tick: --job-deadline (or a per-job deadline_s)
        # bounds wall clock across attempts; exceeding it drains the
        # job through its guard (journal settles — the operator can
        # resubmit with a bigger deadline and it RESUMES)
        while not self._mon_stop.wait(0.2):
            now = time.time()
            with self._lock:
                for job in self._jobs.values():
                    if (job.state == "running" and job.deadline_s > 0
                            and job.started_at is not None
                            and now - job.started_at > job.deadline_s
                            and job.stop_reason is None):
                        self._signal_locked(job, "deadline")

    def drain(self, timeout: float = 600.0) -> int:
        """SIGTERM semantics: stop accepting, drain running jobs
        (their journals settle), persist the queue, report the exit
        rc — 75 (resumable) when unfinished jobs remain, else 0."""
        with self._lock:
            self.accepting = False
            self.draining = True
            running = [j for j in self._jobs.values()
                       if j.state == "running"]
            queued_leased = [j for j in self._jobs.values()
                             if j.state == "queued"
                             and j.lease is not None]
            for job in running:
                self._signal_locked(job, "drain")
            for job in queued_leased:
                # acquired but never started: hand the lease straight
                # back so a survivor picks the job up NOW, not after a
                # timeout
                job.state = "interrupted"
                job.rc = exitcodes.RC_INTERRUPTED
                job.finished_at = time.time()
        if self.fleet:
            self._scan_stop.set()
        for job in queued_leased:
            leaselib.release(self.spool, job.id, job.lease)
        deadline = time.monotonic() + max(0.0, timeout)
        for job in running:
            t = job.thread
            if t is not None:
                t.join(max(0.1, deadline - time.monotonic()))
        for t in list(self._helpers.values()):
            t.join(max(0.1, deadline - time.monotonic()))
        if self._slot_rec is not None:
            leaselib.release(self.spool,
                             f"{spoolproto.SLOT_PREFIX}{self._slot}",
                             self._slot_rec)
            self._slot_rec = None
        self._persist()
        with self._lock:
            resumable = any(j.state in ("queued", "running",
                                        "interrupted")
                            for j in self._jobs.values())
        return exitcodes.RC_INTERRUPTED if resumable else exitcodes.RC_OK

    def close(self) -> None:
        from ccsx_tpu.utils import trace

        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._mon_stop.set()
        self._scan_stop.set()
        self._mon.join(timeout=5.0)
        if self._scan is not None:
            self._scan.join(timeout=5.0)
        if self.warm is not None:
            self.warm.close()
        trace.uninstall()
        self._tracer.close()

    # ---- introspection ----------------------------------------------------

    def job(self, jid: str) -> Optional[Job]:
        with self._lock:
            return self._jobs.get(jid)

    def jobs(self) -> List[dict]:
        with self._lock:
            items = list(self._jobs.values())
        return [j.info() for j in items]

    def job_snapshots(self) -> Dict[str, dict]:
        """job id -> Metrics snapshot, for the ccsx_job_* series."""
        with self._lock:
            items = list(self._jobs.items())
        out = {}
        for jid, job in items:
            snap = job.snap
            if snap is None and job.metrics is not None:
                snap = job.metrics.snapshot()
            if snap:
                out[jid] = snap
        return out

    def counts(self) -> dict:
        with self._lock:
            c = {"jobs": len(self._jobs), "running": self._n_running,
                 "queued": sum(1 for j in self._jobs.values()
                               if j.state == "queued")}
        return c

    def wait(self, jid: str, timeout: float = 120.0) -> str:
        """Block until the job reaches a terminal state (tests).  In
        fleet mode a job not held locally is waited on through the
        spool view — it may be running on ANY replica."""
        deadline = time.monotonic() + timeout
        state = None
        while time.monotonic() < deadline:
            job = self.job(jid)
            if job is not None:
                state = job.state
                if state in TERMINAL:
                    return state
            elif self.fleet:
                view = spoolproto.job_view(self.spool, jid)
                if view is None:
                    raise KeyError(jid)
                state = view["state"]
                if state in spoolproto.MARKER_STATES:
                    return state
            else:
                raise KeyError(jid)
            time.sleep(0.02)
        return state

    def readiness(self):
        """The /readyz hook: (ready, reason).  NOT tied to degraded —
        a tenant-induced hang degrades that JOB to the host rung while
        the server keeps taking traffic (the chaos-soak criterion)."""
        with self._lock:
            if self.draining:
                return False, "draining"
            queued = sum(1 for j in self._jobs.values()
                         if j.state == "queued")
            if queued >= self.max_queue:
                return False, "queue full"
            cold = not self._completed_any
        if cold and self.warm is not None and self.warm.busy():
            return False, "warming"
        return True, "ok"

    # ---- restart persistence ----------------------------------------------

    def _persist(self) -> None:
        if self.fleet:
            # fleet mode has no state.json: the spool records, leases
            # and markers ARE the durable state, shared by all replicas
            return
        with self._lock:
            recs = []
            for j in self._jobs.values():
                recs.append({
                    "id": j.id, "state": j.state, "rc": j.rc,
                    "input": j.in_path, "output": j.out_path,
                    "journal": j.journal_path, "error": j.error,
                    "attempts": j.attempts,
                    "overrides": j.raw_overrides,
                    "submitted_at": j.submitted_at,
                    "finished_at": j.finished_at,
                })
            state = {"version": 1, "seq": self._seq, "jobs": recs}
        try:
            # serialized: concurrent job threads persisting at once
            # would race on the same .tmp sidecar
            with self._persist_lock:
                write_json_atomic(os.path.join(self.spool, STATE_FILE),
                                  state)
        except OSError as e:
            print(f"[ccsx-tpu] serve: state persist failed: {e}",
                  file=sys.stderr)

    def _restore_state(self) -> None:
        path = os.path.join(self.spool, STATE_FILE)
        try:
            with open(path, encoding="utf-8") as f:
                state = json.load(f)
        except (OSError, ValueError):
            return
        self._seq = int(state.get("seq") or 0)
        for rec in state.get("jobs") or []:
            try:
                job = self._build_job(rec["id"], rec["input"],
                                      rec.get("overrides") or {})
            except (KeyError, ValueError):
                continue
            job.out_path = rec.get("output") or job.out_path
            job.journal_path = rec.get("journal") or job.journal_path
            job.rc = rec.get("rc")
            job.error = rec.get("error")
            job.attempts = int(rec.get("attempts") or 0)
            job.submitted_at = rec.get("submitted_at") or time.time()
            job.finished_at = rec.get("finished_at")
            prev = rec.get("state")
            if prev in ("done", "failed", "cancelled"):
                job.state = prev  # history only
            else:
                # queued / running / interrupted at the old server's
                # exit: requeue — the per-job journal resumes them to
                # byte-identical outputs
                job.state = "queued"
                job.attempts = 0
                job.finished_at = None
            self._jobs[job.id] = job
            if job.state == "queued":
                self._queue.append(job)


# ---- the HTTP layer -------------------------------------------------------

def _serve_handler():
    """Build the serve request handler lazily (keeps telemetry.py
    import-light paths — stats/top — from importing this module)."""
    from ccsx_tpu.utils import telemetry
    from ccsx_tpu.utils.metrics import resource_gauges

    class _ServeHandler(telemetry._Handler):
        server_version = "ccsx-tpu-serve"

        def _core(self) -> ServeCore:
            return self.server.ccsx_core  # type: ignore[attr-defined]

        def _send_json(self, code: int, obj, extra=None) -> None:
            data = json.dumps(obj, default=str).encode("utf-8")
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(data)))
            for k, v in (extra or {}).items():
                self.send_header(k, str(v))
            self.end_headers()
            self.wfile.write(data)

        def _send_file(self, path: str) -> None:
            try:
                size = os.path.getsize(path)
                f = open(path, "rb")
            except OSError as e:
                self._send_json(404, {"error": f"no output: {e}"})
                return
            with f:
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; charset=utf-8")
                self.send_header("Content-Length", str(size))
                self.end_headers()
                while True:
                    chunk = f.read(1 << 16)
                    if not chunk:
                        break
                    self.wfile.write(chunk)

        def do_GET(self):  # noqa: N802
            core = self._core()
            path, _, _q = self.path.partition("?")
            try:
                if path == "/healthz":
                    # LIVENESS: answers "is the process serving?" —
                    # always 200 while it is.  Per-job degradation
                    # lives in /jobs/<id> and the ccsx_job_* series;
                    # routability lives in /readyz.
                    self._send_json(200, {"status": "alive",
                                          **core.counts()})
                elif path == "/metrics":
                    body = telemetry.render_prometheus(
                        core.metrics.snapshot(), resource_gauges())
                    body += telemetry.render_job_series(
                        core.job_snapshots())
                    self._send(200, body,
                               "text/plain; version=0.0.4; "
                               "charset=utf-8")
                elif path == "/jobs":
                    if core.fleet:
                        from ccsx_tpu.pipeline import gateway as gw

                        jobs = [gw.job_view(core.spool, jid)
                                for jid in gw.list_job_ids(core.spool)]
                        self._send_json(200, {"jobs": jobs})
                    else:
                        self._send_json(200, {"jobs": core.jobs()})
                elif path.startswith("/jobs/"):
                    parts = path.split("/")
                    job = core.job(parts[2])
                    view = None
                    if job is None and core.fleet:
                        # a fleet job another replica holds (or no one
                        # does yet): answer from the shared spool
                        from ccsx_tpu.pipeline import gateway as gw

                        view = gw.job_view(core.spool, parts[2])
                    if job is None and view is None:
                        self._send_json(404, {"error": "unknown job"})
                    elif len(parts) == 3:
                        self._send_json(200, job.info() if job
                                        else view)
                    elif len(parts) == 4 and parts[3] == "output":
                        state = job.state if job else view["state"]
                        if state != "done":
                            self._send_json(
                                409, {"error": "job not done",
                                      "state": state})
                        else:
                            self._send_file(job.out_path if job
                                            else view.get("output")
                                            or "")
                    else:
                        self._send_json(404, {"error": "unknown path"})
                else:
                    super().do_GET()
            except (BrokenPipeError, ConnectionResetError):
                pass

        def do_POST(self):  # noqa: N802
            core = self._core()
            path, _, query = self.path.partition("?")
            try:
                if path != "/jobs":
                    self._send_json(404, {"error": "unknown path"})
                    return
                import urllib.parse

                params = {k: v[-1] for k, v in
                          urllib.parse.parse_qs(query).items()}
                length = int(self.headers.get("Content-Length") or 0)
                ctype = (self.headers.get("Content-Type") or
                         "").split(";")[0].strip().lower()
                try:
                    if ctype == "application/json":
                        raw = self.rfile.read(length)
                        body = json.loads(raw or b"{}")
                        if not isinstance(body, dict):
                            raise ValueError("JSON body must be an "
                                             "object")
                        params.update(body)
                        input_path = params.pop("input", None)
                        job = core.submit(input_path=input_path,
                                          overrides=params)
                    else:
                        # streamed BAM/FASTQ body (?format=... names
                        # the container; default bam)
                        job = core.submit(body_stream=self.rfile,
                                          body_len=length,
                                          overrides=params)
                except QueueFull as e:
                    self._send_json(429, {"error": str(e)},
                                    extra={"Retry-After": 5})
                    return
                except Draining as e:
                    self._send_json(503, {"error": str(e)})
                    return
                except (ValueError, OSError) as e:
                    self._send_json(400, {"error": str(e)})
                    return
                self._send_json(201, {"id": job.id,
                                      "state": job.state,
                                      "status": f"/jobs/{job.id}",
                                      "output":
                                      f"/jobs/{job.id}/output"})
            except (BrokenPipeError, ConnectionResetError):
                pass

        def do_DELETE(self):  # noqa: N802
            core = self._core()
            path, _, _q = self.path.partition("?")
            try:
                parts = path.split("/")
                if len(parts) != 3 or parts[1] != "jobs":
                    self._send_json(404, {"error": "unknown path"})
                    return
                try:
                    state, changed = core.cancel(parts[2])
                except KeyError:
                    self._send_json(404, {"error": "unknown job"})
                    return
                self._send_json(200 if changed else 409,
                                {"id": parts[2], "state": state,
                                 "cancelled": changed})
            except (BrokenPipeError, ConnectionResetError):
                pass

    return _ServeHandler


# ---- the subcommand -------------------------------------------------------

def serve_main(argv) -> int:
    """`ccsx-tpu serve`: parse serve flags, hand the rest to the
    normal CLI parser for the compute config, run until SIGTERM."""
    import argparse

    from ccsx_tpu import cli
    from ccsx_tpu.utils.drain import DrainGuard
    from ccsx_tpu.utils import telemetry

    ap = argparse.ArgumentParser(
        prog="ccsx-tpu serve",
        description="Resident multi-tenant consensus server: one warm "
                    "runtime, per-job fault isolation, HTTP job API "
                    "on the telemetry stack.  Unrecognized flags are "
                    "the compute config (same flags as a plain run).")
    ap.add_argument("--port", type=int, default=8855,
                    help="HTTP port (auto-bumps when taken; 0 = one "
                         "ephemeral port) [8855]")
    ap.add_argument("--serve-host", default="",
                    help="bind host [CCSX_TELEMETRY_HOST or 0.0.0.0]")
    ap.add_argument("--spool", default=".ccsx_serve",
                    help="spool directory: job inputs/outputs/journals "
                         "+ state.json (restart resumes it) "
                         "[.ccsx_serve]")
    ap.add_argument("--max-queue", type=int, default=16,
                    help="queued-job cap; submissions beyond it get "
                         "HTTP 429 + Retry-After [16]")
    ap.add_argument("--max-active", type=int, default=2,
                    help="concurrently running jobs (they share the "
                         "admission window fairly) [2]")
    ap.add_argument("--job-retries", type=int, default=1,
                    help="retry budget for transient (rc 1) job "
                         "failures; retries RESUME from the job "
                         "journal [1]")
    ap.add_argument("--retry-backoff", type=float, default=0.5,
                    help="base backoff seconds between retries "
                         "(doubles per attempt) [0.5]")
    ap.add_argument("--job-deadline", type=float, default=0.0,
                    help="default per-job wall-clock deadline in "
                         "seconds, across retries (0 = none; jobs can "
                         "set their own deadline_s) [0]")
    ap.add_argument("--fleet", default=None, metavar="SPOOL",
                    help="run as one replica of a serve FLEET sharing "
                         "SPOOL as a job lease domain (replaces "
                         "--spool; jobs are leased, replica death "
                         "requeues them, `ccsx-tpu gateway` balances)")
    ap.add_argument("--replica-name", default=None,
                    help="replica identity in leases/markers "
                         "[s<pid>]")
    ap.add_argument("--lease-timeout", type=float, default=10.0,
                    help="job-lease heartbeat timeout seconds (fleet "
                         "mode) [10]")
    ap.add_argument("--fanout-holes", type=int, default=0,
                    help="fan a job out across replicas through the "
                         "range queue when it has at least this many "
                         "holes (0 = never) [0]")
    ap.add_argument("--fanout-ranges", type=int, default=0,
                    help="range count M for fan-out jobs (0 = auto; "
                         "must match across replicas) [0]")
    ap.add_argument("--poll", type=float, default=0.25,
                    help="fleet spool scan interval seconds [0.25]")
    a, rest = ap.parse_known_args(argv)
    cli_args = cli.build_parser().parse_args(rest)
    if cli_args.help:
        ap.print_help()
        return 1
    for flag, bad in (("--bam", cli_args.bam_out),
                      ("--hosts", cli_args.hosts is not None),
                      ("--fleet-dir", cli_args.fleet_dir is not None),
                      ("--merge-shards",
                       cli_args.merge_shards is not None),
                      ("--make-index", cli_args.make_index)):
        if bad:
            print(f"Error: {flag} is not supported under serve",
                  file=sys.stderr)
            return 1
    try:
        cfg = cli.config_from_args(cli_args)
    except SystemExit as e:
        return int(e.code or 0)
    if cli_args.inject_faults:
        # server-level chaos plan (benchmarks/serve_chaos.py): fires
        # only on threads OUTSIDE any job scope — jobs are isolated in
        # their own (possibly empty) fault domains
        try:
            faultinject.arm(cli_args.inject_faults)
        except ValueError as e:
            print(f"Error: --inject-faults: {e}", file=sys.stderr)
            return 1

    guard = DrainGuard.install()
    spool = a.fleet or a.spool
    core = ServeCore(cfg, spool=spool, max_queue=a.max_queue,
                     max_active=a.max_active, retries=a.job_retries,
                     backoff_s=a.retry_backoff,
                     job_deadline_s=a.job_deadline,
                     fleet=bool(a.fleet), replica=a.replica_name,
                     lease_timeout=a.lease_timeout,
                     fanout_holes=a.fanout_holes,
                     fanout_ranges=a.fanout_ranges, poll_s=a.poll)
    port = a.port
    if a.fleet:
        # deterministic co-hosted ports: replica in slot k serves on
        # base_port + k, and the slot lease advertises the ACTUAL
        # bound port — gateway/top discover it, never guess it
        slot = core.register_replica()
        if port:
            port = port + slot
    try:
        srv = telemetry.TelemetryServer(
            core.metrics, port, host=a.serve_host,
            handler=_serve_handler(),
            attrs={"ccsx_core": core, "ccsx_ready": core.readiness})
    except OSError as e:
        print(f"Error: serve: {e}", file=sys.stderr)
        core.close()
        guard.restore()
        return 1
    core.set_advertised(srv.port)
    mode = (f"fleet replica {core.replica} slot {core._slot}"
            if a.fleet else "solo")
    print(f"[ccsx-tpu] serve: http://{srv.host}:{srv.port} "
          "(POST /jobs, GET /jobs/<id>, /readyz, /metrics; "
          f"spool {spool}; {mode})", file=sys.stderr)
    try:
        while not guard.requested:
            time.sleep(0.2)
        print("[ccsx-tpu] serve: draining — no new jobs, settling "
              "in-flight journals (resumable rc 75)", file=sys.stderr)
        rc = core.drain()
    finally:
        srv.close()
        core.close()
        guard.restore()
    if rc == exitcodes.RC_INTERRUPTED:
        print("[ccsx-tpu] serve: drained with unfinished jobs; "
              "restart the same command to resume them",
              file=sys.stderr)
    return rc
