"""ZMW group-by-hole streaming over any subread record source.

Equivalent of the reference's kseq_zmw_read (seqio.h:152-201): subread names
follow the PacBio convention ``movie/hole/region``; consecutive records with
the same (movie, hole) belong to one ZMW and are accumulated into a single
concatenated buffer plus a lengths vector.  A name that does not split into
exactly 3 '/'-fields is invalid (seqio.h:168-172; the reference kills the
whole stream there — we raise by default, or quarantine when configured).

Filters (applied by the pipeline's read step in the reference,
main.c:659-672) are provided here as `zmw_filter`.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Iterator, List, Optional

import numpy as np

from ccsx_tpu.config import CcsConfig
from ccsx_tpu.io.corruption import CorruptionError
from ccsx_tpu.io.fastx import FastxRecord
from ccsx_tpu.utils import trace


class InvalidZmwName(CorruptionError):
    """Malformed movie/hole/region subread name — classified
    ``zmw_bad_name`` in the corruption taxonomy (io/corruption.py);
    still a ValueError for pre-taxonomy handlers."""

    def __init__(self, msg: str):
        super().__init__("zmw_bad_name", msg)


@dataclasses.dataclass
class Zmw:
    """One hole's worth of subreads (reference zmw_t, main.c:42-48)."""

    movie: str
    hole: str
    seqs: bytes                # concatenated subread bases (ASCII)
    lens: np.ndarray           # int32 per-subread lengths
    offs: np.ndarray           # int32 prefix offsets into seqs
    ccs: Optional[bytes] = None   # filled by the consensus stage

    @property
    def n_passes(self) -> int:
        return len(self.lens)

    @property
    def total_len(self) -> int:
        return len(self.seqs)

    def subread(self, i: int) -> bytes:
        o = int(self.offs[i])
        return self.seqs[o:o + int(self.lens[i])]


def split_name(name: str) -> tuple:
    fields = name.split("/")
    if len(fields) != 3:
        raise InvalidZmwName(f"invalid zmw name :{name}")
    return fields[0], fields[1], fields[2]


def group_zmws(records: Iterable[FastxRecord],
               salvage=None) -> Iterator[Zmw]:
    """Group consecutive records by (movie, hole) into Zmw objects.

    A malformed name kills the whole stream by default (reference
    parity, seqio.h:168-172); with ``salvage`` (a
    corruption.SalvageSink) the poisoned record is dropped and booked
    as ``zmw_bad_name``, and grouping re-anchors on the next record —
    the hole the record truly belonged to emits from its surviving
    passes (the native streamer applies the same rule in-library)."""
    cur_key = None
    cur_seqs: List[bytes] = []
    for rec in records:
        try:
            movie, hole, _region = split_name(rec.name)
        except InvalidZmwName:
            if salvage is None:
                raise
            salvage.record("zmw_bad_name")
            continue
        key = (movie, hole)
        if cur_key is None:
            cur_key, cur_seqs = key, [rec.seq]
        elif key == cur_key:
            cur_seqs.append(rec.seq)
        else:
            yield _build(cur_key, cur_seqs)
            cur_key, cur_seqs = key, [rec.seq]
    if cur_key is not None:
        yield _build(cur_key, cur_seqs)


def _build(key: tuple, seqs: List[bytes]) -> Zmw:
    lens = np.array([len(s) for s in seqs], dtype=np.int32)
    offs = np.zeros(len(seqs), dtype=np.int32)
    if len(seqs) > 1:
        np.cumsum(lens[:-1], out=offs[1:])
    return Zmw(movie=key[0], hole=key[1], seqs=b"".join(seqs),
               lens=lens, offs=offs)


def filter_reason(zmw: Zmw, cfg: CcsConfig) -> Optional[str]:
    """None when the hole passes the read-step filters
    (main.c:659-672), else the drop-reason bucket — the same reason
    taxonomy the native streamer reports (ccsx_filter_counts)."""
    if zmw.n_passes < cfg.min_pass_count:
        return "few_passes"
    total = zmw.total_len
    if total > cfg.max_subread_len:
        return "too_long"
    if total < cfg.min_subread_len:
        return "too_short"
    if cfg.exclude_holes and zmw.hole in cfg.exclude_holes:
        return "excluded"
    return None


def zmw_filter(zmw: Zmw, cfg: CcsConfig) -> bool:
    """Keep/drop rule of the pipeline read step (main.c:659-672)."""
    return filter_reason(zmw, cfg) is None


def stream_zmws(records: Iterable[FastxRecord], cfg: CcsConfig,
                metrics=None, salvage=None) -> Iterator[Zmw]:
    for z in group_zmws(records, salvage=salvage):
        reason = filter_reason(z, cfg)
        if reason is None:
            yield z
        else:
            # filtered holes are otherwise invisible in a trace: the
            # driver's ingest spans only see what this generator
            # yields.  Counted into Metrics (reason-bucketed) when the
            # driver passes its object; the native C++ streamer
            # (native/io.py) applies the same filters in-library and
            # surfaces its counts at stream EOF instead
            if metrics is not None:
                metrics.holes_filtered += 1
                metrics.filtered_reasons[reason] = (
                    metrics.filtered_reasons.get(reason, 0) + 1)
            trace.instant("zmw_filtered", cat="ingest", hole=z.hole,
                          passes=z.n_passes, bases=z.total_len,
                          reason=reason)
