"""Long-template (ultra-long-read) benchmark: the pre-alignment plane's
A/B (ISSUE 11 / ROADMAP item 4).

The long-template regime is where per-pair host seeding and full-length
strand_match DPs become the ceiling again: at >= 50kb a WRONG-strand
doubtful pass slips past the legacy votes>=3 seed gate essentially
always (measured 28-30/30) and pays a multi-second doomed banded DP
before the RC arm even starts.  The prefilter (ops/sketch.py) kills
that arm in one batched screen row, and --seed-device-min-t moves the
surviving pairs' k-mer seeding off the host (ops/seed_device.py).

Scenarios (each a synthetic FASTA through the full CLI, CPU fake
device unless a real backend resolves):

* ``NxL`` (default corpus: interrupted traversals) — N molecules at L
  bases.  At ultra-long template lengths most polymerase traversals
  terminate mid-pass (polymerase death / laser events; at 50-100kb the
  per-traversal completion odds are well under half), so complete
  passes arrive SEPARATED by short partial-pass fragments.  The corpus
  models the adversarial-but-canonical form of that regime: every
  third traversal completes (so complete passes still alternate
  strand), the two between yield 12-40% head fragments.  Fragments
  fall outside the template length group and are skipped by the walk
  — but each one breaks strand-parity trust, so EVERY complete pass
  is alignment-verified (the reference's main.c:392-406 walk at its
  most expensive), fwd arm first; the ~half that are reverse-strand
  are the doomed-DP population the prefilter exists for.
* ``NxLdK`` — the partials corpus with a DOUBLY-LOADED well: K passes
  from a second, unrelated molecule of in-group length (0.97x) are
  interleaved into the back half of the subread stream.  ZMW loading
  is Poisson, so two-molecule wells are a standing fraction of every
  real run, and at ultra-long insert sizes each contaminant pass is
  the filter's canonical hopeless pairing: it survives the legacy
  votes>=3 chance-hit gate at these lengths and pays TWO full doomed
  DPs (fwd then RC, both rejected) in the control arm, while the
  sketch's noise gate kills both arms for the cost of a screen row.
* ``NxLrt`` — the r04-style read-through corpus: regular passes plus
  TWO read-through (missed-adapter) passes flanking the template pass.
  Exercises the out-of-group path (where fwd+RC speculation must NOT
  fire: a read-through carries both strands) and the 2x-template
  query shapes.
* the 100kb single-molecule scenario extends the r04 series (8kb/20kb)
  two octaves: windows scale linearly, DP memory stays flat, and the
  prep plane's share becomes visible.

Both arms run with ``--slab-rows 32`` (artifact-recorded): the
long-molecule regime has ~8 segment rows per hole, and the default
128-row canonical slabs pad the window-refine plane to ~12% fill —
right-sizing the slab is orthogonal tuning that makes the CONTROL arm
faster too, so the prefilter win is measured against the strongest
baseline, not a bloated one.

Arms, interleaved A/B/A/B after one unmeasured warm lap each (the
repo's timing hygiene: jit caches warm, arms alternate so drift hits
both equally):

* ``on``  — --prefilter on,  --seed-device-min-t <crossover>
* ``off`` — --prefilter off, --seed-device-min-t 0  (the legacy path:
  host argsort seeding, every doubtful arm pays its DP)

Output bytes are asserted IDENTICAL between arms on every scenario
(the conservativeness contract), and the artifact records per-arm wall
plus the screen/seeding counters.

Usage:
  python benchmarks/long_molecule.py
      [--scenarios 4x50000,4x50000d4,1x100000d4] [--passes 6]
      [--laps 2] [--json benchmarks/long_molecule_rNN.json]
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import re
import sys
import tempfile
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from ccsx_tpu import cli                                     # noqa: E402
from ccsx_tpu.ops import encode as enc                       # noqa: E402
from ccsx_tpu.utils import synth                             # noqa: E402

ERR = dict(sub_rate=0.02, ins_rate=0.05, del_rate=0.05)
# the partials (ultra-long) corpus runs a modern-chemistry ~5% per-pass
# error mix: at 12% the pass-vs-pass indel random walk out-drifts the
# +-64-diagonal band by 50kb (2*(ins+del)*L variance) and every
# verification fails in BOTH arms — no real instrument pairs 12%
# passes with 100kb templates
ERR_LONG = dict(sub_rate=0.01, ins_rate=0.02, del_rate=0.02)

ARMS = {
    # crossover 16384 == the CLI default; spelled out so the artifact
    # is self-describing
    "on": ["--prefilter", "on", "--seed-device-min-t", "16384"],
    "off": ["--prefilter", "off", "--seed-device-min-t", "0"],
}


def make_long_fasta(path: str, holes: int, tlen: int, n_passes: int,
                    seed: int, corpus: str = "partials",
                    dual: int = 0) -> None:
    """``holes`` molecules at ``tlen``.

    ``partials`` (default): ``n_passes`` COMPLETE traversals with two
    interrupted traversals (12-40% head fragments, correct alternating
    strand) between each consecutive pair — the ultra-long regime's
    canonical shape, where parity trust never survives and every
    complete pass is alignment-verified.  One complete traversal per
    three keeps the complete passes themselves strand-alternating, so
    ~half the verifications try the doomed wrong-strand arm first.

    ``dual``: passes from a SECOND unrelated molecule (0.97x length —
    in-group under the 10% clustering tolerance) inserted into the
    back half of the stream, each right after a fragment so it lands
    doubtful (alignment-verified) and late enough that the group's
    median-by-index template pick stays on the first molecule.

    ``rt``: the r04-style corpus — two read-through passes flanking
    the (median) template pass."""
    rng = np.random.default_rng(seed)
    zs = []
    for h in range(holes):
        if corpus == "rt":
            z = synth.make_zmw(rng, template_len=tlen,
                               n_passes=n_passes, movie="mv",
                               hole=str(h), **ERR)
            mid = len(z.passes) // 2
            for at in (max(mid - 1, 0), min(mid + 2, len(z.passes))):
                z.passes.insert(at, synth.read_through(rng, z.template,
                                                       **ERR))
                z.strands.insert(at, 0)
        else:
            t = rng.integers(0, 4, tlen).astype(np.uint8)
            passes, strands = [], []
            n_trav = 3 * n_passes - 2
            for trav in range(n_trav):
                strand = trav % 2
                p = synth.mutate(rng, t, **ERR_LONG)
                if strand:
                    p = enc.revcomp_codes(p)
                if trav % 3:   # interrupted traversal: head fragment
                    keep = int(len(p) * (0.12 + 0.28 * rng.random()))
                    p = p[:max(keep, 1200)]
                passes.append(p)
                strands.append(strand)
            if dual:
                t2 = rng.integers(0, 4, int(tlen * 0.97)).astype(np.uint8)
                # every contaminant pass sits just before the LAST
                # complete traversal: late enough that the group's
                # median-BY-INDEX template pick stays on the first
                # molecule (spreading them earlier flipped the
                # representative to the contaminant), and each lands
                # doubtful — the first follows a fragment, the rest
                # follow a rejected pass, and rejection keeps the
                # walk's strand_adjust set
                # in-group ids are [n-1 A's, K B's, last A]; the median
                # ids[(n+K)//2] stays on an A pass iff K <= n-3
                assert dual <= n_passes - 3, \
                    "contaminant would capture the median template pick"
                at = len(passes) - 1
                for j in range(dual):
                    p = synth.mutate(rng, t2, **ERR_LONG)
                    if j % 2:
                        p = enc.revcomp_codes(p)
                    passes.insert(at, p)
                    strands.insert(at, j % 2)
            z = synth.SynthZmw(movie="mv", hole=str(h), template=t,
                               passes=passes, strands=strands)
        zs.append(z)
    with open(path, "w") as f:
        f.write(synth.make_fasta(zs))


SLAB_ROWS = "32"   # right-sized for ~8-row holes (see module docstring)


def run_arm(fa: str, tmp: str, tag: str, extra, metrics_keys=()) -> dict:
    out = os.path.join(tmp, f"out_{tag}.fa")
    mpath = os.path.join(tmp, f"m_{tag}.jsonl")
    t0 = time.perf_counter()
    # -M 4M: the read-step filter bounds TOTAL hole length (main.c:659
    # semantics) and a 100kb molecule at 6+ passes crosses the 500k
    # default — raising it is what "opening the ultra-long-read
    # scenario" means at the CLI
    rc = cli.main(["-A", "-m", "1000", "-M", "4000000", "--batch", "on",
                   "--slab-rows", SLAB_ROWS,
                   "--metrics", mpath, *extra, fa, out])
    dt = time.perf_counter() - t0
    assert rc == 0, f"arm {tag} rc={rc}"
    final = [json.loads(ln) for ln in open(mpath)][-1]
    md5 = hashlib.md5(open(out, "rb").read()).hexdigest()
    rec = {"seconds": round(dt, 2), "md5": md5}
    for k in metrics_keys:
        rec[k] = final.get(k)
    return rec


COUNTER_KEYS = ("pair_alignments", "pairs_screened", "pairs_prefiltered",
                "prefilter_share", "pairs_seeded_device",
                "pairs_seeded_host", "windows", "prep_share",
                "prep_blocked_s")


def run_scenario(holes: int, tlen: int, n_passes: int, laps: int,
                 seed: int, corpus: str = "partials",
                 dual: int = 0) -> dict:
    with tempfile.TemporaryDirectory() as tmp:
        fa = os.path.join(tmp, "in.fa")
        make_long_fasta(fa, holes, tlen, n_passes, seed, corpus=corpus,
                        dual=dual)
        # one unmeasured warm lap per arm (cold compiles amortize out),
        # then `laps` interleaved measured laps per arm
        warm = {a: run_arm(fa, tmp, f"warm_{a}", ARMS[a], COUNTER_KEYS)
                for a in ARMS}
        md5s = {a: warm[a]["md5"] for a in ARMS}
        assert len(set(md5s.values())) == 1, \
            f"ARMS NOT BYTE-IDENTICAL: {md5s}"
        walls = {a: [] for a in ARMS}
        for lap in range(laps):
            for a in ARMS:
                walls[a].append(
                    run_arm(fa, tmp, f"l{lap}_{a}", ARMS[a])["seconds"])
        best = {a: min(w) for a, w in walls.items()}
        win = 1.0 - best["on"] / best["off"]
        return {
            "holes": holes, "template_len": tlen, "n_passes": n_passes,
            "corpus": corpus, "dual_passes": dual,
            "slab_rows": int(SLAB_ROWS),
            "md5": next(iter(md5s.values())),
            "arms": {a: {"walls_s": walls[a], "best_s": best[a],
                         "counters": {k: warm[a][k]
                                      for k in COUNTER_KEYS}}
                     for a in ARMS},
            "prefilter_win_pct": round(win * 100, 1),
        }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scenarios", default="4x50000,4x50000d4,1x100000d4",
                    help="comma list of HOLESxTLEN, optional 'rt' "
                         "(read-through corpus) or 'dK' (doubly-loaded "
                         "well, K contaminant passes) suffix "
                         "[4x50000,4x50000d4,1x100000d4]")
    ap.add_argument("--passes", type=int, default=6)
    ap.add_argument("--laps", type=int, default=2,
                    help="measured interleaved laps per arm [2]")
    ap.add_argument("--seed", type=int, default=11)
    ap.add_argument("--json", default=None)
    a = ap.parse_args()

    from ccsx_tpu.utils.device import resolve_device

    resolve_device("auto")
    import jax

    out = {
        "note": "pre-alignment plane A/B on the long-template regime: "
                "--prefilter on + device seeding vs off + host seeding, "
                "interleaved after a warm lap, bytes asserted identical "
                "per scenario (see benchmarks/long_molecule.py)",
        "backend": jax.default_backend(),
        "seed": a.seed, "laps": a.laps,
        "scenarios": [],
    }
    for spec in a.scenarios.split(","):
        spec = spec.lower()
        m = re.fullmatch(r"(\d+)x(\d+)(rt|d(\d+))?", spec)
        assert m, f"bad scenario spec: {spec!r}"
        holes, tlen = int(m.group(1)), int(m.group(2))
        corpus = "rt" if m.group(3) == "rt" else "partials"
        dual = int(m.group(4)) if m.group(4) else 0
        print(f"[long_molecule] scenario {spec} ...", file=sys.stderr)
        r = run_scenario(holes, tlen, a.passes, a.laps, a.seed,
                         corpus=corpus, dual=dual)
        print(f"[long_molecule] {spec}: on {r['arms']['on']['best_s']}s"
              f" off {r['arms']['off']['best_s']}s"
              f" win {r['prefilter_win_pct']}%", file=sys.stderr)
        out["scenarios"].append(r)
    s = json.dumps(out, indent=1)
    print(s)
    if a.json:
        with open(a.json, "w") as f:
            f.write(s + "\n")


if __name__ == "__main__":
    main()
