"""int32-overflow: traced index products that wrap before the divide.

The shipped bug, twice: ``(i - li0) * (lj1 - lj0) // max(li1 - li0, 1)``
— a nominal-line interpolation whose int32 product exceeds 2**31 past
~47kb templates, truncating every long-pair band (fixed in r11 in
``ops/banded._line_interp`` and again in r14 where
``compute_offsets`` had re-derived the same expression).  jax traces
integers as int32 by default, so the wrap is silent: no exception, no
NaN, just a wrong band and a quietly bad consensus.

Rule (scoped to ``ops/`` modules, where code runs under jit/pallas and
operands are traced): flag

- ``X * Y // Z`` where neither factor is a literal — the exact shape
  of both historical bugs — and
- ``X << Y`` with a non-literal shift amount (same wrap, different
  operator),

unless the expression carries an int64 promotion (``astype(jnp.int64)``
/ ``jnp.int64(...)`` / an ``"int64"`` dtype string) or a factor is
already limb-reduced (``>>``/``&`` subexpressions — the
``_line_interp`` idiom keeps every partial product under 2**31 by
splitting into 8-bit limbs).

The fix is never "suppress": route through ``ops/banded._line_interp``
(exact floor semantics, negative-safe) or promote to int64 explicitly.
"""

from __future__ import annotations

import ast
from pathlib import PurePosixPath
from typing import Iterable, List, Sequence

from ccsx_tpu.lint.core import Finding

CHECK = "int32-overflow"

MESSAGE = ("traced int32 product feeds a floor-div without int64 "
           "promotion or limb reduction (the pre-r11 _line_interp / "
           "pre-r14 compute_offsets wrap): use ops/banded._line_interp "
           "or promote with .astype(jnp.int64)")
MESSAGE_SHIFT = ("traced int32 value shifted by a traced amount without "
                 "int64 promotion — the product wraps silently under "
                 "jit; promote with .astype(jnp.int64)")


def _applies(relpath: str) -> bool:
    return "ops" in PurePosixPath(relpath).parts


def _is_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.UnaryOp):
        return _is_literal(node.operand)
    if isinstance(node, ast.Name) and node.id.isupper():
        return True  # ALL_CAPS module constant — a static python int
    return False


def _has_int64(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and "int64" in sub.id:
            return True
        if isinstance(sub, ast.Attribute) and "int64" in sub.attr:
            return True
        if (isinstance(sub, ast.Constant) and isinstance(sub.value, str)
                and "int64" in sub.value):
            return True
    return False


def _limb_reduced(node: ast.AST) -> bool:
    """8-bit-limb split markers: the factor was built from ``>>``/``&``
    pieces, so each partial product is bounded by construction."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.BinOp) and isinstance(
                sub.op, (ast.RShift, ast.BitAnd)):
            return True
    return False


def _line_text(lines: Sequence[str], lineno: int) -> str:
    return lines[lineno - 1].strip() if 1 <= lineno <= len(lines) else ""


def check(tree: ast.AST, src: str, lines: Sequence[str],
          relpath: str) -> Iterable[Finding]:
    if not _applies(relpath):
        return []
    out: List[Finding] = []
    # only function bodies: module-level arithmetic runs once at import
    # time on concrete python ints — nothing there is ever traced
    funcs = [n for n in ast.walk(tree)
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
    seen = set()
    for fn in funcs:
        for node in ast.walk(fn):
            if id(node) in seen or not isinstance(node, ast.BinOp):
                continue
            seen.add(id(node))
            if isinstance(node.op, ast.FloorDiv) and isinstance(
                    node.left, ast.BinOp) and isinstance(
                    node.left.op, ast.Mult):
                mult = node.left
                if _is_literal(mult.left) or _is_literal(mult.right):
                    continue
                if _has_int64(node):
                    continue
                if _limb_reduced(mult.left) or _limb_reduced(mult.right):
                    continue
                out.append(Finding(CHECK, relpath, node.lineno,
                                   node.col_offset, MESSAGE,
                                   _line_text(lines, node.lineno)))
            elif isinstance(node.op, ast.LShift):
                if _is_literal(node.left) or _is_literal(node.right):
                    continue
                if _has_int64(node):
                    continue
                out.append(Finding(CHECK, relpath, node.lineno,
                                   node.col_offset, MESSAGE_SHIFT,
                                   _line_text(lines, node.lineno)))
    return out
