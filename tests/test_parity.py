"""Reference-parity harness (benchmarks/parity.py, VERDICT Missing #1):
the stub-binary test that proves the harness runs MECHANICALLY — two
tools invoked, outputs matched per hole, identity + Q20-yield fields
computed — so the first day a real `ccsx` binary is buildable it can
be pointed at the harness with zero new code.

The stub "reference binary" is a shell script that execs this repo's
own CLI, so every parity number must read perfect agreement."""

import json
import os
import stat
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "benchmarks"))

import parity  # noqa: E402

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def stub_bin(tmp_path_factory):
    """A fake `ccsx`: same CLI contract, implemented by exec'ing our
    own CLI (backend pinned to CPU, the test-suite idiom)."""
    tmp = tmp_path_factory.mktemp("stub")
    p = tmp / "ccsx"
    code = ("import sys, jax; "
            "jax.config.update('jax_platforms', 'cpu'); "
            "from ccsx_tpu.cli import main; "
            "sys.exit(main(sys.argv[1:]))")
    p.write_text("#!/bin/sh\n"
                 f'export PYTHONPATH="{_REPO}:$PYTHONPATH"\n'
                 f'exec "{sys.executable}" -c "{code}" "$@"\n')
    p.chmod(p.stat().st_mode | stat.S_IXUSR)
    return str(p)


def test_parity_missing_binary_refused(tmp_path):
    with pytest.raises(FileNotFoundError, match="not executable"):
        parity.run_parity(str(tmp_path / "nope"), 2, [1])


@pytest.mark.slow  # ~20s: stub-binary harness mechanics (r11 duration audit)
def test_parity_harness_runs_against_stub(stub_bin, tmp_path):
    summary = parity.run_parity(stub_bin, 2, [1], seed=0)
    assert summary["ccsx_bin"] == stub_bin
    [cfg] = summary["configs"]
    assert "error" not in cfg, cfg
    assert cfg["n_holes"] >= 1
    for h in cfg["holes"]:
        # stub == ourselves: byte-level agreement, so identity 1.0
        assert h["emitted_tpu"] and h["emitted_ref"]
        assert h["identity_cross"] == 1.0
        assert h["identity_tpu"] == h["identity_ref"]
        assert h["q20_pred_tpu"] is not None
    assert cfg["n_identical"] == cfg["n_holes"]
    assert summary["mean_identity_cross"] == 1.0
    # the yield delta of a tool against itself is exactly zero
    assert cfg["q20_yield_delta"] == 0.0
    # and the report is JSON-serializable as the CLI would emit it
    json.dumps(summary)


def test_parity_reports_reference_failure(tmp_path):
    """A reference binary that crashes is reported per config, not
    raised — the harness survives partially-broken builds."""
    p = tmp_path / "ccsx"
    p.write_text("#!/bin/sh\necho boom >&2\nexit 3\n")
    p.chmod(p.stat().st_mode | stat.S_IXUSR)
    r = parity.run_config_parity(1, str(p), 2, seed=0)
    assert "error" in r and "rc=3" in r["error"]


@pytest.mark.slow
def test_parity_cli_smoke(stub_bin, tmp_path):
    """(slow: two more cold CLI processes on top of the in-process
    harness test above.)"""
    out = tmp_path / "parity.json"
    r = subprocess.run(
        [sys.executable, os.path.join(_REPO, "benchmarks", "parity.py"),
         "--ccsx", stub_bin, "--holes", "2", "--configs", "1",
         "--json", str(out)],
        env=dict(os.environ, JAX_PLATFORMS="cpu", CCSX_SKIP_PROBE="1"),
        cwd=_REPO, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    assert json.loads(out.read_text())["mean_identity_cross"] == 1.0
