"""Differential tests: native C++ IO vs the pure-Python parsers.

Every stream the Python fallback can parse, the native path must parse to
identical records/holes (SURVEY.md §7.2 step 1: byte-identical grouping).
"""

import gzip

import numpy as np
import pytest

from ccsx_tpu import native
from ccsx_tpu.config import CcsConfig
from ccsx_tpu.io import bam as bam_mod
from ccsx_tpu.io import fastx, zmw
from ccsx_tpu.ops import encode as enc

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable")


def _native_records(path):
    from ccsx_tpu.native.io import read_records_native
    return list(read_records_native(str(path), is_bam=False))


def _records_equal(a, b):
    assert len(a) == len(b)
    for ra, rb in zip(a, b):
        assert ra.name == rb.name
        assert ra.comment == rb.comment
        assert ra.seq == rb.seq
        assert ra.qual == rb.qual


FASTA = b""">m1/1/0_5 first comment here
ACGTA
>m1/1/5_9
CG
TA
>m1/2/0_4\tx
GGGG
"""

FASTQ = b"""@m2/7/0_4 c
ACGT
+
IIII
@m2/7/4_10
AAC
GTT
+anything
IIIII
I
"""


def test_fasta_parity(tmp_path):
    p = tmp_path / "a.fa"
    p.write_bytes(FASTA)
    _records_equal(_native_records(p), list(fastx.read_fastx(str(p))))


def test_fastq_parity(tmp_path):
    p = tmp_path / "a.fq"
    p.write_bytes(FASTQ)
    recs = _native_records(p)
    _records_equal(recs, list(fastx.read_fastx(str(p))))
    assert recs[0].qual == b"IIII"
    assert recs[1].qual == b"IIIIII"


def test_gzip_parity(tmp_path):
    p = tmp_path / "a.fa.gz"
    p.write_bytes(gzip.compress(FASTA + FASTQ))
    _records_equal(_native_records(p), list(fastx.read_fastx(str(p))))


def test_corrupt_gzip_raises(tmp_path):
    p = tmp_path / "trunc.fa.gz"
    blob = gzip.compress(FASTA * 50)
    p.write_bytes(blob[: len(blob) // 2])  # truncated deflate stream
    from ccsx_tpu.native.io import NativeStreamError
    with pytest.raises(NativeStreamError):
        _native_records(p)


def test_fastq_bad_quality_length(tmp_path):
    p = tmp_path / "bad.fq"
    p.write_bytes(b"@r/1/0_4\nACGT\n+\nII\n")
    from ccsx_tpu.native.io import NativeStreamError
    with pytest.raises(NativeStreamError):
        _native_records(p)


def test_bam_parity(tmp_path):
    p = tmp_path / "a.bam"
    rng = np.random.default_rng(3)
    records = []
    for hole in (10, 11):
        for i in range(4):
            seq = bytes(rng.choice(list(b"ACGT"), 100 + 17 * i).tolist())
            qual = bytes(rng.integers(0, 60, len(seq)).astype(np.uint8))
            records.append((f"mv/{hole}/{i}", seq, qual))
    bam_mod.write_bam(p, records)
    from ccsx_tpu.native.io import read_records_native
    got = list(read_records_native(str(p), is_bam=True))
    want = list(bam_mod.read_bam_records(str(p)))
    _records_equal(got, want)


def test_bam_truncated(tmp_path):
    p = tmp_path / "t.bam"
    bam_mod.write_bam(p, [("m/1/0", b"ACGTACGT", b"\x10" * 8)])
    raw = gzip.decompress(p.read_bytes())
    p.write_bytes(gzip.compress(raw[:-3]))
    from ccsx_tpu.native.io import NativeStreamError, read_records_native
    with pytest.raises(NativeStreamError):
        list(read_records_native(str(p), is_bam=True))


def _mkfasta(tmp_path, holes):
    """holes: list of (movie, hole, [seqlens]) -> path"""
    rng = np.random.default_rng(0)
    lines = []
    for movie, hole, lens in holes:
        for i, ln in enumerate(lens):
            seq = "".join(rng.choice(list("ACGT"), ln).tolist())
            lines.append(f">{movie}/{hole}/{i}\n{seq}\n")
    p = tmp_path / "z.fa"
    p.write_text("".join(lines))
    return p


def test_zmw_stream_parity(tmp_path):
    cfg = CcsConfig(is_bam=False, min_subread_len=100, max_subread_len=10**6)
    p = _mkfasta(tmp_path, [
        ("m1", "1", [200] * 6),
        ("m1", "2", [50] * 5),          # filtered: total too small? 250>100 ok
        ("m1", "3", [300] * 3),         # filtered: too few passes (<5)
        ("m2", "1", [400] * 7),
    ])
    from ccsx_tpu.native.io import stream_zmws_native
    got = list(stream_zmws_native(str(p), cfg))
    want = list(zmw.stream_zmws(fastx.read_fastx(str(p)), cfg))
    assert len(got) == len(want)
    for a, b in zip(got, want):
        assert (a.movie, a.hole) == (b.movie, b.hole)
        assert a.seqs == b.seqs
        np.testing.assert_array_equal(a.lens, b.lens)
        np.testing.assert_array_equal(a.offs, b.offs)


def test_zmw_filters_and_exclusion(tmp_path):
    p = _mkfasta(tmp_path, [
        ("m", "1", [500] * 5),
        ("m", "2", [500] * 5),
        ("m", "3", [10] * 5),           # total 50 < min 100
    ])
    cfg = CcsConfig(is_bam=False, min_subread_len=100,
                    exclude_holes=frozenset({"2"}))
    from ccsx_tpu.native.io import stream_zmws_native
    got = list(stream_zmws_native(str(p), cfg))
    assert [z.hole for z in got] == ["1"]


def test_zmw_invalid_name(tmp_path):
    p = tmp_path / "bad.fa"
    p.write_text(">notaholename\nACGT\n")
    from ccsx_tpu.native.io import stream_zmws_native
    cfg = CcsConfig(is_bam=False, min_subread_len=0)
    with pytest.raises(zmw.InvalidZmwName):
        list(stream_zmws_native(str(p), cfg))


def test_prefetch_stream_parity(tmp_path):
    cfg = CcsConfig(is_bam=False, min_subread_len=100, max_subread_len=10**6)
    p = _mkfasta(tmp_path, [(f"m{i % 3}", str(i), [150 + i] * 6)
                            for i in range(40)])
    from ccsx_tpu.native.io import stream_zmws_native, stream_zmws_prefetch
    got = list(stream_zmws_prefetch(str(p), cfg, queue_cap=4))
    want = list(stream_zmws_native(str(p), cfg))
    assert len(got) == len(want) == 40
    for a, b in zip(got, want):
        assert (a.movie, a.hole, a.seqs) == (b.movie, b.hole, b.seqs)
        np.testing.assert_array_equal(a.lens, b.lens)


def test_prefetch_error_propagates(tmp_path):
    p = tmp_path / "bad.fa"
    p.write_text(">m/1/0\nACGT\n>oops\nACGT\n")
    from ccsx_tpu.native.io import stream_zmws_prefetch
    cfg = CcsConfig(is_bam=False, min_subread_len=0)
    with pytest.raises(zmw.InvalidZmwName):
        list(stream_zmws_prefetch(str(p), cfg))


def test_prefetch_early_close(tmp_path):
    # dropping the iterator mid-stream must not hang the producer thread
    cfg = CcsConfig(is_bam=False, min_subread_len=0)
    p = _mkfasta(tmp_path, [("m", str(i), [200] * 6) for i in range(50)])
    from ccsx_tpu.native.io import stream_zmws_prefetch
    it = stream_zmws_prefetch(str(p), cfg, queue_cap=2)
    next(it)
    it.close()


def test_native_writer(tmp_path):
    from ccsx_tpu.native.io import NativeFastaWriter
    p = tmp_path / "out.fa"
    w = NativeFastaWriter(str(p))
    for i in range(500):
        w.put(f"m/{i}/ccs", b"ACGT" * (i % 7 + 1))
    w.close()
    lines = p.read_text().strip().split("\n")
    assert len(lines) == 1000
    assert [ln for ln in lines[0::2]] == [f">m/{i}/ccs" for i in range(500)]
    # append mode
    w = NativeFastaWriter(str(p), append=True)
    w.put("m/extra/ccs", b"TTTT")
    w.close()
    assert p.read_text().strip().split("\n")[-2:] == [">m/extra/ccs", "TTTT"]


def test_native_writer_bad_path():
    from ccsx_tpu.native.io import NativeFastaWriter
    with pytest.raises(OSError):
        NativeFastaWriter("/nonexistent-dir/x/y.fa")


def test_encode_revcomp_native():
    from ccsx_tpu.native.io import encode_native, revcomp_codes_native
    seq = b"ACGTNacgtnXYZ-"
    np.testing.assert_array_equal(encode_native(seq), enc.encode(seq))
    rng = np.random.default_rng(1)
    codes = rng.integers(0, 5, 257).astype(np.uint8)
    np.testing.assert_array_equal(
        revcomp_codes_native(codes), enc.revcomp_codes(codes))


# ---- BGZF block-parallel reader (io_native.cpp BgzfMT) --------------------


def _mk_records(n=40, seqlen=300):
    rng = np.random.default_rng(3)
    out = []
    for i in range(n):
        seq = rng.choice(list(b"ACGT"), seqlen).astype(
            np.uint8).tobytes()
        out.append((f"mv/{i // 4}/{i}_{i + seqlen}", seq, b"\x20" * seqlen))
    return out


def test_bgzf_equals_plain_gzip_bam(tmp_path):
    """The BGZF path must produce byte-identical records to the plain
    single-member gzip path (same BAM payload, different container)."""
    from ccsx_tpu.native.io import read_records_native

    recs = _mk_records()
    pb = str(tmp_path / "b.bam")
    pg = str(tmp_path / "g.bam")
    bam_mod.write_bam(pb, recs, bgzf=True)
    bam_mod.write_bam(pg, recs, bgzf=False)
    a = list(read_records_native(pb, is_bam=True))
    b = list(read_records_native(pg, is_bam=True))
    assert [(r.name, r.seq) for r in a] == [(r.name, r.seq) for r in b]
    assert len(a) == len(recs)
    # multi-block: the BGZF file must actually contain several members
    raw = open(pb, "rb").read()
    assert raw.count(b"\x1f\x8b\x08\x04") >= 2


def test_bgzf_readable_by_python_gzip(tmp_path):
    """BGZF is valid multi-member gzip — the Python fallback reader and
    the reference's plain-gz approach (bamlite.h:13-19) must still work."""
    recs = _mk_records(n=12)
    p = str(tmp_path / "b.bam")
    bam_mod.write_bam(p, recs, bgzf=True)
    got = list(bam_mod.read_bam_records(p))
    assert [r.name for r in got] == [r[0] for r in recs]


def test_bgzf_corrupt_block_raises(tmp_path):
    """A flipped byte inside a BGZF member must fail the CRC check."""
    from ccsx_tpu.native.io import NativeStreamError, read_records_native

    recs = _mk_records(n=20)
    p = str(tmp_path / "b.bam")
    bam_mod.write_bam(p, recs, bgzf=True)
    raw = bytearray(open(p, "rb").read())
    raw[len(raw) // 2] ^= 0xFF  # middle of some block's payload
    open(p, "wb").write(bytes(raw))
    with pytest.raises(NativeStreamError):
        list(read_records_native(p, is_bam=True))


def test_bgzf_truncated_mid_block_raises(tmp_path):
    from ccsx_tpu.native.io import NativeStreamError, read_records_native

    recs = _mk_records(n=20)
    p = str(tmp_path / "b.bam")
    bam_mod.write_bam(p, recs, bgzf=True)
    raw = open(p, "rb").read()
    open(p, "wb").write(raw[: len(raw) // 2 + 7])
    with pytest.raises(NativeStreamError):
        list(read_records_native(p, is_bam=True))


def test_bgzf_threaded_matches_inline(tmp_path, monkeypatch):
    """CCSX_BGZF_THREADS=4 (pool) and =1 (inline inflate) must agree."""
    from ccsx_tpu.native.io import read_records_native

    recs = _mk_records(n=60)
    p = str(tmp_path / "b.bam")
    bam_mod.write_bam(p, recs, bgzf=True)
    monkeypatch.setenv("CCSX_BGZF_THREADS", "1")
    a = [(r.name, r.seq) for r in read_records_native(p, is_bam=True)]
    monkeypatch.setenv("CCSX_BGZF_THREADS", "4")
    b = [(r.name, r.seq) for r in read_records_native(p, is_bam=True)]
    assert a == b and len(a) == 60


def test_bgzf_truncated_at_block_boundary_raises(tmp_path):
    """A BGZF file cut exactly at a member boundary (EOF marker missing)
    must error, not report a clean shorter stream."""
    from ccsx_tpu.native.io import NativeStreamError, read_records_native

    recs = _mk_records(n=40)
    p = str(tmp_path / "b.bam")
    bam_mod.write_bam(p, recs, bgzf=True)
    raw = open(p, "rb").read()
    # drop the trailing EOF marker (28 bytes) only: block-aligned cut
    assert raw.endswith(bam_mod.BGZF_EOF)
    open(p, "wb").write(raw[: -len(bam_mod.BGZF_EOF)])
    with pytest.raises(NativeStreamError):
        list(read_records_native(p, is_bam=True))


def test_bgzf_huge_isize_rejected(tmp_path):
    """A corrupt ISIZE (> 64KB cap) must be a stream error, not a
    multi-GB allocation."""
    from ccsx_tpu.native.io import NativeStreamError, read_records_native

    recs = _mk_records(n=8)
    p = str(tmp_path / "b.bam")
    bam_mod.write_bam(p, recs, bgzf=True)
    raw = bytearray(open(p, "rb").read())
    # first member: header 18 bytes + payload + crc(4) + isize(4);
    # BSIZE at offset 16 gives the member size
    bsize = int.from_bytes(raw[16:18], "little") + 1
    raw[bsize - 4: bsize] = (0xFFFFFFFF).to_bytes(4, "little")
    open(p, "wb").write(bytes(raw))
    with pytest.raises(NativeStreamError):
        list(read_records_native(p, is_bam=True))


def test_bgzf_pool_bench_floor(tmp_path):
    """Regression gate for the decoupled inflate pool (VERDICT r3 item
    6): single-thread pool throughput must stay within striking distance
    of Python's zlib on the same data — both sit on the same libz, so a
    big gap means the pool added overhead.  Relative gate: robust to
    host speed, unlike an absolute MB/s floor."""
    import time

    recs = _mk_records(n=200)
    p = str(tmp_path / "b.bam")
    bam_mod.write_bam(p, recs, bgzf=True)
    L = native.lib()
    if L is None:
        pytest.skip("native library unavailable")
    pool = L.ccsx_bgzf_pool_bench(p.encode(), 1, 3)
    assert pool > 0, "pool bench failed on a well-formed BGZF file"
    raw = open(p, "rb").read()
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        # gzip.decompress walks ALL members (BGZF = multi-member gzip)
        n = len(gzip.decompress(raw))
        best = max(best, n / (time.perf_counter() - t0) / (1 << 20))
    # pool t1 pays per-block init/CRC that the one-shot decompress does
    # not; 0.5x is far below its measured ~1.6x so only a real
    # regression trips this
    assert pool >= 0.5 * best, (pool, best)
