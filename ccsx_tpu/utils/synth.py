"""Synthetic ZMW/subread generator for tests and benchmarks.

Models the PacBio data the reference consumes: a circular template read many
times with alternating strand per pass (main.c:374-375 walks outward from the
template alternating expected strand), each pass an independently noisy copy
(mismatches + insertions + deletions).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from ccsx_tpu.ops import encode as enc


@dataclasses.dataclass
class SynthZmw:
    movie: str
    hole: str
    template: np.ndarray          # 2-bit codes
    passes: List[np.ndarray]      # 2-bit codes, oriented as sequenced
    strands: List[int]            # 0 fwd / 1 rev per pass

    @property
    def names(self) -> List[str]:
        out = []
        off = 0
        for p in self.passes:
            out.append(f"{self.movie}/{self.hole}/{off}_{off + len(p)}")
            off += len(p)
        return out

    def fasta(self) -> str:
        recs = []
        for name, p in zip(self.names, self.passes):
            recs.append(f">{name}\n{enc.decode(p)}\n")
        return "".join(recs)


def mutate(
    rng: np.random.Generator,
    seq: np.ndarray,
    sub_rate: float,
    ins_rate: float,
    del_rate: float,
) -> np.ndarray:
    """Apply independent per-base errors to a 2-bit sequence."""
    out = []
    for b in seq:
        r = rng.random()
        if r < del_rate:
            continue
        if r < del_rate + sub_rate:
            out.append((int(b) + 1 + rng.integers(3)) % 4)
        else:
            out.append(int(b))
        while rng.random() < ins_rate:
            out.append(int(rng.integers(4)))
    return np.array(out, dtype=np.uint8)


def make_zmw(
    rng: np.random.Generator,
    template_len: int = 1000,
    n_passes: int = 5,
    sub_rate: float = 0.02,
    ins_rate: float = 0.04,
    del_rate: float = 0.04,
    movie: str = "m0",
    hole: str = "1",
    first_strand: int = 0,
    template: Optional[np.ndarray] = None,
    partial_ends: bool = False,
) -> SynthZmw:
    """With ``partial_ends``, the first and last passes are truncated
    fragments (the polymerase starts/ends mid-molecule on real ZMWs) —
    these fall outside the dominant length group, forcing the prepare
    stage through its alignment-verified strand walk (main.c:392-406)
    instead of the trusted-parity shortcut."""
    if template is None:
        template = rng.integers(0, 4, size=template_len).astype(np.uint8)
    passes, strands = [], []
    for k in range(n_passes):
        strand = (first_strand + k) % 2
        p = mutate(rng, template, sub_rate, ins_rate, del_rate)
        if strand:
            p = enc.revcomp_codes(p)
        if partial_ends and n_passes >= 5 and k in (0, n_passes - 1):
            frac = 0.3 + 0.3 * rng.random()  # keep 30-60%
            keep = max(int(len(p) * frac), 50)
            # first pass keeps its tail (run-up), last keeps its head
            p = p[-keep:] if k == 0 else p[:keep]
        passes.append(p)
        strands.append(strand)
    return SynthZmw(movie=movie, hole=hole, template=template,
                    passes=passes, strands=strands)


def read_through(
    rng: np.random.Generator,
    template: np.ndarray,
    sub_rate: float = 0.02,
    ins_rate: float = 0.04,
    del_rate: float = 0.04,
) -> np.ndarray:
    """A missed-adapter ("read-through") pass: template ++
    revcomp(template), each half independently noisy.  ~2x the template
    group length, so the reference's prepare stage aligns and clips it
    to one template span (main.c:392-406) instead of trusting strand
    parity."""
    return np.concatenate([
        mutate(rng, template, sub_rate, ins_rate, del_rate),
        enc.revcomp_codes(mutate(rng, template, sub_rate, ins_rate,
                                 del_rate)),
    ])


def make_fasta(zmws: List[SynthZmw]) -> str:
    return "".join(z.fasta() for z in zmws)


def identity(a: np.ndarray, b: np.ndarray) -> float:
    """Global-alignment identity between two code sequences (oracle-based)."""
    from ccsx_tpu.ops import oracle

    rs = oracle.align(a, b, mode="global")
    return rs.identity


def identity_either(a: np.ndarray, b: np.ndarray) -> float:
    """Identity of a vs b in the better of the two orientations.

    Consensus strand follows the chosen template pass (an arbitrary strand,
    in the reference as here), so template comparisons must accept either.
    """
    return max(identity(a, b), identity(enc.revcomp_codes(a), b))
