"""Benchmark: batched star-MSA consensus round throughput.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The measured unit is ZMW-windows consensed per second by the batched device
round (banded DP fill + traceback projection + column vote over a
(Z, P, W) batch) — the hot compute of the pipeline (reference: the bsalign
POA inside ccs_for2's window loop, main.c:552-572, where ~all CPU time
goes; SURVEY.md §3.3).

vs_baseline compares against the single-core CPU (XLA-CPU) number recorded
in bench_baseline.json.  The reference binary itself is not buildable here
(its bsalign dependency is cloned at build time, README.md:11 — no network),
so the stored CPU run of this same workload is the baseline.
Recalibrate with:  python bench.py --calibrate
"""

import json
import os
import sys
import time

# benchmark shapes (kept canonical so compiles cache): Z zmws x P passes x W window
Z, P, W, TLEN = 16, 8, 1024, 1000
WARMUP, ITERS = 2, 8
BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bench_baseline.json")


def measure():
    import jax
    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from ccsx_tpu.config import AlignParams
    from ccsx_tpu.consensus import star
    from ccsx_tpu.ops import msa, traceback
    import __graft_entry__ as ge

    params = AlignParams()
    projector = traceback.make_projector(W, 4)
    voter = msa.make_voter(4)
    # the production aligner dispatch: the vmapped lax.scan fill by
    # default on every backend (it beat the Pallas kernel 183k vs 142k
    # zmw-windows/s on v5e, 2026-07-29 — see consensus/star.use_pallas);
    # CCSX_BANDED_IMPL=pallas selects the kernel for A/B runs
    aligner = star._aligner(params)

    @jax.jit
    def step(qs, qlens, ts, tlens, row_mask):
        Zb, Pb, qmax = qs.shape
        ts_b = jax.numpy.broadcast_to(ts[:, None, :], (Zb, Pb, ts.shape[-1]))
        tl_b = jax.numpy.broadcast_to(tlens[:, None], (Zb, Pb))
        _, moves, offs = aligner(
            qs.reshape(Zb * Pb, qmax), qlens.reshape(Zb * Pb),
            ts_b.reshape(Zb * Pb, -1), tl_b.reshape(Zb * Pb))
        moves = moves.reshape(Zb, Pb, qmax, -1)
        offs = offs.reshape(Zb, Pb, qmax)
        proj = jax.vmap(jax.vmap(projector, in_axes=(0, 0, 0, 0, None)),
                        in_axes=(0, 0, 0, 0, 0))
        aligned, ins_cnt, ins_b, _lead = proj(moves, offs, qs, qlens, tlens)
        cons, ins_base, ins_votes, ncov, match = jax.vmap(voter)(
            aligned, ins_cnt, ins_b, row_mask)
        return cons, ncov

    args = ge._example_batch(Z=Z, P=P, W=W, tlen=TLEN)
    for _ in range(WARMUP):
        jax.block_until_ready(step(*args))
    t0 = time.perf_counter()
    for _ in range(ITERS):
        jax.block_until_ready(step(*args))
    dt = (time.perf_counter() - t0) / ITERS
    return Z / dt  # ZMW-windows per second


def main():
    calibrate = "--calibrate" in sys.argv
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    if calibrate:
        # the baseline is the single-core XLA-CPU run of this workload;
        # the axon plugin overrides JAX_PLATFORMS, so force via config
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        # the tunnelled TPU can hang on init; probe out-of-process and
        # fall back to CPU so the bench always produces its JSON line
        from ccsx_tpu.utils.device import resolve_device

        resolve_device("auto")
    value = measure()

    baseline = None
    if os.path.exists(BASELINE_PATH):
        with open(BASELINE_PATH) as f:
            baseline = json.load(f).get("zmw_windows_per_sec")
    if calibrate:
        with open(BASELINE_PATH, "w") as f:
            json.dump({"zmw_windows_per_sec": value,
                       "note": "single-core XLA-CPU, shapes "
                               f"Z={Z} P={P} W={W}"}, f, indent=1)
        baseline = value

    import jax
    print(json.dumps({
        "metric": "consensus round throughput "
                  f"(Z={Z} zmw x P={P} passes x W={W} window, "
                  f"backend={jax.default_backend()})",
        "value": round(value, 3),
        "unit": "zmw_windows/s",
        "vs_baseline": round(value / baseline, 3) if baseline else None,
    }))


if __name__ == "__main__":
    main()
