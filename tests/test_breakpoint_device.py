"""Differential tests: device breakpoint/advance vs the NumPy spec.

ops/breakpoint.py must reproduce consensus/windowed.find_breakpoint and
_advance exactly (the spec of the reference scan, main.c:580-612 and
622-638) — including the None (-1) cases, the <10-pass colrate switch,
and tiny MSAs below the scan window.
"""

import jax
import numpy as np

from ccsx_tpu.config import CcsConfig
from ccsx_tpu.consensus import windowed as win_mod
from ccsx_tpu.consensus.star import StarMsa
from ccsx_tpu.ops import breakpoint as bp_mod
from ccsx_tpu.utils import synth


def _cases(rng):
    """(passes, tlen) cases spanning the scan's regimes."""
    out = []
    # agreeing 6-pass window (normal breakpoint)
    tpl = rng.integers(0, 4, 400).astype(np.uint8)
    out.append([synth.mutate(rng, tpl, 0.02, 0.04, 0.04) for _ in range(6)]
               + [tpl])
    # 12 passes: the >=10-pass colrate (80) applies
    out.append([synth.mutate(rng, tpl, 0.02, 0.04, 0.04) for _ in range(12)]
               + [tpl])
    # 3 passes at brutal error: likely no breakpoint (None/-1)
    out.append([synth.mutate(rng, tpl, 0.12, 0.15, 0.15) for _ in range(3)]
               + [tpl])
    # tiny template below the scan window
    tiny = rng.integers(0, 4, 8).astype(np.uint8)
    out.append([synth.mutate(rng, tiny, 0.05, 0.0, 0.0) for _ in range(4)]
               + [tiny])
    return out


def test_device_matches_spec(rng):
    cfg = CcsConfig(is_bam=False)
    sm = StarMsa(cfg.align, cfg.max_ins_per_col, cfg.len_bucket_quant)
    for case in _cases(rng):
        passes, draft = case[:-1], case[-1]
        qs, qlens, row_mask = sm.pack(passes, cfg.pass_buckets,
                                      cfg.max_passes)
        ra = sm.round(qs, qlens, row_mask, draft)
        nseq = len(passes)
        host_bp = win_mod.find_breakpoint(ra, nseq, cfg)
        bp_eff = host_bp if host_bp is not None else max(
            ra.tlen - cfg.bp_window, 1)
        host_adv = win_mod._advance(ra, bp_eff)

        tmax = ra.cons.shape[0]
        f = jax.jit(bp_mod.make_bp_advance(
            tmax, cfg.bp_window, cfg.bp_minwin, cfg.bp_rowrate,
            cfg.bp_colrate, cfg.bp_colrate_lowpass))
        bp_d, adv_d = f(ra.match, ra.cons, ra.aligned, ra.ins_cnt,
                        ra.lead_ins.astype(np.int32), row_mask,
                        np.int32(ra.tlen))
        bp_d = int(bp_d)
        assert (bp_d if bp_d >= 1 else None) == host_bp, \
            f"device bp {bp_d} != spec {host_bp} (nseq={nseq})"
        np.testing.assert_array_equal(
            np.asarray(adv_d), host_adv.astype(np.int32))


def test_device_none_encoding_small_tlen(rng):
    """tlen < bp_window + 1 must yield -1 (spec returns None early)."""
    cfg = CcsConfig(is_bam=False)
    f = jax.jit(bp_mod.make_bp_advance(
        64, cfg.bp_window, cfg.bp_minwin, cfg.bp_rowrate,
        cfg.bp_colrate, cfg.bp_colrate_lowpass))
    P, T = 4, 64
    match = np.ones((P, T), bool)
    cons = np.zeros(T, np.uint8)
    aligned = np.zeros((P, T), np.uint8)
    ins_cnt = np.zeros((P, T), np.int32)
    lead = np.zeros(P, np.int32)
    mask = np.ones(P, bool)
    bp, _ = f(match, cons, aligned, ins_cnt, lead, mask, np.int32(6))
    assert int(bp) == -1
