"""Schema-drift fixed sibling, consumer side."""

PROM_COUNTERS = ("holes_in",)
PROM_GAUGES = ("elapsed_s",)
PROM_STRUCTURED = ()
