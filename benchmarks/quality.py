"""Q20-yield parity gate + delta sweeps (SURVEY.md §7.2 step 2 fallback).

The compiled reference binary is not buildable offline (bsalign is cloned
at build time, reference README.md:11), so accuracy parity is gated the
way the blueprint prescribes: >=Q20 consensus yield over a realistic
pass-count distribution on the five BASELINE configs, plus explicit
quantification of the two documented deltas vs the reference:

  * max_window force-flush (windowed.py) vs the reference's unbounded
    window growth (main.c:550,613-616) — swept on low-agreement
    (high-error) holes with window_growth "flush" vs "grow";
  * max_passes=32 pass cap (config.py) vs the reference's all-passes POA
    (main.c:486-492) — swept on 40-60-pass holes.

Q per hole = -10*log10(1 - identity) with identity from a global
alignment vs the known template (better orientation); Q20 <=> identity
>= 0.99.  Yield = emitted holes at >=Q20 / holes in.

Usage: python benchmarks/quality.py [--holes N] [--json out.json]
       (heavier sweeps: --full)
"""

from __future__ import annotations

import argparse
import gzip
import json
import math
import os
import sys
import tempfile

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from ccsx_tpu import cli                                     # noqa: E402
from ccsx_tpu.config import CcsConfig                        # noqa: E402
from ccsx_tpu.consensus import prepare as prep               # noqa: E402
from ccsx_tpu.consensus.align_host import HostAligner        # noqa: E402
from ccsx_tpu.consensus.windowed import consensus_windowed   # noqa: E402
from ccsx_tpu.io import bam, fastx                           # noqa: E402
from ccsx_tpu.ops import encode as enc                       # noqa: E402
from ccsx_tpu.utils import synth                             # noqa: E402
from ccsx_tpu.utils.fingerprint import code_fingerprint      # noqa: E402

# per-pass subread error rates (PacBio CLR-like: ~10-13% total, indel
# heavy).  The gate distribution draws pass counts log-normally: median
# ~9, tail to ~30 — shaped like a Sequel II subreads length/pass profile
ERR = dict(sub_rate=0.02, ins_rate=0.05, del_rate=0.05)

# the correlated-error model (r5, VERDICT r4 weak 6): homopolymer-biased
# indels (the dominant real PacBio mode — indel rate grows with run
# length, inserted bases extend the run) + per-base context on subs.
# Errors become CORRELATED across passes at the same template loci, so
# unanimous columns can be unanimously wrong — the regime that actually
# stresses QV calibration and that the qv_per_hp penalty (config.py)
# was fitted on.  The primary gated calibration table uses this model;
# the i.i.d. table is kept alongside for continuity with r3/r4.
ERR_BIASED = dict(ERR, hp_factor=0.6, hp_ins_same=0.7,
                  context_sub=(0.7, 1.3, 1.3, 0.7))


def sample_pass_counts(rng, n, lo=5, hi=30):
    counts = np.clip(np.round(rng.lognormal(np.log(9), 0.45, n)),
                     lo, hi).astype(int)
    return counts


def q_of(identity: float) -> float:
    return 60.0 if identity >= 1.0 else -10.0 * math.log10(1.0 - identity)


def _fastq(zs) -> str:
    out = []
    for z in zs:
        for name, p in zip(z.names, z.passes):
            s = enc.decode(p)
            out.append(f"@{name}\n{s}\n+\n{'~' * len(s)}\n")
    return "".join(out)


def make_config_input(config, zs, tmp):
    """Write `zs` in the shape BASELINE config `config` prescribes.

    Configs (BASELINE.json): 1 FASTA shred, 2 BAM defaults, 3 whole-read
    -P, 4 deep-pass, 5 gzipped FASTQ.  Input format is what varies here;
    hole composition is the caller's distribution.
    """
    if config == 2:
        p = os.path.join(tmp, "in.bam")
        recs = [(n, enc.decode(s).encode(), None)
                for z in zs for n, s in zip(z.names, z.passes)]
        bam.write_bam(p, recs)
        return p, []
    if config == 3:
        p = os.path.join(tmp, "in.fa")
        open(p, "w").write(synth.make_fasta(zs))
        return p, ["-A", "-P"]
    if config == 5:
        p = os.path.join(tmp, "in.fq.gz")
        with gzip.open(p, "wt") as f:
            f.write(_fastq(zs))
        return p, ["-A"]
    p = os.path.join(tmp, "in.fa")   # configs 1 and 4
    open(p, "w").write(synth.make_fasta(zs))
    return p, ["-A"]


def run_gate_config(config, n_holes, rng, tlen=800, err=None):
    """Q20 yield for one BASELINE config over the pass distribution."""
    err = ERR if err is None else err
    counts = sample_pass_counts(rng, n_holes)
    if config == 4:   # deep-pass config: 15..30 passes
        counts = np.clip(counts + 12, 15, 30)
    zs = [synth.make_zmw(rng, tlen, int(c), movie="mv", hole=str(h), **err)
          for h, c in enumerate(counts)]
    with tempfile.TemporaryDirectory() as tmp:
        in_path, args, = make_config_input(config, zs, tmp)
        out = os.path.join(tmp, "out.fa")
        rc = cli.main([*args, "-m", "1000", "--batch", "auto", in_path, out])
        assert rc == 0, f"config {config}: rc={rc}"
        got = {r.name: r.seq for r in fastx.read_fastx(out)}
    idys = []
    for z in zs:
        k = f"{z.movie}/{z.hole}/ccs"
        idys.append(synth.identity_either(enc.encode(got[k]), z.template)
                    if k in got else 0.0)
    idys = np.array(idys)
    qs = np.array([q_of(i) for i in idys])
    return {
        "config": config,
        "holes_in": n_holes,
        "holes_out": int((idys > 0).sum()),
        "mean_identity": round(float(idys[idys > 0].mean()), 5),
        "median_q": round(float(np.median(qs)), 2),
        "q20_yield": round(float((idys >= 0.99).mean()), 4),
        "pass_counts": [int(c) for c in counts],
    }


def _consensus_identity(z, cfg):
    """Direct consensus path (no CLI) for sweep configs."""
    from ccsx_tpu.io.zmw import Zmw

    lens = np.array([len(p) for p in z.passes], np.int32)
    offs = np.zeros(len(lens), np.int32)
    if len(lens) > 1:
        np.cumsum(lens[:-1], out=offs[1:])
    zz = Zmw(movie=z.movie, hole=z.hole,
             seqs=enc.decode(np.concatenate(z.passes)).encode(),
             lens=lens, offs=offs)
    passes = prep.oriented_passes(zz, HostAligner(cfg.align), cfg)
    if passes is None:
        return 0.0
    cns = consensus_windowed(passes, cfg)
    return synth.identity_either(cns, z.template)


def sweep_max_window(rng, n_holes=4, tlen=6000, err_scale=2.5):
    """Low-agreement holes: flush-at-max_window vs reference-parity
    unbounded growth (window_growth="grow"), with the cap tightened
    (window_init=1024, max_window=2048) so any growth would hit it
    mid-molecule.

    The sweep counts breakpoint-scan failures (the only trigger of
    window growth, main.c:550) while consensing three adversarial
    families: (a) 6-pass holes at ~29% total error, (b) a 3000bp
    period-5 tandem repeat flanked by unique sequence (classic
    alignment-slippage case), (c) 3-pass holes at ~40% error.
    MEASURED RESULT (2026-07-29, recorded in BASELINE.md): zero failures
    — the star-MSA projects every pass onto common draft coordinates, so
    column agreement is structural and the breakpoint scan succeeds even
    where the reference's progressive POA MSA would diverge; the
    force-flush delta is therefore vacuous in this architecture (modes
    remain bit-identical), not merely small."""
    e = {k: min(v * err_scale, 0.12) for k, v in ERR.items()}
    out = {"holes": n_holes, "tlen": tlen, "err": e,
           "window_init": 1024, "max_window": 2048}

    from ccsx_tpu.consensus import windowed as win_mod

    counts = {"scans": 0, "no_breakpoint": 0}
    orig = win_mod.find_breakpoint

    def spy(rr, nseq, cfg):
        bp = orig(rr, nseq, cfg)
        counts["scans"] += 1
        counts["no_breakpoint"] += bp is None
        return bp

    def holes(r):
        hs = [synth.make_zmw(r, tlen, 6, movie="mv", hole=str(h), **e)
              for h in range(n_holes)]
        motif = r.integers(0, 4, 5).astype(np.uint8)
        tpl = np.concatenate([
            r.integers(0, 4, 1500).astype(np.uint8), np.tile(motif, 600),
            r.integers(0, 4, 1500).astype(np.uint8)])
        hs.append(synth.make_zmw(r, len(tpl), 6, movie="mv", hole="rep",
                                 template=tpl, **e))
        hs.append(synth.make_zmw(r, tlen, 3, movie="mv", hole="x",
                                 sub_rate=0.10, ins_rate=0.15,
                                 del_rate=0.15))
        return hs

    seed = rng.integers(1 << 31)
    ids = {"flush": [], "grow": []}
    win_mod.find_breakpoint = spy
    try:
        for mode in ("flush", "grow"):
            cfg = CcsConfig(is_bam=False, min_subread_len=1000,
                            window_growth=mode, window_init=1024,
                            window_add=1024, max_window=2048)
            for z in holes(np.random.default_rng(seed)):
                ids[mode].append(_consensus_identity(z, cfg))
    finally:
        win_mod.find_breakpoint = orig
    for mode in ("flush", "grow"):
        a = np.array(ids[mode])
        out[f"identity_{mode}"] = round(float(a.mean()), 5)
        out[f"q20_yield_{mode}"] = round(float((a >= 0.99).mean()), 4)
    out["delta_identity"] = round(
        out["identity_grow"] - out["identity_flush"], 5)
    out["breakpoint_scans"] = counts["scans"]
    out["no_breakpoint_events"] = counts["no_breakpoint"]
    return out


def sweep_max_passes(rng, n_holes=3, tlen=1200, deep=48):
    """40-60-pass holes: max_passes=32 cap vs all passes."""
    out = {"holes": n_holes, "tlen": tlen, "passes": deep}
    ids = {32: [], deep: []}
    for h in range(n_holes):
        z = synth.make_zmw(rng, tlen, deep, movie="mv", hole=str(h), **ERR)
        for cap in (32, deep):
            cfg = CcsConfig(is_bam=False, min_subread_len=1000,
                            max_passes=cap,
                            pass_buckets=(4, 8, 16, 32, 64))
            ids[cap].append(_consensus_identity(z, cfg))
    for cap in (32, deep):
        a = np.array(ids[cap])
        out[f"identity_cap{cap}"] = round(float(a.mean()), 5)
    out["delta_identity"] = round(
        out[f"identity_cap{deep}"] - out["identity_cap32"], 5)
    return out


def per_base_errors(cns: np.ndarray, tpl: np.ndarray) -> np.ndarray:
    """Per-consensus-base error flags from a global alignment vs the
    template (better orientation): substitution at an 'M' column with
    differing bases, or an 'I' (consensus-only) base.  Deletions have no
    consensus base to blame and are excluded (counted by the caller via
    the cigar if needed)."""
    from ccsx_tpu.ops import oracle

    rc = enc.revcomp_codes(cns)
    r_f = oracle.align(cns, tpl, mode="global")
    r_r = oracle.align(rc, tpl, mode="global")
    fwd = r_f.identity >= r_r.identity
    r, q = (r_f, cns) if fwd else (r_r, rc)
    err = np.zeros(len(q), bool)
    i, j = r.qb, r.tb
    for op, n in r.cigar:
        if op == "M":
            err[i:i + n] = q[i:i + n] != tpl[j:j + n]
            i += n
            j += n
        elif op == "I":
            err[i:i + n] = True
            i += n
        else:  # D
            j += n
    return err if fwd else err[::-1]


def quality_calibration(rng, n_holes=16, tlen=800, err=None):
    """Empirical check of the --fastq vote-margin qualities: bin emitted
    bases by predicted Q, measure the observed per-base error rate per
    bin.  The mapping is usable if observed error falls monotonically
    with predicted Q (it is documented as a confidence score, not a
    calibrated QV — this quantifies how conservative/liberal it is).
    ``err`` selects the error model (default module ERR)."""
    err_model = dict(ERR if err is None else err)
    cfg = CcsConfig(is_bam=False, min_subread_len=1000, emit_quality=True)
    edges = [0, 5, 10, 15, 20, 25, 30, 35, 40, 61]  # 5-Q granularity
    errs = np.zeros(len(edges) - 1, np.int64)
    tot = np.zeros(len(edges) - 1, np.int64)
    for h in range(n_holes):
        npass = int(sample_pass_counts(rng, 1)[0])
        z = synth.make_zmw(rng, tlen, npass, movie="mv", hole=str(h),
                           **err_model)
        lens = np.array([len(p) for p in z.passes], np.int32)
        offs = np.zeros(len(lens), np.int32)
        if len(lens) > 1:
            np.cumsum(lens[:-1], out=offs[1:])
        from ccsx_tpu.io.zmw import Zmw

        zz = Zmw(movie=z.movie, hole=z.hole,
                 seqs=enc.decode(np.concatenate(z.passes)).encode(),
                 lens=lens, offs=offs)
        passes = prep.oriented_passes(zz, HostAligner(cfg.align), cfg)
        if passes is None:
            continue
        cns, quals = consensus_windowed(passes, cfg)
        err = per_base_errors(cns, z.template)
        which = np.digitize(quals, edges) - 1
        for b in range(len(edges) - 1):
            sel = which == b
            errs[b] += int(err[sel].sum())
            tot[b] += int(sel.sum())
    bins = []
    for b in range(len(edges) - 1):
        if tot[b] == 0:
            continue
        rate = errs[b] / tot[b]
        bins.append({
            "predicted_q": f"[{edges[b]},{edges[b + 1]})",
            "bases": int(tot[b]),
            "observed_error_rate": round(float(rate), 5),
            "observed_q": round(-10 * math.log10(max(rate, 1e-6)), 1),
        })
    return bins


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--holes", type=int, default=12)
    ap.add_argument("--json", default=None)
    ap.add_argument("--full", action="store_true",
                    help="heavier sweeps (more holes)")
    ap.add_argument("--device", default="auto",
                    choices=["auto", "tpu", "cpu"])
    a = ap.parse_args()

    from ccsx_tpu.utils.device import resolve_device

    resolve_device(a.device)
    import jax

    from ccsx_tpu.config import CcsConfig

    res = {"backend": jax.default_backend(), "q20_definition":
           "identity >= 0.99 (global alignment vs template, "
           "better orientation)",
           # pin the QV model the table was generated under, so the
           # calibration gate (tests/test_quality_output.py) can detect
           # a stale artifact after a coefficient change
           "qv_coeffs": list(CcsConfig(is_bam=False).qv_coeffs),
           # pin the run parameters so a resumed run can verify the
           # checkpoint came from the same configuration — including the
           # error models, so editing ERR/ERR_BIASED invalidates a stale
           # checkpoint instead of silently mixing old-model sections
           # into an artifact that reports the new models
           "holes": a.holes, "full": bool(a.full),
           # ... and the same CODE: the consensus-source fingerprint
           # (shared with journal v2, utils/fingerprint.py) invalidates
           # a checkpoint cut by a crashed run of OLDER code, which
           # would otherwise silently mix old-code sections into an
           # artifact attributed to current HEAD
           "code_fingerprint": code_fingerprint(),
           # json round-trip so the == check against a reloaded .partial
           # compares like with like (tuples become lists)
           "error_models": json.loads(json.dumps(
               {"iid": ERR, "biased": ERR_BIASED}))}

    # resume from a .partial checkpoint left by a crashed/timed-out run.
    # Sound because every section below draws from its OWN seeded rng
    # (no shared stream), so skipping completed sections reproduces the
    # exact bytes a single uninterrupted run would have produced.
    done = {}
    if a.json and os.path.exists(a.json + ".partial"):
        try:
            with open(a.json + ".partial") as f:
                prev = json.load(f)
            compat_keys = ("backend", "qv_coeffs", "holes", "full",
                           "error_models", "code_fingerprint")
            if all(prev.get(k) == res[k] for k in compat_keys):
                done = prev
                print(f"[quality] resuming from {a.json}.partial "
                      f"(sections: {sorted(done)})", file=sys.stderr)
            else:
                bad = [k for k in compat_keys if prev.get(k) != res[k]]
                print(f"[quality] IGNORING {a.json}.partial: mismatched "
                      f"{bad} — recomputing all sections", file=sys.stderr)
        except (OSError, ValueError):
            pass

    def save():
        # checkpoint after every section: a timed-out run still leaves
        # the completed sections on disk (a full 100-hole run is >1h on
        # a contended 1-core host; losing the gate to a late crash once
        # cost this exact artifact a full regeneration)
        if a.json:
            with open(a.json + ".partial", "w") as f:
                json.dump(res, f, indent=1)

    def section(key, fn):
        res[key] = done[key] if key in done else fn()
        save()

    # each gate config is its own checkpointed section with its own
    # seed — the gate dominates the run (>1h at 100 holes on a 1-core
    # host), so a crash mid-gate must only lose ONE config, not five
    for c in (1, 2, 3, 4, 5):
        section(f"gate_{c}", lambda c=c: run_gate_config(
            c, a.holes, np.random.default_rng(100 + c)))
    # assembled view (what schema consumers read); the gate_N sections
    # stay in the artifact as the per-config resume checkpoints
    res["gate"] = [res[f"gate_{c}"] for c in (1, 2, 3, 4, 5)]
    save()
    # realistic correlated errors on the config-1 shape: the yield the
    # framework would report on homopolymer-heavy real data
    section("gate_biased", lambda: run_gate_config(
        1, a.holes, np.random.default_rng(11), err=ERR_BIASED))
    section("sweep_max_window", lambda: sweep_max_window(
        np.random.default_rng(13), n_holes=8 if a.full else 4))
    section("sweep_max_passes", lambda: sweep_max_passes(
        np.random.default_rng(17), n_holes=6 if a.full else 3))
    # primary gated table: the CORRELATED model (tests/
    # test_quality_output.py asserts monotone at 5-Q granularity);
    # i.i.d. table kept for continuity with the r3/r4 artifacts
    section("quality_calibration", lambda: quality_calibration(
        np.random.default_rng(19), n_holes=64 if a.full else 16,
        err=ERR_BIASED))
    section("quality_calibration_iid", lambda: quality_calibration(
        np.random.default_rng(23), n_holes=64 if a.full else 16))
    print(json.dumps(res, indent=1))
    if a.json:
        with open(a.json, "w") as f:
            json.dump(res, f, indent=1)
        if os.path.exists(a.json + ".partial"):
            os.remove(a.json + ".partial")


if __name__ == "__main__":
    main()
