"""Batched device pipeline (pipeline/batch.py): parity with the per-hole
path, shape-bucketed execution, ordering, quarantine, and resume."""

import numpy as np
import pytest

from ccsx_tpu import cli
from ccsx_tpu.config import CcsConfig
from ccsx_tpu.consensus.star import RoundRequest, StarMsa, run_rounds
from ccsx_tpu.consensus.windowed import windowed_gen
from ccsx_tpu.io import fastx
from ccsx_tpu.ops import encode as enc
from ccsx_tpu.pipeline.batch import BatchExecutor, _z_bucket
from ccsx_tpu.utils import synth


def _passes(rng, n=4, tlen=600):
    tpl = rng.integers(0, 4, tlen).astype(np.uint8)
    return [synth.mutate(rng, tpl, 0.02, 0.04, 0.04) for _ in range(n)]


def test_z_bucket():
    assert _z_bucket(1) == 1
    assert _z_bucket(3) == 4
    assert _z_bucket(64) == 64
    assert _z_bucket(65) == 128  # keeps doubling: bounded retraces


@pytest.mark.slow  # ~23s: fine-grained executor-vs-per-hole A/B; the
# CLI batched==per-hole byte-identity pin below keeps the invariant
# tier-1 (r20 budget audit)
def test_executor_matches_per_hole_rounds(rng):
    """One batched dispatch == N independent per-hole rounds, bitwise."""
    cfg = CcsConfig(is_bam=False)
    sm = StarMsa(cfg.align, cfg.max_ins_per_col, cfg.len_bucket_quant)
    reqs = []
    for i in range(5):
        ps = _passes(rng, n=3 + (i % 3), tlen=500 + 40 * i)
        qs, qlens, row_mask = sm.pack(ps, cfg.pass_buckets, cfg.max_passes)
        reqs.append(RoundRequest(qs, qlens, row_mask, ps[0]))

    batched = BatchExecutor(cfg).run(reqs)
    for req, rb in zip(reqs, batched):
        ra = sm.round(req.qs, req.qlens, req.row_mask, req.draft)
        assert ra.tlen == rb.tlen
        np.testing.assert_array_equal(ra.cons, rb.cons)
        np.testing.assert_array_equal(ra.ins_base, rb.ins_base)
        np.testing.assert_array_equal(ra.ins_votes, rb.ins_votes)
        np.testing.assert_array_equal(ra.ncov, rb.ncov)
        # the batched path leaves the big per-pass tensors on device and
        # returns the device breakpoint + advance instead; they must
        # equal the host spec computed from the per-hole result
        assert rb.aligned is None and rb.match is None
        from ccsx_tpu.consensus import windowed as win_mod

        nseq = int(req.row_mask.sum())
        host_bp = win_mod.find_breakpoint(ra, nseq, cfg)
        assert (rb.bp if rb.bp >= 1 else None) == host_bp
        bp_eff = host_bp if host_bp is not None else max(
            ra.tlen - cfg.bp_window, 1)
        np.testing.assert_array_equal(
            rb.advance, win_mod._advance(ra, bp_eff).astype(np.int32))


@pytest.mark.slow  # ~17s window sweep; the CLI batched==per-hole pin
# keeps the executor tier-1 (r13 audit; r20 moved per-hole-rounds slow)
def test_executor_drives_windowed_gen_to_same_result(rng):
    """Driving the windowed generator with batched results reproduces the
    per-hole windowed consensus exactly."""
    cfg = CcsConfig(is_bam=False, window_init=512, window_add=512,
                    window_minlen=256, max_window=2048)
    sm = StarMsa(cfg.align, cfg.max_ins_per_col, cfg.len_bucket_quant)
    ps = _passes(rng, n=5, tlen=1500)

    want = run_rounds(windowed_gen(ps, cfg), sm)

    ex = BatchExecutor(cfg)
    gen = windowed_gen(ps, cfg)
    req = next(gen)
    try:
        while True:
            rr = ex.run([req])[0]
            req = gen.send(rr)
    except StopIteration as e:
        got = e.value
    np.testing.assert_array_equal(want, got)


def _make_inputs(tmp_path, rng, n_holes, tlen=900):
    # >=5 passes so every hole clears the count filter (min_fulllen_count+2)
    zs = [synth.make_zmw(rng, template_len=tlen, n_passes=5 + (h % 3),
                         movie="mv", hole=str(100 + h))
          for h in range(n_holes)]
    fa = tmp_path / "in.fa"
    fa.write_text(synth.make_fasta(zs))
    return zs, fa


def test_cli_batched_equals_per_hole(tmp_path, rng):
    """--batch on must produce byte-identical output to --batch off."""
    zs, fa = _make_inputs(tmp_path, rng, n_holes=4)
    o_ref = tmp_path / "ref.fa"
    o_bat = tmp_path / "bat.fa"
    assert cli.main(["-A", "-m", "1000", "--batch", "off",
                     str(fa), str(o_ref)]) == 0
    assert cli.main(["-A", "-m", "1000", "--batch", "on",
                     str(fa), str(o_bat)]) == 0
    assert o_ref.read_text() == o_bat.read_text()
    assert o_ref.read_text().count(">") == 4


@pytest.mark.slow  # ~20s: projector A/B; per-hole equality tests stay tier-1 (r11 audit)
def test_cli_batched_scan_projector_equals_walk(tmp_path, rng, monkeypatch):
    """CCSX_PROJECTOR=scan (the TPU-default row-scan traceback,
    ops/traceback.make_projector_scan) through the FULL fused batched
    pipeline must be byte-identical to the walk default — integration
    coverage for the composition (vmap inside _refine_step's while_loop)
    that unit differential tests can't see."""
    from ccsx_tpu.consensus import star
    from ccsx_tpu.pipeline import batch as batch_mod

    zs, fa = _make_inputs(tmp_path, rng, n_holes=3, tlen=1100)
    o_ref = tmp_path / "ref.fq"
    o_scan = tmp_path / "scan.fq"
    args = ["-A", "-m", "1000", "--fastq", "--batch", "on"]

    def clear():
        for fn in (star._projector, batch_mod._round_body,
                   batch_mod._round_step, batch_mod._refine_step):
            fn.cache_clear()

    # pin BOTH runs explicitly: the unset-env default is the walk on
    # every backend (until the TPU A/B flips it), but a pre-set
    # CCSX_PROJECTOR in the environment would pollute the baseline
    clear()  # projector impl is read when the builders run
    monkeypatch.setenv("CCSX_PROJECTOR", "walk")
    try:
        assert cli.main(args + [str(fa), str(o_ref)]) == 0
        clear()
        monkeypatch.setenv("CCSX_PROJECTOR", "scan")
        assert cli.main(args + [str(fa), str(o_scan)]) == 0
    finally:
        monkeypatch.undo()
        clear()
    assert o_ref.read_text() == o_scan.read_text()
    assert o_ref.read_text().count("@") >= 3


def test_cli_batched_whole_read_equals_per_hole(tmp_path, rng):
    zs, fa = _make_inputs(tmp_path, rng, n_holes=3)
    o_ref = tmp_path / "ref.fa"
    o_bat = tmp_path / "bat.fa"
    assert cli.main(["-A", "-P", "-m", "1000", "--batch", "off",
                     str(fa), str(o_ref)]) == 0
    assert cli.main(["-A", "-P", "-m", "1000", "--batch", "on",
                     str(fa), str(o_bat)]) == 0
    assert o_ref.read_text() == o_bat.read_text()


@pytest.mark.slow  # ~10s: a third batch-grid point (r20 budget audit,
# same family as the two r16 demotions); the CLI batched==per-hole
# byte-identity pin keeps ordering tier-1 at the default window
def test_cli_batched_small_inflight_preserves_order(tmp_path, rng):
    """A tiny in-flight window forces staggered admission; output order
    must stay input order."""
    zs, fa = _make_inputs(tmp_path, rng, n_holes=5, tlen=700)
    out = tmp_path / "o.fa"
    assert cli.main(["-A", "-m", "1000", "--batch", "on",
                     "--inflight", "2", str(fa), str(out)]) == 0
    names = [r.name for r in fastx.read_fastx(str(out))]
    assert names == [f"mv/{100 + h}/ccs" for h in range(5)]


def test_cli_batched_journal_resume(tmp_path, rng):
    import json

    zs, fa = _make_inputs(tmp_path, rng, n_holes=3, tlen=700)
    full = tmp_path / "full.fa"
    assert cli.main(["-A", "-m", "1000", "--batch", "on",
                     str(fa), str(full)]) == 0
    out = tmp_path / "o.fa"
    jp = tmp_path / "j.json"
    jp.write_text(json.dumps({"input_id": str(fa), "holes_done": 2}))
    recs = list(fastx.read_fastx(str(full)))
    out.write_text("".join(f">{r.name}\n{r.seq.decode()}\n"
                           for r in recs[:2]))
    assert cli.main(["-A", "-m", "1000", "--batch", "on",
                     "--journal", str(jp), str(fa), str(out)]) == 0
    assert out.read_text() == full.read_text()
    assert json.loads(jp.read_text())["holes_done"] == 3


def test_executor_deep_pass_vote_compaction(rng):
    """uint8 vote/coverage transfer must stay exact at the deepest pass
    bucket (64): votes*2 reaches 128 — the compaction headroom case."""
    cfg = CcsConfig(is_bam=False, max_passes=64,
                    pass_buckets=(4, 8, 16, 32, 64))
    sm = StarMsa(cfg.align, cfg.max_ins_per_col, cfg.len_bucket_quant)
    tpl = rng.integers(0, 4, 300).astype(np.uint8)
    from ccsx_tpu.utils import synth as synth_mod

    ps = [synth_mod.mutate(rng, tpl, 0.02, 0.04, 0.04) for _ in range(40)]
    qs, qlens, row_mask = sm.pack(ps, cfg.pass_buckets, cfg.max_passes)
    req = RoundRequest(qs, qlens, row_mask, ps[0])
    rb = BatchExecutor(cfg).run([req])[0]
    ra = sm.round(req.qs, req.qlens, req.row_mask, req.draft)
    np.testing.assert_array_equal(ra.cons, rb.cons)
    np.testing.assert_array_equal(ra.ins_votes, rb.ins_votes)
    np.testing.assert_array_equal(ra.ncov, rb.ncov)
    assert int(np.asarray(rb.ncov).max()) == 40
    # materialization arithmetic (votes*2 > ncov) must agree too
    np.testing.assert_array_equal(ra.materialize(), rb.materialize())


@pytest.mark.parametrize("mesh", [
    (4, 2),
    # extra mesh shapes ride slow; (4,2) + test_sharded_round's
    # split-invariant pin the 'pass' collectives tier-1 (r16 budget audit)
    pytest.param((2, 4), marks=pytest.mark.slow),
    pytest.param((8, 1), marks=pytest.mark.slow),
])
def test_executor_pass_axis_mesh_matches_per_hole(rng, mesh):
    """The production batched round under a (data, pass) mesh must equal
    the per-hole rounds exactly — GSPMD's psums over 'pass' are the same
    collectives tests/test_sharded_round.py pins."""
    cfg = CcsConfig(is_bam=False, mesh_shape=mesh)
    sm = StarMsa(cfg.align, cfg.max_ins_per_col, cfg.len_bucket_quant)
    reqs = []
    for i in range(5):
        ps = _passes(rng, n=5 + (i % 4), tlen=500 + 40 * i)  # P bucket 8
        qs, qlens, row_mask = sm.pack(ps, cfg.pass_buckets, cfg.max_passes)
        reqs.append(RoundRequest(qs, qlens, row_mask, ps[0]))
    batched = BatchExecutor(cfg).run(reqs)
    from ccsx_tpu.consensus import windowed as win_mod

    for req, rb in zip(reqs, batched):
        ra = sm.round(req.qs, req.qlens, req.row_mask, req.draft)
        np.testing.assert_array_equal(ra.cons, rb.cons)
        np.testing.assert_array_equal(ra.ins_base, rb.ins_base)
        np.testing.assert_array_equal(ra.ins_votes, rb.ins_votes)
        np.testing.assert_array_equal(ra.ncov, rb.ncov)
        # the on-device breakpoint/advance must survive the pass axis too
        nseq = int(req.row_mask.sum())
        host_bp = win_mod.find_breakpoint(ra, nseq, cfg)
        assert (rb.bp if rb.bp >= 1 else None) == host_bp
        bp_eff = host_bp if host_bp is not None else max(
            ra.tlen - cfg.bp_window, 1)
        np.testing.assert_array_equal(
            rb.advance, win_mod._advance(ra, bp_eff).astype(np.int32))


def test_cli_mesh_flag_output_identical(tmp_path, rng):
    """--mesh 4,2 (pass-parallel production path) == --batch off output."""
    zs, fa = _make_inputs(tmp_path, rng, n_holes=3)
    o_ref = tmp_path / "ref.fa"
    o_mesh = tmp_path / "mesh.fa"
    assert cli.main(["-A", "-m", "1000", "--batch", "off",
                     str(fa), str(o_ref)]) == 0
    assert cli.main(["-A", "-m", "1000", "--batch", "on", "--mesh", "4,2",
                     str(fa), str(o_mesh)]) == 0
    assert o_ref.read_text() == o_mesh.read_text()


def test_cli_mesh_flag_invalid(tmp_path, capsys):
    rc = cli.main(["--mesh", "nope", "x.fa", str(tmp_path / "y.fa")])
    assert rc == 1
    assert "--mesh" in capsys.readouterr().err


def test_cli_mesh_too_large_clean_error(tmp_path, rng, capsys):
    """An infeasible --mesh fails rc 1 with a clean message and must NOT
    truncate an existing output file."""
    zs, fa = _make_inputs(tmp_path, rng, n_holes=1)
    out = tmp_path / "o.fa"
    out.write_text("precious\n")
    rc = cli.main(["-A", "-m", "1000", "--batch", "on", "--mesh", "16,2",
                   str(fa), str(out)])
    assert rc == 1
    assert "invalid --mesh" in capsys.readouterr().err
    assert out.read_text() == "precious\n"


@pytest.mark.slow  # ~12s: transfer-protocol A/B; the single-device ==
# multi-device dispatch pin (test_dispatch.py::test_fused_multichip_
# byte_identical_to_single_device) keeps the divergence seam tier-1
# (r20 budget audit)
def test_packed_transfer_protocol_matches_unpacked(rng):
    """The packed single-device transfer protocol (one uint8 + one int32
    buffer each way, pipeline/batch._pack_args/_unpack_round/_unpack_
    refine) must be bit-identical to the separate-array protocol the
    multi-device path ships — if they drift, single-chip and sharded
    runs diverge silently."""
    from ccsx_tpu.pipeline import batch as bm

    cfg = CcsConfig(is_bam=False)
    sm = StarMsa(cfg.align, cfg.max_ins_per_col, cfg.len_bucket_quant)
    ps = _passes(rng, n=4, tlen=700)
    qs, qlens, row_mask = sm.pack(ps, cfg.pass_buckets, cfg.max_passes)
    P, qmax = qs.shape
    ex = BatchExecutor(cfg)
    tmax = bm.bucket_len(len(ps[0]), cfg.len_bucket_quant)
    args = ex._stack_group(
        [RoundRequest(qs, qlens, row_mask, ps[0])], [0], P, qmax, tmax)
    bp_consts = ex._bp_consts()

    plain = bm._round_step(cfg.align, cfg.max_ins_per_col, tmax,
                           bp_consts)(*args)
    packed = bm._round_step(cfg.align, cfg.max_ins_per_col, tmax,
                            bp_consts, pack=(P, qmax))(
                                *bm._pack_args(args))
    un = bm._unpack_round(np.asarray(packed[0]), np.asarray(packed[1]),
                          cfg.max_ins_per_col, tmax)
    for a, b in zip(plain, un):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    rplain = bm._refine_step(cfg.align, cfg.max_ins_per_col, tmax,
                             cfg.refine_iters, bp_consts)(*args)
    rpacked = bm._refine_step(cfg.align, cfg.max_ins_per_col, tmax,
                              cfg.refine_iters, bp_consts,
                              pack=(P, qmax))(*bm._pack_args(args))
    run = bm._unpack_refine(np.asarray(rpacked[0]),
                            np.asarray(rpacked[1]),
                            cfg.max_ins_per_col, tmax)
    for a, b in zip(rplain, run):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow  # ~13s knob A/B; test_packing's packed==bucketed==
# per-hole CLI pin keeps the invariant tier-1 (r13 audit)
def test_pass_buckets_knob_output_invariant(tmp_path, rng):
    """--pass-buckets changes only device padding (masked rows), never
    output bytes — the invariance that makes it a safe tuning knob —
    while the occupancy counters show the repacking happened."""
    import json

    zs = [synth.make_zmw(rng, template_len=900, n_passes=5 + (h % 6),
                         movie="mv", hole=str(h)) for h in range(4)]
    fa = tmp_path / "in.fa"
    fa.write_text(synth.make_fasta(zs))
    outs, fills = [], []
    for i, extra in enumerate(([], ["--pass-buckets", "6,12,32"])):
        o = tmp_path / f"o{i}.fq"
        m = tmp_path / f"m{i}.jsonl"
        assert cli.main(["-A", "-m", "1000", "--fastq", "--batch", "on",
                         "--metrics", str(m), *extra, str(fa),
                         str(o)]) == 0
        outs.append(o.read_text())
        fin = [json.loads(ln) for ln in m.read_text().splitlines()][-1]
        fills.append(fin["dp_pass_fill"])
    assert outs[0] == outs[1]
    # the repacking is real (which direction depends on the pass
    # distribution — that is exactly what the knob is for)
    assert fills[0] != fills[1], fills


def test_pass_buckets_bad_value_rejected(capsys):
    assert cli.main(["--pass-buckets", "8,4", "in.fa", "out.fa"]) == 1
    assert "--pass-buckets" in capsys.readouterr().err
