#!/bin/sh
# End-of-round TPU measurement battery (r5b order).  Run when the
# tunnel is healthy; each step is its own process.  ALL timing uses the
# forced-execution marginal method (bench.py docstring): the lazy axon
# runtime neither blocks in block_until_ready nor executes unfetched
# dispatches, so only fori_loop+checksum+fetch numbers are real.
#
#   sh benchmarks/tpu_battery.sh            # full battery
set -x
cd "$(dirname "$0")/.."

# (1) the honest round number + compile-cache warm for the driver's
# end-of-round bench (the fori_loop programs need one long compile)
CCSX_BENCH_WATCHDOG=2400 python bench.py | tee benchmarks/bench_tpu_r05b.json

# (2) e2e at scale over the packed transfer protocol (the CLI writes
# real output files, so its wall-clock numbers are honest everywhere)
python benchmarks/e2e_scale.py --holes 256 --inflight 64 \
    --json benchmarks/e2e_scale_r05_packed.json

# (3) honest per-stage round profile + op-level jax.profiler trace
# (the artifact the roofline claim is checked against), then the
# scan-projector A/B
python benchmarks/round_profile.py --trace-dir benchmarks/trace_r05b \
    --json benchmarks/round_profile_r05b.json
CCSX_PROJECTOR=scan python benchmarks/round_profile.py \
    --json benchmarks/round_profile_r05b_scanproj.json

# (4) pallas A/B with the honest harness if time remains
python benchmarks/pallas_ab.py --mode check
python benchmarks/pallas_ab.py --mode time --gblocks 8,16,32 \
    --json benchmarks/pallas_ab_tpu_r05b.json
