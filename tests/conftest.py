"""Test harness config: force JAX onto an 8-device virtual CPU mesh.

Must run before jax is imported anywhere (pytest imports conftest first).
The driver validates real multi-chip sharding separately via
__graft_entry__.dryrun_multichip.
"""

import os

# force CPU with 8 virtual devices: the environment's axon (TPU tunnel)
# plugin overrides JAX_PLATFORMS at import time, so the env var alone is
# not enough — set the config explicitly before any backend initializes.
# CCSX_TEST_TPU=1 opts out, running the suite on the real chip (used to
# run the Pallas differential tests with interpret=False on hardware).
_ON_TPU = os.environ.get("CCSX_TEST_TPU") == "1"
os.environ["CCSX_SKIP_PROBE"] = "1"
if not _ON_TPU:
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

import jax  # noqa: E402

if not _ON_TPU:
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
