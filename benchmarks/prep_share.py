"""Prep-share measurement (VERDICT r2 item 6; align_host.py's criterion).

Times host prep (ccs_prepare: orientation/clip strand_match walk) over a
mixed chunk of >=1024 holes and compares it against the device-round time
the same holes' consensus needs:

  * measured        — prep_s vs compute_s from a real batched pipeline
                      run on the resolved backend;
  * at-peak projection — compute projected at bench.py round speed
                      (windows x per-window dispatch time at the bench's
                      measured zmw_windows/s), the criterion the
                      align_host.py docstring states: if prep exceeds
                      ~10% of wall time at device-round speed, batch it.

Usage: python benchmarks/prep_share.py [--holes N] [--json out.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from ccsx_tpu import cli                                     # noqa: E402
from ccsx_tpu.utils import synth                             # noqa: E402


def make_holes(rng, n):
    """Mixed chunk: varying lengths, pass counts, partial ends."""
    zs = []
    for h in range(n):
        tlen = int(rng.integers(600, 2600))
        n_passes = int(np.clip(round(rng.lognormal(np.log(8), 0.5)), 5, 24))
        zs.append(synth.make_zmw(
            rng, tlen, n_passes, movie="mv", hole=str(h),
            partial_ends=bool(h % 3 == 0)))
    return zs


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--holes", type=int, default=1024)
    ap.add_argument("--json", default=None)
    ap.add_argument("--device", default="auto",
                    choices=["auto", "tpu", "cpu"])
    ap.add_argument("--prep-threads", type=int, default=None,
                    dest="prep_threads",
                    help="forwarded to the CLI: overlapped prep plane "
                         "width (0 = inline, the A/B control) "
                         "[CLI auto]")
    ap.add_argument("--bench-zmw-windows-per-sec", type=float, default=None,
                    help="round speed for the at-peak projection "
                         "[read BENCH value or bench_peak.json]")
    a = ap.parse_args()

    from ccsx_tpu.utils.device import resolve_device

    resolve_device(a.device)
    import jax

    rng = np.random.default_rng(11)
    zs = make_holes(rng, a.holes)
    with tempfile.TemporaryDirectory() as tmp:
        fa = os.path.join(tmp, "in.fa")
        open(fa, "w").write(synth.make_fasta(zs))
        out = os.path.join(tmp, "out.fa")
        met = os.path.join(tmp, "m.jsonl")
        extra = ([] if a.prep_threads is None
                 else ["--prep-threads", str(a.prep_threads)])
        t0 = time.perf_counter()
        rc = cli.main(["-A", "-m", "1000", "--batch", "on",
                       "--metrics", met, *extra, fa, out])
        wall = time.perf_counter() - t0
        assert rc == 0
        final = [json.loads(line) for line in open(met)][-1]

    prep_s = final["prep_s"]
    compute_s = final["compute_s"]
    windows = final["windows"]
    res = {
        "backend": jax.default_backend(),
        "holes": a.holes,
        "wall_s": round(wall, 2),
        "prep_s": prep_s,
        "compute_s": compute_s,
        "ingest_s": final["ingest_s"],
        "write_s": final["write_s"],
        "windows": windows,
        "device_dispatches": final["device_dispatches"],
        "prep_ms_per_hole": round(prep_s / a.holes * 1e3, 3),
        # prep WORK share (summed across pool threads when the prep
        # plane is on — can legitimately exceed the blocked share)
        "prep_share_measured": round(prep_s / max(wall, 1e-9), 4),
        # prep plane counters (pipeline/prep_pool.py): the critical-path
        # share the <= 0.10 bar reads, and the overlap quality
        "prep_threads": final.get("prep_threads"),
        "prep_blocked_s": final.get("prep_blocked_s"),
        "prep_share_blocked": final.get("prep_share"),
        "prep_overlap_share": final.get("prep_overlap_share"),
        "prep_queue_peak": final.get("prep_queue_peak"),
    }
    # at-peak projection: what the share becomes when the device rounds
    # run at bench.py speed (each zmw-window ~ 1/bench_rate seconds).
    # Window shapes here are close to the bench shapes (P<=16, W<=2560);
    # the projection is deliberately rough — order-of-magnitude is what
    # the 10% criterion needs.
    rate = a.bench_zmw_windows_per_sec
    if rate is None:
        rate = 170000.0  # v5e measured 2026-07-29 (BENCH_r03 ballpark)
    proj_compute = windows / rate
    res["peak_zmw_windows_per_sec"] = rate
    res["projected_compute_s_at_peak"] = round(proj_compute, 4)
    res["prep_share_at_peak"] = round(
        prep_s / max(prep_s + proj_compute, 1e-9), 4)

    # direct A/B of the pair-alignment batching (PairExecutor vs the
    # per-pair HostAligner path) on alignment-heavy pairs — the synthetic
    # chunk above rarely aligns (its fragments are skipped pre-alignment:
    # walk() drops out-of-group passes shorter than the template), so the
    # residual prep_s there is host Python (group_lens + generator
    # startup), not pair fills
    from ccsx_tpu.config import AlignParams
    from ccsx_tpu.consensus import prepare as prep_mod
    from ccsx_tpu.consensus.align_host import HostAligner
    from ccsx_tpu.pipeline.batch import PairExecutor

    pr_rng = np.random.default_rng(5)
    pairs = []
    for _ in range(64):
        tl = int(pr_rng.integers(1200, 1600))
        tpl = pr_rng.integers(0, 4, tl).astype(np.uint8)
        q = synth.mutate(pr_rng, tpl, 0.03, 0.05, 0.05)
        pairs.append(prep_mod.PairRequest(q, tpl, 75))
    host = HostAligner(AlignParams())
    pe = PairExecutor(AlignParams())
    # warm both arms before timing.  The device arm warms through the
    # PRODUCTION warmup API (PairExecutor.warm — the same factory and
    # zero-input dispatch the pipeline's AOT precompiler uses,
    # pipeline/warmup.py) instead of the old hand-rolled double-run, so
    # this bench's timings and the production path compile through one
    # code path; without a WarmupCompiler attached, warm() is
    # synchronous.
    host.strand_match(pairs[0].q, pairs[0].t, 75)
    pe.warm(pairs)
    t0 = time.perf_counter()
    for pr in pairs:
        host.strand_match(pr.q, pr.t, pr.pct)
    t_host = time.perf_counter() - t0
    t0 = time.perf_counter()
    batched = pe.run(pairs)
    t_batch = time.perf_counter() - t0
    per_pair = [host.strand_match(pr.q, pr.t, pr.pct) for pr in pairs]
    agree = sum(a[0] == b[0] and a[1].qb == b[1].qb and a[1].qe == b[1].qe
                for a, b in zip(per_pair, batched))
    res["pair_ab"] = {
        "pairs": len(pairs),
        "per_pair_s": round(t_host, 4),
        "batched_s": round(t_batch, 4),
        "speedup": round(t_host / max(t_batch, 1e-9), 2),
        "results_agree": agree,
    }
    print(json.dumps(res, indent=1))
    if a.json:
        with open(a.json, "w") as f:
            json.dump(res, f, indent=1)


if __name__ == "__main__":
    main()
