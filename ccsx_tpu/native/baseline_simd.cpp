// Banded affine-gap FILL kernel for the CPU baseline measurement
// (benchmarks/cpu_baseline.py; VERDICT r4 item 4).
//
// The north-star comparison (BASELINE.md) needs an honest per-core CPU
// cells/s for the workload the reference actually runs: bsalign's
// banded-striped SIMD fill (reference main.c:849 band=128; reference
// Makefile:6-17 builds SSE4.2/AVX2 dispatch).  bsalign itself is not
// buildable offline, so this file measures the SAME banded recurrence
// the TPU path computes (ops/banded.py: band=128, affine Gotoh,
// horizontal gap via max-plus prefix scan, deterministic nominal band
// line), compiled TWICE from identical source (Makefile):
//
//   * ccsx_banded_fill_vec    — -O3 -march=native: every per-row step
//     is an elementwise/shifted-pointer loop over a fixed 128-wide
//     int16 band (the shape compilers vectorize to AVX2/AVX-512), the
//     horizontal scan is log2(128) ping-pong Hillis-Steele passes
//   * ccsx_banded_fill_scalar — -O2 -fno-tree-vectorize: the "1 lane"
//     control (CCSX_VARIANT_SCALAR translation unit)
//
// vec/scalar on identical source + bit-identical output IS the
// measured SIMD factor that replaces the old guessed 8x credit.  A
// thread-pool driver (ccsx_banded_fill_many) measures pair-level
// scaling — the reference's own parallel shape (kthread.c:48-65,
// atomic work claiming over holes) — though on 1-core hosts the curve
// measures the host.
//
// Fill only, no traceback: the baseline unit is DP cells/s and the
// fill dominates bsalign's runtime; both variants return the final
// band row so the differential test (tests/test_native_align.py) can
// assert bit-equality and the compiler cannot dead-code the loop.

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <atomic>
#include <thread>
#include <vector>

namespace {

constexpr int kBand = 128;           // == reference bandwidth, main.c:849
constexpr int16_t kNeg = -16384;     // safe: |scores| < band*|weights| << 16k

}  // namespace

#if defined(CCSX_VARIANT_SCALAR)
#define FILL_NAME ccsx_banded_fill_scalar
#else
#define FILL_NAME ccsx_banded_fill_vec
#endif

extern "C" int FILL_NAME(const uint8_t* q, int64_t qlen, const uint8_t* t,
                         int64_t tlen, int match, int mismatch, int gap_open,
                         int gap_ext, int16_t* h_final) {
  if (!q || !t || !h_final || qlen <= 0 || tlen <= 0) return -1;
  const int16_t mat = (int16_t)match, mis = (int16_t)mismatch;
  const int16_t oe = (int16_t)(gap_open + gap_ext), ge = (int16_t)gap_ext;

  // band arrays padded [1 left, 3 right] so vertical/diag predecessors
  // at shift d in [0,2] are plain shifted-pointer reads, no clamping
  alignas(64) int16_t Hp[kBand + 4], Ep[kBand + 4];
  alignas(64) int16_t H[kBand], E[kBand], tq[kBand], jge[kBand];
  alignas(64) int16_t b0[kBand], b1[kBand];

  for (int j = 0; j < kBand; j++) jge[j] = (int16_t)(j * ge);

  // row 0: band at template col 0; global init H(0,col) = open + col*ext
  int64_t off = 0;
  for (int j = 0; j < kBand; j++) {
    int64_t col = off + j;
    H[j] = col == 0 ? 0
         : (col <= tlen ? (int16_t)(oe + (col - 1) * ge) : kNeg);
    E[j] = kNeg;
  }

  for (int64_t i = 1; i <= qlen; i++) {
    // deterministic nominal line (i*tlen/qlen), shift bounded [0,2]
    // (ops/banded.py's band walk; argmax adaptation deliberately absent
    // there and here)
    int64_t center = (i * tlen) / qlen;
    int64_t noff = std::min(std::max(center - kBand / 2, (int64_t)0),
                            std::max(tlen + 1 - kBand, (int64_t)0));
    int d = (int)std::min(std::max(noff - off, (int64_t)0), (int64_t)2);
    noff = off + d;

    Hp[0] = kNeg; Ep[0] = kNeg;
    std::memcpy(Hp + 1, H, sizeof H);
    std::memcpy(Ep + 1, E, sizeof E);
    for (int j = 0; j < 3; j++) {
      Hp[kBand + 1 + j] = kNeg;
      Ep[kBand + 1 + j] = kNeg;
    }

    // template lanes: contiguous widening copy + sentinel edges
    // (lane j is template col noff+j; sentinel never matches)
    {
      int64_t lo = std::max((int64_t)1 - noff, (int64_t)0);
      int64_t hi = std::min((int64_t)kBand, tlen + 1 - noff);
      for (int64_t j = 0; j < lo; j++) tq[j] = 0x7fff;
      for (int64_t j = lo; j < hi; j++) tq[j] = t[noff + j - 1];
      for (int64_t j = std::max(hi, lo); j < kBand; j++) tq[j] = 0x7fff;
    }

    // E (vertical), diag, h0 = max(diag, E), scan input — elementwise
    const int16_t qi = q[i - 1] < 4 ? (int16_t)q[i - 1] : (int16_t)0x7ffe;
    const int16_t* hv = Hp + 1 + d;  // vertical pred of lane j
    const int16_t* ev = Ep + 1 + d;
    const int16_t* hd = Hp + d;      // diagonal pred of lane j
    for (int j = 0; j < kBand; j++) {
      int16_t e1 = (int16_t)(hv[j] + oe), e2 = (int16_t)(ev[j] + ge);
      int16_t e = e1 > e2 ? e1 : e2;
      E[j] = e;
      int16_t s = tq[j] == qi ? mat : mis;
      int16_t h0 = (int16_t)(hd[j] + s);
      if (e > h0) h0 = e;
      H[j] = h0;
      b0[j] = (int16_t)(h0 + oe - jge[j]);
    }
    if (noff == 0) b0[0] = kNeg;  // col 0 opens no horizontal gap

    // F[j] = ge*j + max_{k<j} b[k]: exclusive max-prefix-scan as
    // log2(128) ping-pong Hillis-Steele passes (each elementwise over
    // disjoint src/dst, so the compiler can vectorize every pass)
    {
      int16_t *src = b0, *dst = b1;
      for (int s = 1; s < kBand; s <<= 1) {
        std::memcpy(dst, src, (size_t)s * sizeof(int16_t));
        for (int j = s; j < kBand; j++)
          dst[j] = src[j] > src[j - s] ? src[j] : src[j - s];
        std::swap(src, dst);
      }
      for (int j = 1; j < kBand; j++) {
        int16_t f = (int16_t)(src[j - 1] + jge[j]);
        if (f > H[j]) H[j] = f;
      }
    }
    if (noff == 0) {  // reinstate the global first-column init
      H[0] = (int16_t)(oe + (i - 1) * ge);
      E[0] = (int16_t)(oe + (i - 1) * ge);
    }
    off = noff;
  }
  std::memcpy(h_final, H, sizeof H);
  return 0;
}

#if !defined(CCSX_VARIANT_SCALAR)

extern "C" int ccsx_banded_fill_scalar(const uint8_t*, int64_t,
                                       const uint8_t*, int64_t, int, int,
                                       int, int, int16_t*);

// Thread pool over independent pairs (the reference's hole-level
// parallelism, kthread.c:48-65: atomic work claiming, no ordering).
// qs/ts: npairs sequences of qlen/tlen each, row-major.  h_finals:
// npairs * 128 int16 (may be null -> scratch).  Returns cells filled.
extern "C" int64_t ccsx_banded_fill_many(
    const uint8_t* qs, const uint8_t* ts, int64_t qlen, int64_t tlen,
    int64_t npairs, int nthreads, int vectorized, int match, int mismatch,
    int gap_open, int gap_ext, int16_t* h_finals) {
  if (!qs || !ts || qlen <= 0 || tlen <= 0 || npairs <= 0 || nthreads <= 0)
    return -1;
  std::atomic<int64_t> next(0);
  auto worker = [&]() {
    std::vector<int16_t> scratch(kBand);
    for (;;) {
      int64_t k = next.fetch_add(1);
      if (k >= npairs) return;
      int16_t* hf = h_finals ? h_finals + k * kBand : scratch.data();
      if (vectorized)
        ccsx_banded_fill_vec(qs + k * qlen, qlen, ts + k * tlen, tlen,
                             match, mismatch, gap_open, gap_ext, hf);
      else
        ccsx_banded_fill_scalar(qs + k * qlen, qlen, ts + k * tlen, tlen,
                                match, mismatch, gap_open, gap_ext, hf);
    }
  };
  std::vector<std::thread> pool;
  for (int n = 1; n < nthreads; n++) pool.emplace_back(worker);
  worker();
  for (auto& th : pool) th.join();
  return npairs * qlen * kBand;
}

#endif  // !CCSX_VARIANT_SCALAR
