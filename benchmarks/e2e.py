"""End-to-end ZMWs/sec benchmark over the five BASELINE.md configs.

Each config generates a synthetic input shaped like the baseline plan's
(the real Sequel II subreads.bam is not in the environment), runs the full
CLI — ingest, prep, consensus, write — and reports holes/sec plus mean
consensus identity against the known templates.  JSON lines on stdout.

Usage:
    python benchmarks/e2e.py [--holes N] [--config 1..5] [--batch auto|on|off]
"""

from __future__ import annotations

import argparse
import gzip
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ccsx_tpu import cli                                     # noqa: E402
from ccsx_tpu.io import bam, fastx                           # noqa: E402
from ccsx_tpu.ops import encode as enc                       # noqa: E402
from ccsx_tpu.utils import synth                             # noqa: E402


def _fastq(zs) -> str:
    out = []
    for z in zs:
        for name, p in zip(z.names, z.passes):
            s = enc.decode(p)
            out.append(f"@{name}\n{s}\n+\n{'~' * len(s)}\n")
    return "".join(out)


def make_input(config: int, n_holes: int, rng, tmp):
    """Returns (input_path, cli_args, zmws)."""
    if config == 1:    # single-ZMW FASTA (-A), ~1kb, shred
        # NB the plan says 3 subreads, but the count filter keeps holes
        # only at >= c+2 = 5 subreads (main.c:659) — the reference would
        # emit nothing; 5 passes keeps the config meaningful.
        zs = [synth.make_zmw(rng, 1000, 5, movie="mv", hole="1")]
        p = os.path.join(tmp, "c1.fa")
        open(p, "w").write(synth.make_fasta(zs))
        return p, ["-A", "-m", "1000", "-c", "3"], zs
    if config == 2:    # subreads.bam, defaults (-c 3 -m 5000)
        zs = [synth.make_zmw(rng, 2000, 5 + (h % 3), movie="mv",
                             hole=str(h)) for h in range(n_holes)]
        p = os.path.join(tmp, "c2.bam")
        recs = [(n, enc.decode(s).encode(), None)
                for z in zs for n, s in zip(z.names, z.passes)]
        bam.write_bam(p, recs)
        return p, [], zs
    if config == 3:    # -P primitive whole-read POA path
        zs = [synth.make_zmw(rng, 1500, 5, movie="mv", hole=str(h))
              for h in range(n_holes)]
        p = os.path.join(tmp, "c3.fa")
        open(p, "w").write(synth.make_fasta(zs))
        return p, ["-A", "-P", "-m", "1000"], zs
    if config == 4:    # high-pass ZMWs (>=15 subreads) — deep MSAs
        zs = [synth.make_zmw(rng, 1500, 15 + (h % 4), movie="mv",
                             hole=str(h)) for h in range(max(n_holes // 2, 1))]
        p = os.path.join(tmp, "c4.fa")
        open(p, "w").write(synth.make_fasta(zs))
        return p, ["-A", "-m", "1000", "-M", "500000"], zs
    if config == 5:    # gzipped FASTQ stream, bucketed batches
        zs = [synth.make_zmw(rng, 1200 + 300 * (h % 4), 4 + (h % 5),
                             movie="mv", hole=str(h)) for h in range(n_holes)]
        p = os.path.join(tmp, "c5.fq.gz")
        with gzip.open(p, "wt") as f:
            f.write(_fastq(zs))
        return p, ["-A", "-m", "1000"], zs
    raise ValueError(config)


def run_config(config: int, n_holes: int, batch: str, seed: int = 0,
               trace_path: str = None,
               stall_timeout: float = None,
               telemetry_port: int = None) -> dict:
    rng = np.random.default_rng(seed)
    with tempfile.TemporaryDirectory() as tmp:
        in_path, args, zs = make_input(config, n_holes, rng, tmp)
        out = os.path.join(tmp, "out.fa")
        mpath = os.path.join(tmp, "m.jsonl")
        extra = []
        if trace_path:
            extra += ["--trace", trace_path]
        if stall_timeout is not None:
            extra += ["--stall-timeout", str(stall_timeout)]
        if telemetry_port:
            # live endpoints while the bench runs (an operator can
            # `ccsx-tpu top host:port` a long battery mid-flight)
            extra += ["--telemetry-port", str(telemetry_port)]
        t0 = time.perf_counter()
        rc = cli.main([*args, "--batch", batch, "--metrics", mpath,
                       *extra, in_path, out])
        dt = time.perf_counter() - t0
        assert rc == 0, f"config {config}: rc={rc}"
        got = {r.name: r.seq for r in fastx.read_fastx(out)}
        idys = []
        for z in zs:
            k = f"{z.movie}/{z.hole}/ccs"
            if k in got:
                idys.append(synth.identity_either(
                    enc.encode(got[k]), z.template))
        with open(mpath) as f:
            lines = f.read().splitlines()
        final = json.loads(lines[-1]) if lines else {}
        import jax

        return {
            "config": config,
            "backend": jax.default_backend(),
            "batch": batch,
            "holes_in": len(zs),
            "holes_out": len(got),
            "seconds": round(dt, 3),
            "zmws_per_sec": round(len(got) / dt, 3),
            # prep plane (pipeline/prep_pool.py): critical-path prep
            # share of wall + how much prep work the overlap hid
            # (bench.py's vs_prev gates prep_share regressions)
            "prep_share": final.get("prep_share"),
            "prep_overlap_share": final.get("prep_overlap_share"),
            # ragged pass-packing occupancy (batched runs; None under
            # --batch off or the bucketed control)
            "dp_row_fill": final.get("dp_row_fill"),
            "packed_holes_per_dispatch": final.get(
                "packed_holes_per_dispatch"),
            # per-shape-group compile/execute attribution (utils/
            # trace.py): lands in every bench artifact so throughput
            # claims carry their own evidence
            "groups": final.get("groups"),
            "degraded": final.get("degraded"),
            # resilient execution (pipeline/resilience.py): a run that
            # completed only via abandoned dispatches or an open
            # circuit breaker produced host-path wall time — bench.py's
            # vs_prev refuses to read it as a comparable perf number
            "device_hangs": final.get("device_hangs"),
            "breaker_trips": final.get("breaker_trips"),
            # tracing forces per-dispatch execution (Span.force), a
            # different discipline than the async untraced overlap —
            # recorded so vs_prev never compares across the two
            "traced": bool(trace_path),
            "mean_identity": round(float(np.mean(idys)), 5) if idys else None,
        }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--holes", type=int, default=16)
    ap.add_argument("--config", type=int, default=None, choices=range(1, 6))
    ap.add_argument("--batch", default="auto", choices=["auto", "on", "off"])
    ap.add_argument("--trace", default=None,
                    help="flight-recorder passthrough: per-config span "
                         "JSONL at <PATH>.c<N>.jsonl")
    ap.add_argument("--stall-timeout", type=float, default=None,
                    dest="stall_timeout",
                    help="hang-watchdog passthrough (seconds)")
    a = ap.parse_args()
    configs = [a.config] if a.config else [1, 2, 3, 4, 5]
    for c in configs:
        tp = f"{a.trace}.c{c}.jsonl" if a.trace else None
        print(json.dumps(run_config(c, a.holes, a.batch, trace_path=tp,
                                    stall_timeout=a.stall_timeout)),
              flush=True)


if __name__ == "__main__":
    main()
