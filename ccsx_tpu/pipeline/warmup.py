"""AOT warmup precompiler: overlap XLA compiles with ingest/prep.

The r7 flight recorder showed cold compiles serializing IN FRONT of the
stream: the first dispatch of every (group, shape) blocks the driver
thread for the whole XLA compile (tens of seconds on TPU, minutes
through a remote-compile tunnel) while the chip and the ingest pipe
both idle.  With canonical slab shapes (pipeline/pack.py, r8) a group's
executables are PREDICTABLE the moment prep yields its first
RefineRequest — qmax/tmax/iters from the request, R from the
(<= ladder)-entry canonical height set — so this module compiles them
on a background thread concurrently with ingest/prep, and the first
real dispatch of a warmed shape runs at steady-state speed.

Mechanism: the builder executes the REAL jitted step (the same object
the dispatch path gets from the lru-cached factory) on an all-zero
slab and blocks until ready.  A zero slab has an all-False row mask,
so every segment starts frozen and the fused while_loop exits without
one iteration — the execution costs ~a breakpoint scan on zeros.
``fn.lower(...).compile()`` would share the XLA compile but NOT the
jit dispatch cache (measured on jax 0.4: the first real call still
pays a retrace + cache population, which would then book as execute
time in the tracer); the zero-slab call primes the exact fast path.

Attribution (utils/trace.py): each builder runs inside a
``device_span(..., warmup=True)`` carrying the SAME group and shape
keys the dispatch span will use, so the warmup books the (group,
shape)'s one compile — and the first real dispatch books as execute,
which is the trace-visible proof the overlap worked.  A warmup span
for an already-seen shape books nothing.

Coordination with the dispatch path: before dispatching a shape, the
executor calls ``claim(key)`` — a still-queued warmup is cancelled
(the dispatch compiles inline, exactly as without warmup), an
in-flight one returns an Event to wait on (the compile is already
running on the other thread; waiting costs no more than compiling and
avoids a duplicate), a finished or unknown one returns None.

``--no-warmup`` (cfg.warmup_compile = False) disables the whole layer:
the drivers then construct no WarmupCompiler and every call site
degrades to r7 behavior.  Compile failures in a builder are swallowed
with a stderr note — the dispatch path retries inline and owns the
real failure ladder (pipeline/batch._recover_group).
"""

from __future__ import annotations

import sys
import time
import threading
from typing import Callable, Dict, List, Optional, Tuple


class WarmupCompiler:
    """One background thread draining a FIFO of (key, builder) compile
    jobs.  Keys are arbitrary hashables (the executors use executable-
    identity tuples); a key is only ever built once.

    ``debounce_s``: a job only STARTS once it has sat queued this long.
    Executors refine their shape predictions as admission accumulates
    holes (warm_refine's row accumulator) and cancel superseded keys
    via claim() — but a build that already started cannot be cancelled,
    and XLA compiles cost tens of seconds, so racing the first
    prediction into the compiler would build a program the refined
    prediction obsoletes milliseconds later.  Half a second of settle
    time is noise against the compile it saves.

    ``workers``: build threads.  More than one matters at the sweep
    where the run's groups cross a shape boundary TOGETHER (lockstep
    windows: the whole admission batch dribbles below the slab budget
    in the same sweep, so several groups need their tail-height
    executable at once) — XLA compiles release the GIL, so a small
    pool turns that serial compile train into concurrent builds.  The
    default scales to the host but stays small: compile threads
    compete with the dispatch stream for cores."""

    def __init__(self, debounce_s: float = 0.5,
                 workers: Optional[int] = None):
        import os

        self.debounce_s = max(0.0, float(debounce_s))
        if workers is None:
            workers = min(4, max(1, (os.cpu_count() or 4) // 4))
        self._cv = threading.Condition()
        self._queue: List[Tuple[object, Callable[[], None], float]] = []
        self._state: Dict[object, str] = {}  # queued|running|claimed|done
        self._events: Dict[object, threading.Event] = {}
        self._stop = False
        self._threads = [
            threading.Thread(target=self._run, daemon=True,
                             name=f"ccsx-warmup-{i}")
            for i in range(max(1, int(workers)))]
        for t in self._threads:
            t.start()

    def submit(self, key, builder: Callable[[], None],
               urgent: bool = False) -> bool:
        """Enqueue ``builder`` under ``key`` unless the key was ever
        submitted before (or the compiler is closed).  Returns whether
        the job was accepted.  ``urgent`` skips the debounce — for
        sweep-time EXACT shapes (no refinement can supersede them, and
        their dispatch is imminent).

        A CLAIMED (cancelled) key is resubmittable: prediction
        refinement cancels a superseded height, but the same height
        can become wanted again later (the dribble-tail warm after the
        group's prediction walked past it) — a permanent tombstone
        would silently drop exactly that resubmission.  If the claim
        came from a dispatch that compiled inline, the re-build is a
        jit-cache hit costing milliseconds."""
        with self._cv:
            if self._stop or self._state.get(key) in ("queued",
                                                      "running", "done"):
                return False
            self._state[key] = "queued"
            t = time.monotonic() - (self.debounce_s if urgent else 0.0)
            self._queue.append((key, builder, t))
            self._cv.notify()
            return True

    def claim(self, key) -> Optional[threading.Event]:
        """Dispatch-path synchronization for ``key``:

        * queued  -> cancelled; returns None (caller compiles inline —
                     no duplicated work, attribution lands on the
                     dispatch span as without warmup)
        * running -> returns the completion Event (caller should wait:
                     the compile is already happening concurrently)
        * done / claimed / never submitted -> None
        """
        with self._cv:
            st = self._state.get(key)
            if st == "queued":
                self._queue = [e for e in self._queue if e[0] != key]
                self._state[key] = "claimed"
                return None
            if st == "running":
                return self._events[key]
            return None

    def busy(self) -> bool:
        """True while any accepted job is queued or building — the
        serving plane's readiness probe (a cold server still compiling
        its first tenant's executables reports ``ready: false`` so a
        load balancer does not route a job storm into a compile
        storm)."""
        with self._cv:
            return bool(self._queue) or ("running"
                                         in self._state.values())

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until every accepted job has finished (benchmarks use
        this to warm synchronously before timing).  Returns False on
        timeout."""
        with self._cv:
            return self._cv.wait_for(
                lambda: not self._queue and "running" not in
                self._state.values(), timeout=timeout)

    def close(self, timeout: float = 10.0) -> None:
        """Drop queued jobs, let in-flight builds finish, stop the
        threads.  Idempotent; safe from a driver finally block."""
        with self._cv:
            self._stop = True
            self._queue.clear()
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=timeout)

    def _run(self) -> None:
        while True:
            with self._cv:
                while True:
                    if self._stop:
                        return
                    if self._queue:
                        # debounce: give prediction refinement its
                        # cancellation window before committing.  Pick
                        # the EARLIEST-READY job, not the FIFO head: an
                        # urgent (pre-aged) sweep-time job must not sit
                        # behind a still-debouncing prediction, or its
                        # own dispatch claims it back and compiles
                        # inline — the exact stall it exists to avoid.
                        now = time.monotonic()
                        i = min(range(len(self._queue)),
                                key=lambda j: self._queue[j][2])
                        wait = (self._queue[i][2] + self.debounce_s
                                - now)
                        if wait <= 0:
                            break
                        self._cv.wait(wait)
                    else:
                        self._cv.wait()
                key, builder, _ = self._queue.pop(i)
                self._state[key] = "running"
                ev = self._events[key] = threading.Event()
            try:
                builder()
            except Exception as e:  # dispatch path owns the real ladder
                print(f"[ccsx-tpu] warmup compile failed for {key!r} "
                      f"(dispatch will compile inline): {e}",
                      file=sys.stderr)
            finally:
                with self._cv:
                    self._state[key] = "done"
                    ev.set()
                    self._cv.notify_all()
