"""Q20-yield gate + documented-delta regressions (SURVEY §7.2 step 2).

Fast versions of benchmarks/quality.py's gate and sweeps: the compiled
reference is unavailable offline, so accuracy parity is pinned as a
>=Q20 (identity >= 0.99) yield floor over a pass-count spread, plus
regressions for the two documented deltas (max_passes cap, max_window
force-flush) and for the window_growth="grow" parity mode.
"""

import numpy as np
import pytest

from ccsx_tpu.config import CcsConfig
from ccsx_tpu.consensus import windowed as win_mod
from ccsx_tpu.consensus.align_host import HostAligner
from ccsx_tpu.consensus.prepare import oriented_passes
from ccsx_tpu.consensus.windowed import consensus_windowed
from ccsx_tpu.io.zmw import Zmw
from ccsx_tpu.ops import encode as enc
from ccsx_tpu.utils import synth

ERR = dict(sub_rate=0.02, ins_rate=0.05, del_rate=0.05)


def _consensus_identity(z, cfg):
    lens = np.array([len(p) for p in z.passes], np.int32)
    offs = np.zeros(len(lens), np.int32)
    if len(lens) > 1:
        np.cumsum(lens[:-1], out=offs[1:])
    zz = Zmw(movie=z.movie, hole=z.hole,
             seqs=enc.decode(np.concatenate(z.passes)).encode(),
             lens=lens, offs=offs)
    passes = oriented_passes(zz, HostAligner(cfg.align), cfg)
    if passes is None:
        return 0.0
    return synth.identity_either(consensus_windowed(passes, cfg), z.template)


def test_q20_yield_over_pass_distribution(rng):
    """>=Q20 yield over a 5..16-pass spread at ~12% subread error."""
    cfg = CcsConfig(is_bam=False, min_subread_len=500)
    idys = []
    for h, n_passes in enumerate((5, 7, 9, 12, 16)):
        z = synth.make_zmw(rng, 400, n_passes, movie="mv", hole=str(h),
                           **ERR)
        idys.append(_consensus_identity(z, cfg))
    idys = np.array(idys)
    yield_q20 = (idys >= 0.99).mean()
    # floor measured 2026-07-29 (benchmarks/quality.py gate: 1.0 across
    # all five BASELINE configs at 12 holes each); 0.8 leaves room for
    # one unlucky low-pass hole without masking a real regression
    assert yield_q20 >= 0.8, f"Q20 yield {yield_q20} ({idys})"
    assert idys[-3:].min() >= 0.99  # >=9 passes must always clear Q20


def test_max_passes_cap_regression(rng):
    """The max_passes=32 cap on a 40-pass hole costs no measurable
    identity vs all-passes (delta measured 0.0, benchmarks/quality.py)."""
    z = synth.make_zmw(rng, 500, 40, movie="mv", hole="0", **ERR)
    ids = {}
    for cap in (32, 40):
        cfg = CcsConfig(is_bam=False, min_subread_len=500, max_passes=cap,
                        pass_buckets=(4, 8, 16, 32, 64))
        ids[cap] = _consensus_identity(z, cfg)
    assert ids[32] >= 0.995
    assert ids[32] >= ids[40] - 0.005


@pytest.mark.slow  # ~70s: two full windowed consensus runs per mode
def test_window_growth_modes_identical_when_breakpoints_found(rng):
    """Measured invariant: the star-MSA's draft-anchored columns agree so
    the breakpoint scan succeeds and flush vs grow are bit-identical
    (benchmarks/quality.py sweep: 0 no-breakpoint events across
    adversarial noise/repeat cases)."""
    z = synth.make_zmw(rng, 2500, 5, movie="mv", hole="0",
                       sub_rate=0.04, ins_rate=0.08, del_rate=0.08)
    outs = {}
    for mode in ("flush", "grow"):
        cfg = CcsConfig(is_bam=False, min_subread_len=500,
                        window_init=512, window_add=512, max_window=1024,
                        window_growth=mode)
        outs[mode] = _consensus_identity(z, cfg)
    assert outs["flush"] == outs["grow"]


@pytest.mark.slow  # ~130s: unbounded-growth parity mode recompiles at every grown window shape
def test_window_growth_parity_mode_grows_past_cap(rng, monkeypatch):
    """Deterministic coverage of the growth machinery itself: with the
    breakpoint scan forced to fail N times, "grow" must escalate the
    window past max_window (reference main.c:550 semantics) while
    "flush" must force a flush at the cap."""
    # template long enough that growth past the cap happens mid-molecule
    # (at 2500 the fits check final-flushes the tail before the third
    # growth, and the final flush skips the breakpoint scan entirely)
    z = synth.make_zmw(rng, 4000, 5, movie="mv", hole="0", **ERR)
    orig = win_mod.find_breakpoint

    def run(mode, fails):
        state = {"left": fails, "seen": []}

        def spy(rr, nseq, cfg):
            state["seen"].append(rr.tlen)
            if state["left"] > 0:
                state["left"] -= 1
                return None
            return orig(rr, nseq, cfg)

        monkeypatch.setattr(win_mod, "find_breakpoint", spy)
        cfg = CcsConfig(is_bam=False, min_subread_len=500,
                        window_init=512, window_add=512, max_window=1024,
                        window_growth=mode)
        idy = _consensus_identity(z, cfg)
        monkeypatch.setattr(win_mod, "find_breakpoint", orig)
        return idy, state["seen"]

    idy_flush, seen_flush = run("flush", fails=2)
    # flush: windows scanned at ~512 and ~1024, then cap -> forced flush
    # (never a third growth); later windows restart at 512.  The scanned
    # MSA length tracks window_size within alignment noise
    assert max(seen_flush) < 1400, seen_flush
    idy_grow, seen_grow = run("grow", fails=3)
    # grow: three failures escalate 512 -> 1024 -> 1536 -> 2048 > cap,
    # and the 2048 window IS scanned (mid-molecule, not a final flush)
    assert max(seen_grow) > 1800, seen_grow
    # the forced no-breakpoint flush costs a little quality (it flushes
    # at an arbitrary column); both modes must still stay near Q17+
    assert idy_flush >= 0.97 and idy_grow >= 0.97
