"""Differential tests: JAX banded DP vs the NumPy oracle.

With band=128 and short sequences the band covers the full DP matrix, so
scores must match the unbanded oracle exactly.  Path statistics (mat/aln)
can differ between co-optimal paths; we check them on unambiguous cases and
check the strand_match acceptance decision on realistic noisy pairs.
"""

import numpy as np
import pytest

from ccsx_tpu.config import AlignParams
from ccsx_tpu.ops import banded, encode as enc, oracle
from ccsx_tpu.utils import synth

P = AlignParams()
SCORES = dict(match=P.match, mismatch=P.mismatch,
              gap_open=P.gap_open, gap_extend=P.gap_extend)


def _pad(x, n):
    out = np.full(n, banded.PAD, dtype=np.uint8)
    out[: len(x)] = x
    return out


def run_one(q, t, mode, qmax=None, tmax=None, **kw):
    # pad to canonical shapes: distinct shapes trigger fresh jit compiles
    qmax = qmax or max(128, -(-len(q) // 128) * 128)
    tmax = tmax or max(128, -(-len(t) // 128) * 128)
    res = banded.banded_align(
        _pad(q, qmax), np.int32(len(q)), _pad(t, tmax), np.int32(len(t)),
        mode=mode, **kw,
    )
    return {k: int(v) for k, v in res._asdict().items()}


@pytest.mark.parametrize("mode", ["global", "qfree", "local"])
def test_scores_match_oracle_random(mode, rng):
    for trial in range(15):
        Q = int(rng.integers(3, 100))
        T = int(rng.integers(3, 100))
        q = rng.integers(0, 4, Q).astype(np.uint8)
        t = rng.integers(0, 4, T).astype(np.uint8)
        want = oracle.align(q, t, mode=mode, **SCORES)
        got = run_one(q, t, mode)
        assert got["score"] == want.score, (mode, trial, Q, T)


@pytest.mark.parametrize("mode", ["global", "qfree", "local"])
def test_scores_match_oracle_related(mode, rng):
    """Pairs that are actual noisy copies (the realistic regime)."""
    for trial in range(10):
        t = rng.integers(0, 4, int(rng.integers(50, 150))).astype(np.uint8)
        q = synth.mutate(rng, t, 0.03, 0.05, 0.05)
        want = oracle.align(q, t, mode=mode, **SCORES)
        got = run_one(q, t, mode)
        assert got["score"] == want.score, (mode, trial)


def test_padding_invariance(rng):
    q = rng.integers(0, 4, 40).astype(np.uint8)
    t = rng.integers(0, 4, 50).astype(np.uint8)
    base = run_one(q, t, "global")
    padded = run_one(q, t, "global", qmax=96, tmax=130)
    assert base == padded


def test_global_identical_stats():
    q = enc.encode("ACGTACGTACGTACGT")
    got = run_one(q, q, "global")
    assert got["score"] == 32
    assert got["mat"] == 16 and got["aln"] == 16


def test_global_stats_with_gap(rng):
    t = rng.integers(0, 4, 60).astype(np.uint8)
    q = np.delete(t, [20, 21])  # two template-only bases
    want = oracle.align(q, t, mode="global", **SCORES)
    got = run_one(q, t, "global")
    assert got["score"] == want.score
    assert got["mat"] == want.mat
    assert got["aln"] == want.aln


def test_qfree_clip_span(rng):
    t = rng.integers(0, 4, 90).astype(np.uint8)
    junk1 = rng.integers(0, 4, 40).astype(np.uint8)
    junk2 = rng.integers(0, 4, 35).astype(np.uint8)
    q = np.concatenate([junk1, t, junk2])
    want = oracle.align(q, t, mode="qfree", **SCORES)
    got = run_one(q, t, "qfree")
    assert got["score"] == want.score
    assert abs(got["qb"] - want.qb) <= 2
    assert abs(got["qe"] - want.qe) <= 2


def test_local_span(rng):
    core = rng.integers(0, 4, 60).astype(np.uint8)
    q = np.concatenate([rng.integers(0, 4, 25).astype(np.uint8), core])
    t = np.concatenate([core, rng.integers(0, 4, 20).astype(np.uint8)])
    want = oracle.align(q, t, mode="local", **SCORES)
    got = run_one(q, t, "local")
    assert got["score"] == want.score
    assert got["mat"] >= want.mat - 2


def test_strand_match_decision_parity(rng):
    """The accept/reject decision (main.c:280) must agree with the oracle."""
    for trial in range(8):
        z = synth.make_zmw(rng, template_len=200, n_passes=2, first_strand=0)
        fwd, rev = z.passes[0], z.passes[1]
        for q in (fwd, enc.revcomp_codes(rev), rev):
            ok_oracle, _ = oracle.strand_match_oracle(q, z.template, 75, **SCORES)
            got = run_one(q, z.template, "local", qmax=512, tmax=256)
            ok_banded = (
                got["aln"] * 2 > min(len(q), len(z.template))
                and got["mat"] * 100 >= got["aln"] * 75
            )
            assert ok_banded == ok_oracle, trial


def test_batch_vmap_matches_single(rng):
    qs, ts, qlens, tlens = [], [], [], []
    QM, TM = 80, 80
    for _ in range(6):
        Q = int(rng.integers(10, QM))
        T = int(rng.integers(10, TM))
        q = rng.integers(0, 4, Q).astype(np.uint8)
        t = rng.integers(0, 4, T).astype(np.uint8)
        qs.append(_pad(q, QM))
        ts.append(_pad(t, TM))
        qlens.append(Q)
        tlens.append(T)
    f = banded.make_batched("global", P)
    res = f(np.stack(qs), np.array(qlens, np.int32),
            np.stack(ts), np.array(tlens, np.int32))
    for b in range(6):
        single = run_one(qs[b][: qlens[b]], ts[b][: tlens[b]], "global")
        assert int(res.score[b]) == single["score"]


def test_long_band_limited(rng):
    """Long related pair: banded score must equal oracle (band tracks path)."""
    t = rng.integers(0, 4, 600).astype(np.uint8)
    q = synth.mutate(rng, t, 0.02, 0.05, 0.05)
    want = oracle.align(q, t, mode="global", **SCORES)
    got = run_one(q, t, "global")
    assert got["score"] == want.score


def test_qfree_junk_suffix_long_template(rng):
    """Regression: template longer than the band, query = template + junk
    suffix — the slope-1 qfree line must keep column tlen reachable at the
    true end row (was badly wrong with the corner-to-corner line)."""
    t = rng.integers(0, 4, 300).astype(np.uint8)
    q = np.concatenate([t, rng.integers(0, 4, 500).astype(np.uint8)])
    want = oracle.align(q, t, mode="qfree", **SCORES)
    got = run_one(q, t, "qfree", qmax=896, tmax=384)
    assert got["score"] == want.score
    assert abs(got["qe"] - want.qe) <= 2


def test_global_unreachable_band_returns_sentinel():
    """Regression: if the band cannot geometrically reach column tlen the
    result must be the NEG sentinel, not a plausible-looking interior cell."""
    q = np.zeros(10, dtype=np.uint8)
    t = np.tile(np.arange(4, dtype=np.uint8), 150)  # tlen=600 >> qlen*maxshift
    got = run_one(q, t, "global", qmax=128, tmax=640)
    assert got["score"] == banded.NEG
    assert got["aln"] == 0 and got["mat"] == 0


def test_params_band_is_respected(rng):
    """AlignParams.band must be the default band width."""
    t = rng.integers(0, 4, 50).astype(np.uint8)
    q = synth.mutate(rng, t, 0.03, 0.03, 0.03)
    narrow = AlignParams(band=16)
    res = banded.banded_align(
        _pad(q, 128), np.int32(len(q)), _pad(t, 128), np.int32(len(t)),
        mode="global", params=narrow)
    # with band 16 the fill still works on near-diagonal pairs
    want = oracle.align(q, t, mode="global", **SCORES)
    assert int(res.score) == want.score


def test_with_stats_false_same_moves(rng):
    """The slim hot-path carry (with_stats=False) must emit bitwise
    identical moves/offs/score to the full-stats spec."""
    import jax

    from ccsx_tpu.config import AlignParams

    params = AlignParams()
    Q = T = 256
    n = 8
    qs = rng.integers(0, 4, (n, Q)).astype(np.uint8)
    ts = rng.integers(0, 4, (n, T)).astype(np.uint8)
    qlens = rng.integers(Q - 60, Q, n).astype(np.int32)
    tlens = rng.integers(T - 60, T, n).astype(np.int32)
    full = banded.make_batched("global", params, with_moves=True)
    slim = banded.make_batched("global", params, with_moves=True,
                               with_stats=False)
    r1, m1, o1 = jax.block_until_ready(full(qs, qlens, ts, tlens))
    r2, m2, o2 = jax.block_until_ready(slim(qs, qlens, ts, tlens))
    np.testing.assert_array_equal(np.asarray(m1), np.asarray(m2))
    np.testing.assert_array_equal(np.asarray(o1), np.asarray(o2))
    np.testing.assert_array_equal(np.asarray(r1.score), np.asarray(r2.score))
    # stats channels are intentionally absent: reported as zero
    assert int(np.asarray(r2.aln).max()) == 0


def test_line_interp_exact_incl_overflow_range():
    """The band's nominal-line interpolation must be exact past the
    int32 product cliff: the pre-r11 expression `(i-li0)*(lj1-lj0)//D`
    wrapped once row*span crossed 2^31 (every near-square pair past
    ~46341 bases), freezing the band offset mid-template and silently
    truncating ultra-long pair alignments.  _line_interp is pinned
    against Python big-int floor division across the realistic line
    space (slope-sane: |result| fits int32), overflow region included,
    negative rows (before a hinted line start) included."""
    rng = np.random.default_rng(11)
    for _ in range(3000):
        denom = int(rng.integers(1, 300001))
        # slope <= 8: covers corner lines (tlen/qlen) and slope-1 hints
        span = min(int(denom * rng.uniform(0, 8)), 2**21)
        ip = int(rng.integers(-300000, 300001))
        got = int(banded._line_interp(
            np.int32(ip), np.int32(span), np.int32(denom)))
        assert got == (ip * span) // denom, (ip, span, denom)


@pytest.mark.parametrize("L", [100352])
def test_local_full_span_past_int32_cliff(L):
    """A (noise-free) identical pair PAST the 2^31 interpolation cliff
    must align end-to-end: before the r11 fix a 100kb identical pair
    'aligned' exactly floor(2^31/tlen)+band-ish rows (qe 21537) because
    the frozen band offset lost the diagonal.  One jitted call at the
    real bucketed shape; also guards the off-tracker's monotone clip
    path at scale."""
    from ccsx_tpu.consensus.star import pad_to

    t = np.random.default_rng(5).integers(0, 4, L).astype(np.uint8)
    r = banded.banded_align(pad_to(t, L), np.int32(L), pad_to(t, L),
                            np.int32(L), mode="local",
                            params=AlignParams())
    assert int(r.qe) == L and int(r.score) == 2 * L
    assert int(r.mat) == L
