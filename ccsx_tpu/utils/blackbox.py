"""Crash-persistent black-box recorder (ISSUE 18 leg 3).

A hung or SIGKILLed process takes its in-memory trace buffer with it —
the `tpu attempt hung` bench rounds and the serve-fleet chaos kills
left no forensic trail beyond "the heartbeat stopped".  This module is
the flight-data recorder: a small file-backed mmap ring buffer per
process into which the tracer and metrics planes mirror their last-N
events.  Because the ring is a *file-backed* mmap, dirty pages survive
the process — the kernel owns them the moment they are written, so a
SIGKILL (which gives the process no chance to flush anything) still
leaves a readable dump with the in-flight span/job/range named.

Format (version 1):

    [64-byte header] [capacity bytes of ring data]
    header: magic "CCSXBB01" (8) | u32 version | u32 pad
            | u64 capacity @16 | u64 head @24 | zeros
    data:   newline-terminated JSON records written at head % capacity,
            wrapping; head is the TOTAL bytes ever written (never
            wraps), so a reader knows both the write cursor and whether
            the ring has lapped.  After a lap the oldest line is
            usually torn mid-record; the reader drops it.

Writers never read the ring and readers never lock it: a dump is read
from a *dead* process' file (or a live one's, tolerating one torn
record at the seam).  Recording is enabled by the ``CCSX_BLACKBOX``
environment variable naming a DIRECTORY; each process writes
``blackbox.<pid>.bin`` there, which is what the lease graveyard and
the shepherd's reap log link to.  ``ccsx-tpu blackbox <path>`` renders
a dump (cli.py -> blackbox_main).

Deliberately dependency-free and jax-free: the recorder must work in
the gateway/top/stats processes and cost ~a dict-dump per event.
"""

from __future__ import annotations

import json
import mmap
import os
import struct
import sys
import threading
import time
from typing import List, Optional

MAGIC = b"CCSXBB01"
VERSION = 1
HEADER = 64
_CAP_OFF = 16             # u64 capacity (after magic + version + pad)
_HEAD_OFF = 24            # u64 head = TOTAL bytes ever written
DEFAULT_CAPACITY = 1 << 18   # 256 KiB ~ last few thousand events
ENV_DIR = "CCSX_BLACKBOX"
ENV_CAP = "CCSX_BLACKBOX_CAP"


def box_path(d: str, pid: Optional[int] = None) -> str:
    """The per-process ring file name inside a black-box dir —
    deterministic from the pid, which is exactly what a reaper that
    only knows the dead child's pid needs."""
    return os.path.join(d, f"blackbox.{os.getpid() if pid is None else pid}.bin")


class BlackBox:
    """One process' ring writer.  Thread-safe (the tracer's watchdog
    thread and the driver record concurrently).  All record() failures
    are swallowed — the black box must never take the plane down."""

    def __init__(self, path: str, capacity: int = DEFAULT_CAPACITY):
        self.path = path
        self.capacity = max(int(capacity), 4096)
        self._lock = threading.Lock()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        # O_CREAT without O_EXCL: a restarted pid reuses (and laps) its
        # old ring — the head read back from a valid header resumes it
        self._fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            size = HEADER + self.capacity
            st = os.fstat(self._fd)
            fresh = st.st_size != size
            if fresh:
                os.ftruncate(self._fd, size)
            self._mm = mmap.mmap(self._fd, size)
        except (OSError, ValueError):
            os.close(self._fd)
            raise
        if (not fresh and self._mm[:8] == MAGIC
                and struct.unpack_from("<Q", self._mm, _CAP_OFF)[0]
                == self.capacity):
            # a restarted pid resumes (and laps) its old ring
            self.head = struct.unpack_from("<Q", self._mm, _HEAD_OFF)[0]
        else:
            self.head = 0
            self._mm[:HEADER] = b"\0" * HEADER
            self._mm[:8] = MAGIC
            struct.pack_into("<II", self._mm, 8, VERSION, 0)
            struct.pack_into("<Q", self._mm, _CAP_OFF, self.capacity)
        struct.pack_into("<Q", self._mm, _HEAD_OFF, self.head)

    def record(self, rec: dict) -> None:
        try:
            line = (json.dumps(rec, separators=(",", ":"))
                    .encode("utf-8", "replace") + b"\n")
        except (TypeError, ValueError):
            return
        if len(line) > self.capacity:
            return            # one giant record cannot lap itself
        with self._lock:
            try:
                pos = self.head % self.capacity
                end = pos + len(line)
                if end <= self.capacity:
                    self._mm[HEADER + pos:HEADER + end] = line
                else:
                    split = self.capacity - pos
                    self._mm[HEADER + pos:HEADER + self.capacity] = \
                        line[:split]
                    self._mm[HEADER:HEADER + end - self.capacity] = \
                        line[split:]
                self.head += len(line)
                struct.pack_into("<Q", self._mm, _HEAD_OFF, self.head)
            except (OSError, ValueError):
                pass

    def note(self, kind: str, **fields) -> None:
        """A convenience record with the standard envelope (wall ts +
        pid) — the 'inflight' notes the reaper greps for."""
        self.record({"bb": kind, "ts": round(time.time(), 6),
                     "pid": os.getpid(), **fields})

    def close(self) -> None:
        with self._lock:
            try:
                self._mm.close()
            except (OSError, ValueError):
                pass
            try:
                os.close(self._fd)
            except OSError:
                pass


# ---- process-global singleton ----------------------------------------------

_inst: Optional[BlackBox] = None
_inst_pid: Optional[int] = None
_inst_lock = threading.Lock()


def get() -> Optional[BlackBox]:
    """The process' recorder, or None when CCSX_BLACKBOX is unset (the
    plane-off default: zero cost, zero files).  Lazily opened; fork-
    aware (a forked child re-opens under its own pid so two processes
    never share one ring head)."""
    global _inst, _inst_pid
    d = os.environ.get(ENV_DIR)
    if not d:
        return None
    pid = os.getpid()
    if _inst is not None and _inst_pid == pid:
        return _inst
    with _inst_lock:
        if _inst is not None and _inst_pid == pid:
            return _inst
        try:
            cap = int(os.environ.get(ENV_CAP, "") or DEFAULT_CAPACITY)
        except ValueError:
            cap = DEFAULT_CAPACITY
        try:
            _inst = BlackBox(box_path(d), capacity=cap)
            _inst_pid = pid
        except (OSError, ValueError) as e:
            # an unwritable dir disables the recorder, loudly once
            print(f"[ccsx-tpu] blackbox disabled: {e}", file=sys.stderr)
            os.environ.pop(ENV_DIR, None)
            _inst = None
        return _inst


def record(rec: dict) -> None:
    bb = get()
    if bb is not None:
        bb.record(rec)


def note(kind: str, **fields) -> None:
    bb = get()
    if bb is not None:
        bb.note(kind, **fields)


def reset() -> None:
    """Test hook: drop the singleton so a changed CCSX_BLACKBOX takes
    effect within one process."""
    global _inst, _inst_pid
    with _inst_lock:
        if _inst is not None:
            _inst.close()
        _inst = None
        _inst_pid = None


# ---- reader ----------------------------------------------------------------


def read_dump(path: str) -> List[dict]:
    """Reconstruct the event list from a ring file — typically a DEAD
    process' (no locking; a live writer costs at most one torn record
    at the seam).  Oldest first; torn/partial lines are dropped."""
    with open(path, "rb") as f:
        hdr = f.read(HEADER)
        if len(hdr) < HEADER or hdr[:8] != MAGIC:
            raise ValueError(f"{path}: not a ccsx black-box file")
        capacity = struct.unpack_from("<Q", hdr, _CAP_OFF)[0]
        head = struct.unpack_from("<Q", hdr, _HEAD_OFF)[0]
        data = f.read(capacity)
    if capacity <= 0 or len(data) < capacity:
        raise ValueError(f"{path}: truncated black-box file")
    if head <= capacity:
        # never lapped: bytes [0, head) are the whole story.  The
        # boundary matters — at head == capacity exactly, head %
        # capacity is 0 and a wrap-based slice would return nothing
        wrapped = False
        buf = data[:head]
    else:
        wrapped = True
        pos = head % capacity
        buf = data[pos:] + data[:pos]
    lines = buf.split(b"\n")
    if wrapped and lines:
        lines = lines[1:]     # the lap seam tears the oldest record
    out = []
    for ln in lines:
        ln = ln.strip(b"\0").strip()
        if not ln:
            continue
        try:
            out.append(json.loads(ln.decode("utf-8", "replace")))
        except ValueError:
            continue
    return out


def inflight(events: List[dict]) -> List[dict]:
    """The records naming UNFINISHED work at the moment of death:
    'inflight' notes (job/range claims) without a matching 'done' note,
    and span-begin mirrors without their close.  This is what the
    reaper and `ccsx-tpu blackbox` headline."""
    open_notes = {}
    open_spans = {}
    for ev in events:
        kind = ev.get("bb")
        if kind == "inflight":
            open_notes[(ev.get("what"), ev.get("id"))] = ev
        elif kind == "done":
            open_notes.pop((ev.get("what"), ev.get("id")), None)
        elif ev.get("ev") == "begin":
            open_spans[(ev.get("tid"), ev.get("name"))] = ev
        elif ev.get("ev") == "span":
            open_spans.pop((ev.get("tid"), ev.get("name")), None)
    return list(open_notes.values()) + list(open_spans.values())


def render(path: str, out=None, tail: int = 40) -> int:
    """Human rendering of one dump: headline the in-flight work, then
    the last `tail` events."""
    out = out or sys.stdout
    try:
        events = read_dump(path)
    except (OSError, ValueError) as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f"== black box {path}: {len(events)} event(s) recovered ==",
          file=out)
    live = inflight(events)
    if live:
        print(f"-- in-flight at death ({len(live)}) --", file=out)
        for ev in live:
            print("  " + json.dumps(ev, sort_keys=True), file=out)
    else:
        print("-- nothing in flight --", file=out)
    print(f"-- last {min(tail, len(events))} event(s) --", file=out)
    for ev in events[-tail:]:
        print("  " + json.dumps(ev, sort_keys=True), file=out)
    return 0


def blackbox_main(argv) -> int:
    """`ccsx-tpu blackbox <path|dir>...`: render ring dumps.  A
    directory argument expands to every blackbox.*.bin inside it."""
    import argparse
    import glob as globmod

    p = argparse.ArgumentParser(prog="ccsx-tpu blackbox")
    p.add_argument("paths", nargs="+",
                   help="ring file(s) or dir(s) holding blackbox.*.bin")
    p.add_argument("--tail", type=int, default=40,
                   help="events of tail to print per dump [40]")
    args = p.parse_args(argv)
    paths = []
    for a in args.paths:
        if os.path.isdir(a):
            paths.extend(sorted(
                globmod.glob(os.path.join(a, "blackbox.*.bin"))))
        else:
            paths.append(a)
    if not paths:
        print("Error: no black-box files found", file=sys.stderr)
        return 1
    rc = 0
    for path in paths:
        rc = max(rc, render(path, tail=args.tail))
    return rc
