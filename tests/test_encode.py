import numpy as np

from ccsx_tpu.ops import encode as enc


def test_encode_decode_roundtrip():
    s = "ACGTACGT"
    codes = enc.encode(s)
    assert codes.tolist() == [0, 1, 2, 3, 0, 1, 2, 3]
    assert enc.decode(codes) == s


def test_encode_lowercase_and_n():
    codes = enc.encode("acgtNX")
    assert codes.tolist() == [0, 1, 2, 3, 4, 4]


def test_revcomp_ascii():
    assert enc.revcomp_ascii(b"ACGT") == b"ACGT"
    assert enc.revcomp_ascii(b"AACG") == b"CGTT"
    assert enc.revcomp_ascii(b"acgN") == b"Ncgt"


def test_revcomp_codes():
    codes = enc.encode("AACG")
    rc = enc.revcomp_codes(codes)
    assert enc.decode(rc) == "CGTT"
    # involution
    assert np.array_equal(enc.revcomp_codes(rc), codes)
    # N fixed point
    assert enc.revcomp_codes(np.array([4], dtype=np.uint8)).tolist() == [4]


def test_revcomp_matches_ascii_path():
    rng = np.random.default_rng(1)
    codes = rng.integers(0, 4, 100).astype(np.uint8)
    via_ascii = enc.encode(enc.revcomp_ascii(enc.decode(codes).encode()))
    assert np.array_equal(enc.revcomp_codes(codes), via_ascii)
