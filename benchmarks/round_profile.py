"""Per-stage breakdown of one consensus round (VERDICT r3 item 4).

ARCHITECTURE.md's perf notes previously argued the VPU roofline from a
hand-counted ~20 ops/cell; this tool replaces the argument with
measurement, two ways:

  1. staged timing — the round's three stages (banded DP fill,
     traceback projection, column vote) are jitted and timed SEPARATELY
     on device, plus the fused full round.  The deltas attribute round
     time to stages and quantify what XLA's fusion of the full round
     buys.  Timing uses the forced-execution marginal method (see
     _time): the r5 discovery that ``block_until_ready`` does NOT wait
     on the axon runtime invalidated the original block-per-window
     loop — the r5 first-cut artifacts (round_profile_r05*.json,
     "fused_full_round": 27us) measured dispatch bookkeeping, not the
     chip.
  2. a ``jax.profiler`` trace of the warm full round is written to
     --trace-dir for op-level inspection (the artifact the roofline
     claim can be checked against).

Run on the TPU host:  python benchmarks/round_profile.py --json out.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

Z, P, W, TLEN = 16, 8, 1024, 1000   # bench.py's canonical round shapes
ITERS, WINDOWS = 20, 6   # ITERS raised on TPU in main() (signal >> d2h jitter)


def _time(fn, *args):
    """Best-window marginal seconds per fn(*args) call (the shared
    forced-execution method — full rationale in marginal_time.py)."""
    from marginal_time import marginal_time

    return min(marginal_time(fn, *args, iters=ITERS, repeats=WINDOWS))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--device", default="auto",
                    choices=["auto", "tpu", "cpu"])
    ap.add_argument("--trace-dir", default=None,
                    help="also write a jax.profiler trace here")
    ap.add_argument("--json", default=None)
    a = ap.parse_args()

    from ccsx_tpu.utils.device import resolve_device

    resolve_device(a.device)
    import jax
    import jax.numpy as jnp

    # on TPU the stages are ~0.1-1 ms: raise ITERS so the marginal
    # (ITERS-1) x stage time dominates the +-ms jitter of the two
    # checksum fetches.  CPU stages are ~0.1-0.5 s; 20 is plenty.
    global ITERS
    if jax.default_backend() != "cpu":
        ITERS = 200

    from ccsx_tpu.config import AlignParams
    from ccsx_tpu.consensus import star
    from ccsx_tpu.ops import msa, traceback
    import __graft_entry__ as ge

    params = AlignParams()
    aligner = star._aligner(params)
    projector = traceback.make_projector(W, 4)
    voter = msa.make_voter(4)
    qs, qlens, ts, tlens, row_mask = ge._example_batch(
        Z=Z, P=P, W=W, tlen=TLEN)

    # flatten to the shapes the round uses internally (bench.py step)
    ts_b = np.ascontiguousarray(np.broadcast_to(
        np.asarray(ts)[:, None, :], (Z, P, np.asarray(ts).shape[-1])))
    tl_b = np.ascontiguousarray(np.broadcast_to(
        np.asarray(tlens)[:, None], (Z, P)))
    qs_f = np.asarray(qs).reshape(Z * P, -1)
    ql_f = np.asarray(qlens).reshape(Z * P)
    ts_f = ts_b.reshape(Z * P, -1)
    tl_f = tl_b.reshape(Z * P)

    # ---- stage 1: banded DP fill (moves emission included) ----
    fill = jax.jit(lambda q, ql, t, tl: aligner(q, ql, t, tl))
    t_fill = _time(fill, qs_f, ql_f, ts_f, tl_f)
    _, moves, offs = jax.block_until_ready(fill(qs_f, ql_f, ts_f, tl_f))

    # ---- stage 2: traceback projection ----
    moves_r = jnp.asarray(moves).reshape(Z, P, qs_f.shape[-1], -1)
    offs_r = jnp.asarray(offs).reshape(Z, P, -1)
    proj = jax.jit(jax.vmap(jax.vmap(projector, in_axes=(0, 0, 0, 0, None)),
                            in_axes=(0, 0, 0, 0, 0)))
    qs_r = jnp.asarray(qs)
    ql_r = jnp.asarray(qlens)
    tl_r = jnp.asarray(tlens)
    t_proj = _time(proj, moves_r, offs_r, qs_r, ql_r, tl_r)
    aligned, ins_cnt, ins_b, _lead = jax.block_until_ready(
        proj(moves_r, offs_r, qs_r, ql_r, tl_r))

    # ---- stage 3: column vote ----
    vote = jax.jit(jax.vmap(voter))
    rm = jnp.asarray(row_mask)
    t_vote = _time(vote, aligned, ins_cnt, ins_b, rm)

    # ---- fused full round (the bench.py step) ----
    @jax.jit
    def full(qs, qlens, ts, tlens, row_mask):
        Zb, Pb, qmax = qs.shape
        tsb = jnp.broadcast_to(ts[:, None, :], (Zb, Pb, ts.shape[-1]))
        tlb = jnp.broadcast_to(tlens[:, None], (Zb, Pb))
        _, mv, of = aligner(qs.reshape(Zb * Pb, qmax),
                            qlens.reshape(Zb * Pb),
                            tsb.reshape(Zb * Pb, -1),
                            tlb.reshape(Zb * Pb))
        mv = mv.reshape(Zb, Pb, qmax, -1)
        of = of.reshape(Zb, Pb, qmax)
        pj = jax.vmap(jax.vmap(projector, in_axes=(0, 0, 0, 0, None)),
                      in_axes=(0, 0, 0, 0, 0))
        al, ic, ib, _ = pj(mv, of, qs, qlens, tlens)
        return jax.vmap(voter)(al, ic, ib, row_mask)

    qs3 = qs_r.reshape(Z, P, -1)
    ql3 = ql_r.reshape(Z, P)
    t_full = _time(full, qs3, ql3, jnp.asarray(ts), tl_r, rm)

    if a.trace_dir:
        with jax.profiler.trace(a.trace_dir):
            for _ in range(5):
                # np.asarray, not block_until_ready: the fetch is the
                # only op that provably forces execution inside the
                # trace window on the lazy axon runtime
                np.asarray(full(qs3, ql3, jnp.asarray(ts),
                                tl_r, rm)[0])

    cells = Z * P * W * 128
    res = {
        "backend": jax.default_backend(),
        "shapes": {"Z": Z, "P": P, "W": W, "tlen": TLEN, "band": 128},
        "banded_impl": "pallas" if star.use_pallas() else "scan",
        "projector_impl": os.environ.get("CCSX_PROJECTOR", "") or "walk",
        "stage_seconds": {
            "fill": round(t_fill, 6),
            "projection": round(t_proj, 6),
            "vote": round(t_vote, 6),
            "sum_of_stages": round(t_fill + t_proj + t_vote, 6),
            "fused_full_round": round(t_full, 6),
        },
        "stage_share_pct": {
            "fill": round(100 * t_fill / (t_fill + t_proj + t_vote), 1),
            "projection": round(100 * t_proj / (t_fill + t_proj + t_vote), 1),
            "vote": round(100 * t_vote / (t_fill + t_proj + t_vote), 1),
        },
        "fusion_gain_pct": round(
            100 * (1 - t_full / (t_fill + t_proj + t_vote)), 1),
        "fill_cells_per_sec": round(cells / t_fill),
        "round_cells_per_sec": round(cells / t_full),
        "round_zmw_windows_per_sec": round(Z / t_full, 1),
        "trace_dir": a.trace_dir,
    }
    print(json.dumps(res, indent=1))
    if a.json:
        with open(a.json, "w") as f:
            json.dump(res, f, indent=1)


if __name__ == "__main__":
    main()
