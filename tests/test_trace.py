"""Flight recorder (utils/trace.py): span tracing, Chrome export,
compile/execute attribution, the stall watchdog, the `stats`
subcommand, and the bench regression gate.

Fast unit tier — the tier-1 suite has ~100 s of headroom inside its
870 s budget, so the two pipeline-level tests here reuse the same tiny
shapes test_metrics.py compiles and everything else is pure-host.
"""

import importlib.util
import io
import json
import os
import threading
import time

import numpy as np
import pytest

from ccsx_tpu import cli
from ccsx_tpu.utils import faultinject, synth, trace
from ccsx_tpu.utils.metrics import Metrics


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    faultinject.disarm()


def _write_fasta(tmp_path, rng, n_holes=3, tlen=700, n_passes=5):
    zs = [synth.make_zmw(rng, template_len=tlen, n_passes=n_passes,
                         movie="mv", hole=str(h)) for h in range(n_holes)]
    fa = tmp_path / "in.fa"
    fa.write_text(synth.make_fasta(zs))
    return zs, fa


def _read_jsonl(path):
    return [json.loads(line) for line in open(path) if line.strip()]


# ---- tracer unit tier ------------------------------------------------------


def test_span_nesting_and_record_fields(tmp_path):
    p = str(tmp_path / "t.jsonl")
    tr = trace.Tracer(p)
    with tr.span("outer", cat="compute", n=2):
        with tr.span("inner", cat="device" if False else "prep"):
            time.sleep(0.01)
    tr.close()
    recs = _read_jsonl(p)
    assert recs[0]["ev"] == "meta"
    spans = {r["name"]: r for r in recs if r["ev"] == "span"}
    outer, inner = spans["outer"], spans["inner"]
    # inner closes first (JSONL is close-ordered), and nests inside
    # outer's [start, start+dur] interval
    assert recs[1]["name"] == "inner"
    assert inner["mono"] >= outer["mono"]
    assert inner["mono"] + inner["dur"] <= outer["mono"] + outer["dur"] + 1e-6
    assert inner["dur"] >= 0.01
    assert outer["args"] == {"n": 2}
    assert abs(outer["ts"] - time.time()) < 60


def test_thread_safety_every_line_valid(tmp_path):
    p = str(tmp_path / "t.jsonl")
    tr = trace.Tracer(p)

    def work(i):
        for j in range(100):
            with tr.span(f"w{i}", cat="compute", j=j):
                pass

    threads = [threading.Thread(target=work, args=(i,), name=f"wk{i}")
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    tr.close()
    recs = _read_jsonl(p)  # json.loads would raise on a torn line
    spans = [r for r in recs if r["ev"] == "span"]
    assert len(spans) == 800
    for i in range(8):
        mine = [r for r in spans if r["name"] == f"w{i}"]
        assert len(mine) == 100
        assert all(r["tid"] == f"wk{i}" for r in mine)


def test_device_span_attribution_first_call_is_compile(tmp_path):
    m = Metrics()
    tr = trace.Tracer(str(tmp_path / "t.jsonl"), metrics=m)
    for _ in range(3):
        with tr.device_span("refine", group="g:q1:t1:i1",
                            cells=100) as sp:
            assert sp.force("x") == "x"  # identity passthrough
            time.sleep(0.002)
    tr.close()
    st = m.snapshot()["groups"]["g:q1:t1:i1"]
    assert st["compiles"] == 1
    assert st["dispatches"] == 3
    assert st["compile_s"] > 0
    assert st["execute_s"] > 0
    assert st["dp_cells"] == 300
    # steady-state rate excludes the compile call's cells and wall
    raw = m.group_stats["g:q1:t1:i1"]
    assert st["dp_cells_per_sec"] == round(200 / raw["execute_s"])
    recs = _read_jsonl(str(tmp_path / "t.jsonl"))
    compiles = [r for r in recs
                if r["ev"] == "span" and r.get("compile")]
    assert len(compiles) == 1


def test_device_span_recompile_per_shape(tmp_path):
    """The same group key dispatched at a different jit-specializing
    shape (the bucketed batch dim) is a RECOMPILE, not steady-state
    execute — compiles counts per (group, shape)."""
    m = Metrics()
    tr = trace.Tracer(str(tmp_path / "t.jsonl"), metrics=m)
    for shape in ("Z4", "Z8", "Z4"):
        with tr.device_span("round", group="round:P8:q1:t1",
                            shape=shape, cells=10):
            pass
    tr.close()
    st = m.snapshot()["groups"]["round:P8:q1:t1"]
    assert st["compiles"] == 2         # Z4 and Z8 each compiled once
    assert st["dispatches"] == 3
    recs = _read_jsonl(str(tmp_path / "t.jsonl"))
    flags = [r["compile"] for r in recs if r["ev"] == "span"]
    assert flags == [True, True, False]


def test_failed_dispatch_not_attributed(tmp_path):
    """A dispatch that raises (the OOM the recovery ladder bisects and
    re-dispatches) is recorded error=true but NOT booked into the group
    table — its cells would otherwise be double-counted by the retry."""
    m = Metrics()
    tr = trace.Tracer(str(tmp_path / "t.jsonl"), metrics=m)
    with pytest.raises(RuntimeError):
        with tr.device_span("refine", group="g", cells=100):
            raise RuntimeError("RESOURCE_EXHAUSTED: injected")
    with tr.device_span("refine", group="g", cells=50):
        pass
    tr.close()
    st = m.snapshot()["groups"]["g"]
    assert st["dispatches"] == 1 and st["dp_cells"] == 50
    assert st["compiles"] == 1         # the retry is the compile call
    recs = [r for r in _read_jsonl(str(tmp_path / "t.jsonl"))
            if r["ev"] == "span"]
    assert recs[0]["args"]["error"] is True
    assert "compile" not in recs[0]


def test_materialize_span_watched_but_not_attributed(tmp_path, capsys):
    """attribute=False (the finish-phase materialization wait): the
    watchdog sees it — the untraced async-runtime hang surfaces at
    materialization, not dispatch — but it never enters group tables."""
    m = Metrics()
    p = str(tmp_path / "t.jsonl")
    tr = trace.Tracer(p, stall_timeout=0.15, metrics=m)
    with tr.device_span("materialize", group="(8, 1536)",
                        attribute=False):
        pass          # consume the first-of-shape compile grace
    with tr.device_span("materialize", group="(8, 1536)",
                        attribute=False):
        time.sleep(0.5)
    with tr.device_span("refine", group="g", cells=10):
        pass
    tr.close()
    err = capsys.readouterr().err
    assert "STALL WATCHDOG" in err and "(8, 1536)" in err
    assert m.degraded
    assert set(m.group_stats) == {"g"}     # materialize not attributed
    d = trace.summarize([p])
    assert set(d["groups"]) == {"g"}       # ...from the trace either
    # but it IS on the timeline and eligible for the slowest list
    names = {s["group"] for s in d["slowest"]}
    assert "(8, 1536)" in names


def test_bench_vs_prev_traced_discipline_not_compared():
    """Traced e2e numbers (forced per-dispatch execution) must never be
    compared against untraced (async overlap) ones."""
    bench = _load_bench_module()
    prev = {"backend": "cpu", "e2e": [
        {"config": 2, "holes_in": 4, "zmws_per_sec": 2.0}]}
    line = {"backend": "cpu", "e2e": [
        {"config": 2, "holes_in": 4, "zmws_per_sec": 1.0, "traced": True}]}
    bench.compare_with_prev(line, prev, "BENCH_r9.json")
    assert "zmws_per_sec" not in line["vs_prev"]
    assert "regressed" not in line


def test_span_eof_stopiteration_not_an_error(tmp_path):
    """The drivers wrap next(stream) in an ingest span; EOF must not
    leave a spurious error=true span at the end of every clean trace."""
    p = str(tmp_path / "t.jsonl")
    tr = trace.Tracer(p)
    with pytest.raises(StopIteration):
        with tr.span("ingest_hole", cat="ingest"):
            next(iter(()))
    tr.close()
    spans = [r for r in _read_jsonl(p) if r["ev"] == "span"]
    assert len(spans) == 1
    assert "error" not in spans[0].get("args", {})


def test_nested_span_self_time_disjoint(tmp_path):
    """Category sums stay disjoint: an enclosing sweep span carries
    "self" (dur minus nested children) and summarize() uses it."""
    p = str(tmp_path / "t.jsonl")
    tr = trace.Tracer(p)
    with tr.span("refine_sweep", cat="compute"):
        with tr.device_span("refine", group="g"):
            time.sleep(0.05)
    tr.close()
    recs = {r["name"]: r for r in _read_jsonl(p) if r["ev"] == "span"}
    outer, dev = recs["refine_sweep"], recs["refine"]
    assert "self" not in dev           # leaves: self == dur, omitted
    # self, dur, and child dur are each independently rounded to 6
    # decimals in the records, so allow half-ulp slack from all three
    assert outer["self"] <= outer["dur"] - dev["dur"] + 2e-6
    d = trace.summarize([p])
    assert d["stage_seconds"]["device"] >= 0.05
    # compute's stage share excludes the nested device time
    assert d["stage_seconds"]["compute"] < 0.05


def test_chrome_export_is_loadable(tmp_path):
    p = str(tmp_path / "t.jsonl")
    tr = trace.Tracer(p)
    with tr.span("host_work", cat="prep"):
        pass
    with tr.device_span("refine", group="g", cells=10):
        pass
    tr.instant("recover", cat="recover", kind="oom")
    tr.close()
    cp = trace.chrome_path(p)
    assert cp.endswith(".chrome.json")
    with open(cp) as f:
        chrome = json.load(f)
    events = chrome["traceEvents"]
    xs = [e for e in events if e.get("ph") == "X"]
    assert len(xs) == 2
    for e in xs:
        assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
        assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
        assert e["cat"] in trace.CATEGORIES and "tid" in e
    assert any(e.get("ph") == "i" for e in events)
    assert any(e.get("ph") == "M" and e.get("name") == "thread_name"
               for e in events)


def test_watchdog_fires_while_span_open(tmp_path, capsys):
    buf = io.StringIO()
    m = Metrics(stream=buf)
    p = str(tmp_path / "t.jsonl")
    tr = trace.Tracer(p, stall_timeout=0.15, metrics=m)
    with tr.device_span("refine_packed", group="packed:q9:t9:i9",
                        plan={"rows": 8, "holes": 2}):
        pass          # first-of-shape: consumes the compile grace
    with tr.device_span("refine_packed", group="packed:q9:t9:i9",
                        plan={"rows": 8, "holes": 2}):
        time.sleep(1.0)   # steady state: bare --stall-timeout applies
    tr.close()
    err = capsys.readouterr().err
    assert "STALL WATCHDOG" in err
    assert "packed:q9:t9:i9" in err
    assert "File \"" in err            # the thread-stack dump
    assert "\"rows\": 8" in err        # the in-flight slab plan
    assert m.degraded and m.degraded.startswith("stall watchdog")
    stalls = [r for r in _read_jsonl(p) if r["ev"] == "stall"]
    assert len(stalls) == 1            # fires once per stalled span
    # fired WHILE the dispatch was open (within one timeout interval of
    # the deadline, well before the 1.0 s sleep released the span), and
    # the record carries the stacks
    assert 0.15 <= stalls[0]["open_s"] < 1.0
    assert any("sleep" in s for s in stalls[0]["stacks"].values())
    events = [json.loads(line) for line in buf.getvalue().splitlines()]
    assert [e["event"] for e in events if e["event"] == "stall"] == ["stall"]
    assert all("ts" in e for e in events)


def test_watchdog_quiet_on_healthy_spans(tmp_path, capsys):
    m = Metrics()
    tr = trace.Tracer(None, stall_timeout=60.0, metrics=m)
    with tr.device_span("refine", group="g"):
        pass
    tr.close()
    assert "STALL" not in capsys.readouterr().err
    assert m.degraded is None
    # path=None: attribution still counts (watchdog-only mode)
    assert m.group_stats["g"]["dispatches"] == 1


def test_stall_fault_point_spec():
    plan = faultinject.parse_spec("stall@2")
    assert plan == {"stall": [2, False]}


def test_watchdog_compile_grace_first_of_shape(tmp_path, capsys):
    """The first span of a (group, shape) gets COMPILE_GRACE x the
    stall budget: a cold multi-minute XLA compile is not a hang."""
    m = Metrics()
    tr = trace.Tracer(str(tmp_path / "t.jsonl"), stall_timeout=0.15,
                      metrics=m)
    with tr.device_span("round", group="g", shape="Z4"):
        time.sleep(0.5)    # > timeout, < timeout * COMPILE_GRACE
    assert "STALL" not in capsys.readouterr().err
    assert m.degraded is None
    with tr.device_span("round", group="g", shape="Z8"):
        time.sleep(0.5)    # a NEW shape: compile grace again
    assert "STALL" not in capsys.readouterr().err
    with tr.device_span("round", group="g", shape="Z4"):
        time.sleep(0.5)    # steady state: bare timeout, fires
    tr.close()
    assert "STALL WATCHDOG" in capsys.readouterr().err
    assert "compile grace" not in str(m.degraded)
    assert m.degraded and m.degraded.startswith("stall watchdog")


def test_retry_path_materialize_span_stable_group(tmp_path):
    """The recovery/retry path (_run_group_sync) materializes inside a
    watchdog-visible 'materialize' device span — an async-runtime hang
    in a RETRIED dispatch must not be invisible — and the span carries
    the STABLE dispatch-namespace group label plus an output-shape tag
    (compile grace re-arms per fresh shape, not per slab ordinal)."""
    from ccsx_tpu.pipeline import batch as batch_mod

    assert batch_mod._out_shape_tag(np.zeros((4, 2))) == "4x2"
    p = str(tmp_path / "t.jsonl")
    tr = trace.Tracer(p)
    trace.install(tr)
    try:
        results = [None]
        batch_mod._run_group_sync(
            [0], (1, 2, 3, 7), lambda idxs, key: np.zeros((4, 2)),
            lambda idxs, key, out: None, lambda i: None, results,
            None, 0, 3, 0.0, label=lambda k: f"packed:q{k[0]}:t{k[1]}")
    finally:
        trace.uninstall()
        tr.close()
    mats = [r for r in _read_jsonl(p) if r.get("ev") == "span"
            and r["name"] == "materialize"]
    assert len(mats) == 1
    assert mats[0]["args"]["group"] == "packed:q1:t2"   # no slab ordinal
    assert mats[0]["args"]["shape"] == "4x2"
    assert "compile" not in mats[0]    # attribute=False: timeline only


# ---- pipeline integration --------------------------------------------------


@pytest.fixture(scope="module")
def traced_run(tmp_path_factory):
    """ONE traced batched CLI run shared by the integration asserts
    (same shapes as test_metrics.py, so the jit cache is warm)."""
    tmp = tmp_path_factory.mktemp("traced")
    rng = np.random.default_rng(0)
    _, fa = _write_fasta(tmp, rng)
    out, m, t = str(tmp / "o.fa"), str(tmp / "m.jsonl"), str(tmp / "t.jsonl")
    rc = cli.main(["-A", "-m", "1000", "--batch", "on", "--metrics", m,
                   "--trace", t, str(fa), out])
    assert rc == 0
    return {"trace": t, "metrics": m, "out": out}


def test_traced_run_group_table_matches_spans(traced_run):
    """The acceptance identity: per-shape-group compile and execute
    sums from the trace spans equal the group table in the final
    metrics event."""
    recs = _read_jsonl(traced_run["trace"])
    # attribution rule: only spans carrying a "compile" key enter the
    # group table (materialize/failed spans are timeline-only)
    dev = [r for r in recs if r["ev"] == "span" and r["cat"] == "device"
           and "compile" in r]
    assert dev, "no device spans recorded"
    assert any(r["name"] == "materialize" for r in recs
               if r["ev"] == "span")      # finish-phase wait is traced
    sums = {}
    for r in dev:
        st = sums.setdefault(r["args"]["group"],
                             {"compiles": 0, "compile_s": 0.0,
                              "execute_s": 0.0, "dispatches": 0,
                              "dp_cells": 0})
        if r.get("warmup"):
            # AOT warmup span (pipeline/warmup.py): books the shape's
            # compile, never a dispatch — the same rule device_span
            # and stats' summarize() apply
            if r.get("compile"):
                st["compiles"] += 1
                st["compile_s"] += r["dur"]
            continue
        st["dispatches"] += 1
        st["dp_cells"] += r["args"].get("cells", 0)
        if r.get("compile"):
            st["compiles"] += 1
            st["compile_s"] += r["dur"]
        else:
            st["execute_s"] += r["dur"]
    finals = [e for e in _read_jsonl(traced_run["metrics"])
              if e["event"] == "final"]
    assert len(finals) == 1
    groups = finals[0]["groups"]
    assert set(groups) == set(sums)
    for key, st in sums.items():
        g = groups[key]
        assert g["compiles"] == st["compiles"]
        assert g["dispatches"] == st["dispatches"]
        assert g["dp_cells"] == st["dp_cells"]
        assert abs(g["compile_s"] - st["compile_s"]) < 0.01
        assert abs(g["execute_s"] - st["execute_s"]) < 0.01
    # every metrics event (satellite bugfix) carries the wall-clock ts
    assert all("ts" in e for e in _read_jsonl(traced_run["metrics"]))


def test_traced_run_span_taxonomy_and_chrome(traced_run):
    recs = _read_jsonl(traced_run["trace"])
    cats = {r["cat"] for r in recs if r["ev"] == "span"}
    # ingest + prep + compute + device all present in one batched run
    assert {"ingest", "prep", "compute", "device"} <= cats
    chrome = json.load(open(trace.chrome_path(traced_run["trace"])))
    assert any(e.get("cat") == "device" for e in chrome["traceEvents"])


def test_stats_subcommand_summary(traced_run, capsys):
    rc = cli.main(["stats", traced_run["trace"], traced_run["metrics"]])
    assert rc == 0
    out = capsys.readouterr().out
    assert "shape groups:" in out
    assert "packed:" in out                 # the packed refine group
    assert "stage breakdown" in out
    assert "slowest device dispatches:" in out
    assert "occupancy recap:" in out
    assert "degraded: none" in out


def test_stats_subcommand_missing_file(capsys):
    assert cli.main(["stats", "/nonexistent/x.jsonl"]) == 1
    assert "Error: stats:" in capsys.readouterr().err


def test_injected_stall_fires_watchdog_in_pipeline(tmp_path, rng,
                                                   monkeypatch, capsys):
    """The end-to-end acceptance path: an injected stall inside a
    device dispatch trips the watchdog, which dumps thread stacks + the
    in-flight shape group and degrades (not kills) the run.  The first
    dispatch of a shape carries the 10x compile grace (0.2 s -> 2 s
    budget), so the injected sleep must outlast it."""
    monkeypatch.setenv("CCSX_FAULT_STALL_S", "2.6")
    _, fa = _write_fasta(tmp_path, rng)
    out, m = str(tmp_path / "o.fa"), str(tmp_path / "m.jsonl")
    rc = cli.main(["-A", "-m", "1000", "--batch", "on",
                   "--stall-timeout", "0.2", "--inject-faults", "stall@1",
                   "--metrics", m, str(fa), out])
    assert rc == 0                          # degraded, never killed
    err = capsys.readouterr().err
    assert "STALL WATCHDOG" in err
    assert "packed:" in err                 # the in-flight shape group
    assert "File \"" in err                 # thread stacks
    events = _read_jsonl(m)
    assert any(e["event"] == "stall" for e in events)
    fin = events[-1]
    assert fin["event"] == "final"
    assert fin["degraded"].startswith("stall watchdog")
    assert fin["holes_out"] == 3            # the run still completed


def test_unwritable_trace_path_polite_rc1(tmp_path, rng, capsys):
    """An unwritable --trace path refuses with rc 1 (like an unwritable
    output path), not a traceback — and the finally still settles."""
    _, fa = _write_fasta(tmp_path, rng)
    rc = cli.main(["-A", "-m", "1000", "--batch", "on",
                   "--trace", str(tmp_path / "no-such-dir" / "t.jsonl"),
                   str(fa), str(tmp_path / "o.fa")])
    assert rc == 1
    assert "Cannot open trace file" in capsys.readouterr().err
    assert trace.current() is None         # nothing left installed


def test_unforced_group_table_flagged(tmp_path):
    """Without --trace the per-group seconds are unforced bookkeeping:
    metrics events carry groups_forced=false and stats warns loudly."""
    m = Metrics()
    tr = trace.Tracer(None, stall_timeout=0, metrics=m)
    with tr.device_span("refine", group="g", cells=10):
        pass
    tr.close()
    snap = m.snapshot()
    assert snap["groups_forced"] is False
    mp = tmp_path / "m.jsonl"
    mp.write_text(json.dumps({"event": "final", **snap}) + "\n")
    d = trace.summarize([str(mp)])
    assert d["groups_forced"] is False
    assert "UNFORCED" in trace.format_summary(d)
    # a --trace run is forced evidence
    m2 = Metrics()
    tr2 = trace.Tracer(str(tmp_path / "t.jsonl"), metrics=m2)
    with tr2.device_span("refine", group="g", cells=10):
        pass
    tr2.close()
    assert m2.snapshot()["groups_forced"] is True


# ---- bench regression gate (satellite) ------------------------------------


def _load_bench_module():
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "ccsx_bench_gate", os.path.join(root, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bench_find_prev_picks_highest_round(tmp_path):
    bench = _load_bench_module()
    raw = {"backend": "cpu", "dp_cells_per_sec": 100, "e2e": []}
    (tmp_path / "BENCH_r2.json").write_text(json.dumps(raw))
    wrapped = {"n": 10, "parsed": {"backend": "cpu",
                                   "dp_cells_per_sec": 200, "e2e": []}}
    (tmp_path / "BENCH_r10.json").write_text(json.dumps(wrapped))
    (tmp_path / "BENCH_r11.json").write_text("not json")  # skipped
    art, line = bench.find_prev_bench(str(tmp_path))
    assert art == "BENCH_r10.json"          # numeric, not lexicographic
    assert line["dp_cells_per_sec"] == 200  # unwrapped from "parsed"


def test_bench_vs_prev_regression_flag(capsys):
    bench = _load_bench_module()
    prev = {"backend": "cpu", "dp_cells_per_sec": 1000,
            "e2e": [{"config": 2, "holes_in": 4, "zmws_per_sec": 2.0}]}
    line = {"backend": "cpu", "dp_cells_per_sec": 500,
            "e2e": [{"config": 2, "holes_in": 4, "zmws_per_sec": 1.9}]}
    bench.compare_with_prev(line, prev, "BENCH_r9.json")
    assert line["vs_prev"]["dp_cells_per_sec"] == 0.5
    assert line["vs_prev"]["zmws_per_sec"] == 0.95
    assert line["regressed"] == ["dp_cells_per_sec x0.50"]
    assert "REGRESSION" in capsys.readouterr().err
    # within 20%: no flag
    ok = {"backend": "cpu", "dp_cells_per_sec": 900,
          "e2e": [{"config": 2, "holes_in": 4, "zmws_per_sec": 1.9}]}
    bench.compare_with_prev(ok, prev, "BENCH_r9.json")
    assert "regressed" not in ok


def test_bench_vs_prev_backend_mismatch_skipped():
    bench = _load_bench_module()
    prev = {"backend": "tpu", "dp_cells_per_sec": 1e12, "e2e": []}
    line = {"backend": "cpu", "dp_cells_per_sec": 1.0, "e2e": []}
    bench.compare_with_prev(line, prev, "BENCH_r9.json")
    assert "skipped" in line["vs_prev"]
    assert "regressed" not in line
    # hole-count mismatch: that config is not compared
    prev2 = {"backend": "cpu", "dp_cells_per_sec": 100,
             "e2e": [{"config": 1, "holes_in": 16, "zmws_per_sec": 9.0}]}
    line2 = {"backend": "cpu", "dp_cells_per_sec": 100,
             "e2e": [{"config": 1, "holes_in": 4, "zmws_per_sec": 1.0}]}
    bench.compare_with_prev(line2, prev2, "BENCH_r9.json")
    assert "zmws_per_sec" not in line2["vs_prev"]
    assert "regressed" not in line2
