"""Graceful drain on SIGTERM/SIGINT (the preemptible-TPU reality).

Production CCS jobs run on preemptible capacity: the scheduler sends
SIGTERM and the process has seconds to make its work durable.  Without
a handler, Python's default SIGTERM kills mid-hole — safe (journal v2's
torn-tail truncation repairs the output on resume) but wasteful, and
SIGINT raises KeyboardInterrupt through whatever stack frame is live.

``DrainGuard`` turns both signals into a cooperative drain: the first
signal sets a flag the drivers poll at their admission points — they
stop admitting new holes, finish every in-flight group, flush the
writer, settle the journal, and exit ``exitcodes.RC_INTERRUPTED`` (75,
EX_TEMPFAIL: resumable — re-running the same command with the same
--journal continues to a byte-identical output).  A second signal
restores the previous handlers, so a third behaves as if the guard were
never installed (the operator's escape hatch from a wedged drain).

Signal handlers can only be installed from the main thread; anywhere
else (e.g. a driver running under a test harness thread) install()
degrades to a no-op guard whose flag never fires — the historical
behavior, never an error.
"""

from __future__ import annotations

import signal
import sys
import threading

_SIGNALS = (signal.SIGTERM, signal.SIGINT)


class DrainGuard:
    """Install with DrainGuard.install(); poll ``.requested``; restore
    the previous handlers with ``.restore()`` (drivers do so in their
    ``finally`` so nested/successive runs in one process stack
    cleanly)."""

    def __init__(self):
        self.requested = False
        self._signum = None
        self._prev = {}
        self._installed = False

    @classmethod
    def install(cls) -> "DrainGuard":
        g = cls()
        if threading.current_thread() is not threading.main_thread():
            return g   # no-op guard: flag never fires
        try:
            for sig in _SIGNALS:
                g._prev[sig] = signal.signal(sig, g._handle)
            g._installed = True
        except (ValueError, OSError):
            g._prev.clear()
        return g

    def _handle(self, signum, frame) -> None:
        if self.requested:
            # second signal: hand control back to the previous
            # handlers — the third signal then acts on them
            self.restore()
            print("[ccsx-tpu] second signal during drain: restoring "
                  "default handlers (next one is fatal)",
                  file=sys.stderr)
            return
        self.requested = True
        self._signum = signum
        print(f"[ccsx-tpu] {signal.Signals(signum).name}: draining — "
              "admission stopped, finishing in-flight holes, then "
              "flushing writer + journal (resumable rc 75)",
              file=sys.stderr)

    def restore(self) -> None:
        if not self._installed:
            return
        self._installed = False
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, OSError):
                pass


class FlagGuard:
    """A drain-guard surrogate for EMBEDDED drivers (the serving plane,
    pipeline/serve.py): same ``.requested`` / ``.restore()`` surface as
    DrainGuard, but raised by its owner — a job cancel (DELETE), the
    job deadline, or a server-wide drain fanning out — instead of a
    process signal.  Signal handlers belong to exactly one owner per
    process; under ``ccsx-tpu serve`` that owner is the server's main
    thread, and each job drains through one of these."""

    def __init__(self):
        self._ev = threading.Event()
        self.reason: str = ""

    @property
    def requested(self) -> bool:
        return self._ev.is_set()

    def request(self, reason: str = "") -> None:
        if reason and not self.reason:
            self.reason = reason
        self._ev.set()

    def restore(self) -> None:  # no handlers to restore
        pass
