"""Device traceback: move matrix -> star-MSA projection.

Converts the packed move bytes emitted by ``banded_align(mode='global',
with_moves=True)`` into the template-anchored projection used by the
consensus vote (the same representation oracle.project_to_template builds):

  aligned[j]   query code aligned to template column j (0-3), 4 = deletion
  ins_cnt[j]   number of query bases inserted after template column j
  ins_b[j, r]  the last ``max_ins`` inserted bases after column j, in
               forward order, left-justified (PAD=5 elsewhere)
  lead_ins     query bases consumed before template column 0 (counted for
               cursor bookkeeping; not voted)

Two implementations, bit-identical (tests/test_traceback.py).  The cell
walk is the unconditional default on every backend until the TPU A/B
(benchmarks/round_profile.py with CCSX_PROJECTOR=scan) flips it; the
scan is opt-in via ``CCSX_PROJECTOR=scan``:

* ``make_projector_scan`` (opt-in) — a ``lax.scan`` over query ROWS.  The
  key observation: a global affine traceback consumes exactly one query
  row per DIAG/UP move, and the only multi-cell-per-row events are
  horizontal (F) gap runs — whose lengths are a pure function of the
  move bytes and are precomputed VECTORIZED as per-row run-lengths of
  the F-extend bit.  With gap_open < 0, at most one F run precedes each
  row-consuming move (an open that beats an extension implies the source
  cell's H strictly beats its F, so the next choice cannot be LEFT
  again); the scan still resolves twice per row as insurance.  The scan
  carries only three scalars and emits per-row records; the projection
  arrays are built AFTER the scan by vectorized scatters.  vs the cell
  walk this halves the sequential depth (qlen steps instead of
  qlen+tlen) and removes all in-loop scatters.
* ``make_projector_reference`` (default) — the original cell-by-cell
  ``lax.while_loop`` from (qlen, tlen) back to (0, 0); one move byte
  gather + masked scatters per step.  Kept as the executable spec.

This replaces the role of bsalign's MSA materialization
(tidy_msa_bspoa, main.c:572) — our "MSA" is the stack of these
projections.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ccsx_tpu.ops.banded import EBIT_EXT, FBIT_EXT, MOVE_LEFT, MOVE_UP

GAP = 4
PAD = 5

_H, _E, _F = 0, 1, 2


def make_projector(tmax: int, max_ins: int = 4):
    """Build a jitted projector for templates padded to ``tmax`` columns.

    Dispatches between the two bit-identical implementations:
    ``CCSX_PROJECTOR=scan|walk`` forces one; default is the cell walk.
    Measured on XLA:CPU the walk's in-loop scatters are cheap and the
    scan's extra gathers lose (0.31s vs 0.48s at the bench shapes).
    The r5 first-cut TPU A/B (round_profile_r05{,_scanproj}.json,
    2026-07-31) read a projection-stage dead heat but was taken with
    the blocking loop the lazy axon runtime turns into RPC-latency
    readings (bench.py docstring) — it is not evidence either way.
    The corrected profiler (forced-execution marginal timing) decides
    this at its next hardware run; until a measurement favors the scan
    the walk stays the default on every backend."""
    import os

    impl = os.environ.get("CCSX_PROJECTOR", "")
    if impl not in ("", "scan", "walk"):
        raise ValueError(
            f"CCSX_PROJECTOR={impl!r}: expected 'scan' or 'walk'")
    if impl == "scan":
        return make_projector_scan(tmax, max_ins)
    return make_projector_reference(tmax, max_ins)


def make_projector_scan(tmax: int, max_ins: int = 4):
    """The row-scan projector (see module docstring; bit-identical to
    make_projector_reference)."""

    @jax.jit
    def project(moves, offs, q, qlen, tlen):
        qmax = q.shape[0]
        B = moves.shape[1]
        mv = moves.astype(jnp.int32)
        choice = mv & 3
        ebit = (mv & EBIT_EXT) != 0
        fbit = (mv & FBIT_EXT) != 0
        # per-row consecutive F-extend run count ENDING at each lane
        # (including the lane itself): runc[i, l] = l - (last lane <= l
        # with fbit clear), 0 where fbit is clear
        lanes = jnp.arange(B, dtype=jnp.int32)
        clear_pos = jnp.where(fbit, jnp.int32(-1), lanes[None, :])
        last_clear = jax.lax.associative_scan(jnp.maximum, clear_pos,
                                              axis=1)
        runc = jnp.where(fbit, lanes[None, :] - last_clear, 0)

        qlen_i = qlen.astype(jnp.int32)
        tlen_i = tlen.astype(jnp.int32)

        def step(carry, xs):
            j, state, r = carry
            i, ch_row, eb_row, rc_row, off_row = xs
            live = i <= qlen_i

            def lane_of(jj):
                return jnp.clip(jj - off_row, 0, B - 1)

            # resolve a pending horizontal gap run (state H, choice
            # LEFT): consume 1 + runc cells at once.  Applied twice —
            # the second application is a no-op for gap_open < 0.
            def resolve(jj):
                l = lane_of(jj)
                is_left = (state == _H) & (ch_row[l] == MOVE_LEFT) \
                    & (jj > 0)
                return jnp.where(is_left, jj - (1 + rc_row[l]), jj)

            j1 = resolve(resolve(j))
            l1 = lane_of(j1)
            is_up = live & ((j1 == 0) | (state == _E)
                            | (ch_row[l1] == MOVE_UP))
            is_diag = live & ~is_up
            r_emit = jnp.where(state == _E, r + 1, jnp.int32(0))
            state_n = jnp.where(
                is_up,
                jnp.where(eb_row[l1] | (j1 == 0), jnp.int32(_E),
                          jnp.int32(_H)),
                jnp.int32(_H))
            j_n = jnp.where(is_diag, j1 - 1, j1)
            carry_n = (jnp.where(live, j_n, j),
                       jnp.where(live, state_n, state),
                       jnp.where(live, jnp.where(is_up, r_emit, 0), r))
            return carry_n, (is_diag, is_up, j1, r_emit)

        xs = (jnp.arange(1, qmax + 1, dtype=jnp.int32),
              choice, ebit, runc, offs.astype(jnp.int32))
        _, (is_diag, is_up, jcol, r_emit) = jax.lax.scan(
            step, (tlen_i, jnp.int32(_H), jnp.int32(0)), xs,
            reverse=True)

        qv = q.astype(jnp.uint8)
        # aligned: every column < tlen is either diag-written or a
        # deletion (GAP); scatter conflicts are impossible (each diag
        # consumes a distinct column); dead rows write a dump slot
        cols = jnp.arange(tmax, dtype=jnp.int32)
        aligned0 = jnp.where(cols < tlen_i, jnp.uint8(GAP),
                             jnp.uint8(PAD))
        aligned = jnp.concatenate([aligned0, jnp.zeros((1,), jnp.uint8)])
        a_idx = jnp.where(is_diag, jcol - 1, tmax)
        aligned = aligned.at[a_idx].set(qv)[:tmax]

        # insertions: slot j holds bases inserted after template column
        # j-1 (slot 0 = leading); one vertical run per slot, so a row's
        # stored position is min(k, max_ins)-1-r with k the run length
        s_idx = jnp.where(is_up, jcol, tmax + 1)
        ins_cnt_full = jnp.zeros((tmax + 2,), jnp.int32).at[s_idx].add(
            is_up.astype(jnp.int32))
        k_row = ins_cnt_full[s_idx]
        kept = is_up & (r_emit < max_ins)
        pos = jnp.clip(jnp.minimum(k_row, max_ins) - 1 - r_emit,
                       0, max_ins - 1)
        b_slot = jnp.where(kept, s_idx, tmax + 1)
        ins_b_full = jnp.full((tmax + 2, max_ins), PAD, jnp.uint8)
        ins_b_full = ins_b_full.at[b_slot, pos].set(qv)
        return (aligned, ins_cnt_full[1:tmax + 1],
                ins_b_full[1:tmax + 1], ins_cnt_full[0])

    return project


def make_projector_reference(tmax: int, max_ins: int = 4):
    """The original cell-by-cell walk (executable spec for the scan
    projector; one move-byte gather + masked scatters per step)."""

    @jax.jit
    def project(moves, offs, q, qlen, tlen):
        qmax = q.shape[0]
        B = moves.shape[1]
        aligned = jnp.full((tmax,), PAD, jnp.uint8)
        # slot s+1 holds insertions after template column s; slot 0 holds
        # the leading insertions (query bases before template column 0),
        # which cursor bookkeeping must still count (main.c:622-638 walks
        # every MSA cell)
        ins_cnt = jnp.zeros((tmax + 1,), jnp.int32)
        ins_b = jnp.full((tmax + 1, max_ins), PAD, jnp.uint8)

        def cond(st):
            i, j, state, *_ = st
            return (i > 0) | (j > 0)

        def body(st):
            i, j, state, aligned, ins_cnt, ins_b = st
            # move byte of cell (i, j); rows are 1-indexed: row i at moves[i-1]
            row = jnp.clip(i - 1, 0, qmax - 1)
            lane = jnp.clip(j - offs[row], 0, B - 1)
            m = moves[row, lane].astype(jnp.int32)
            choice = m & 3

            def do_diag(st):
                i, j, state, aligned, ins_cnt, ins_b = st
                aligned = aligned.at[j - 1].set(q[i - 1])
                return (i - 1, j - 1, jnp.int32(_H), aligned, ins_cnt, ins_b)

            def do_up(st):
                # consume one query base as an insertion after column j-1
                # (slot j in the shifted ins arrays; j == 0 -> leading slot)
                i, j, state, aligned, ins_cnt, ins_b = st
                slot = j
                cnt = ins_cnt[slot]
                pos = max_ins - 1 - cnt
                ins_b = jax.lax.cond(
                    pos >= 0,
                    lambda b: b.at[slot, jnp.maximum(pos, 0)].set(q[i - 1]),
                    lambda b: b,
                    ins_b,
                )
                ins_cnt = ins_cnt.at[slot].add(1)
                nxt = jnp.where((m & EBIT_EXT) != 0, _E, _H)
                # boundary: column 0 of the DP is a forced vertical run
                nxt = jnp.where(j == 0, _E, nxt).astype(jnp.int32)
                return (i - 1, j, nxt, aligned, ins_cnt, ins_b)

            def do_left(st):
                i, j, state, aligned, ins_cnt, ins_b = st
                aligned = aligned.at[j - 1].set(GAP)
                nxt = jnp.where((m & FBIT_EXT) != 0, _F, _H)
                nxt = jnp.where(i == 0, _F, nxt).astype(jnp.int32)
                return (i, j - 1, nxt, aligned, ins_cnt, ins_b)

            # boundary overrides: off the matrix edges the op is forced
            forced_up = (j == 0) & (i > 0)
            forced_left = (i == 0) & (j > 0)
            op = jnp.where(
                forced_up, 1,
                jnp.where(
                    forced_left, 2,
                    jnp.where(
                        state == _E, 1,
                        jnp.where(
                            state == _F, 2,
                            jnp.where(choice == 0, 0,
                                      jnp.where(choice == MOVE_UP, 1, 2)),
                        ),
                    ),
                ),
            )
            return jax.lax.switch(op, [do_diag, do_up, do_left], st)

        i0 = qlen.astype(jnp.int32)
        j0 = tlen.astype(jnp.int32)
        st = (i0, j0, jnp.int32(_H), aligned, ins_cnt, ins_b)
        _, _, _, aligned, ins_cnt, ins_b = jax.lax.while_loop(cond, body, st)

        # left-justify the right-aligned insertion cells
        used = jnp.minimum(ins_cnt, max_ins)
        shift = (max_ins - used)[:, None]
        cols = jnp.arange(max_ins)[None, :] + shift
        ins_b = jnp.take_along_axis(
            ins_b, jnp.clip(cols, 0, max_ins - 1), axis=1
        )
        ins_b = jnp.where(jnp.arange(max_ins)[None, :] < used[:, None],
                          ins_b, PAD)
        # split the leading slot back out: index j = insertions after
        # template column j; lead_ins = query bases before column 0
        return aligned, ins_cnt[1:], ins_b[1:], ins_cnt[0]

    return project
