"""FASTA/FASTQ parser (plain or gzip), kseq-equivalent semantics.

Replicates the behavior of the reference's kseq.h state machine
(kseq.h:177-218): records start at '>' or '@'; sequence may span multiple
lines; FASTQ quality runs until it reaches sequence length; name is the first
whitespace-delimited token, the rest is the comment.  This is the Python
fallback path; the hot path is the native C++ reader (ccsx_tpu/native).
"""

from __future__ import annotations

import dataclasses
import gzip
import io
import zlib
from typing import Iterator, Optional, Union

from ccsx_tpu.io.corruption import CorruptionError


class FastxError(CorruptionError):
    """Classified FASTA/FASTQ parse failure (io/corruption.py
    taxonomy); subclasses CorruptionError(ValueError), so pre-taxonomy
    ``except ValueError`` handlers still work."""


@dataclasses.dataclass
class FastxRecord:
    name: str
    comment: str
    seq: bytes
    qual: Optional[bytes]  # None for FASTA


def format_record(name: str, seq: bytes,
                  qual: Optional[bytes] = None) -> "tuple[str, int]":
    """(text, nbytes) of ONE output record — FASTA (2-line) without
    ``qual``, FASTQ (4-line) with it.  THE single formatter both output
    writers share (pipeline/run._PyWriter, parallel ShardWriter):
    nbytes is the UTF-8-encoded length, which feeds journal v2's
    torn-tail truncation offsets, so format and accounting must never
    diverge between drivers."""
    if qual is None:
        rec = f">{name}\n{seq.decode()}\n"
    else:
        rec = f"@{name}\n{seq.decode()}\n+\n{qual.decode()}\n"
    return rec, len(rec.encode("utf-8"))


def _open(path_or_file) -> io.BufferedReader:
    if hasattr(path_or_file, "read"):
        f = path_or_file
        if not hasattr(f, "peek"):  # e.g. raw BytesIO: make it peekable
            f = io.BufferedReader(f)
        # transparently un-gzip file objects too
        if f.peek(2)[:2] == b"\x1f\x8b":
            return io.BufferedReader(gzip.GzipFile(fileobj=f))
        return f
    path = str(path_or_file)
    f = open(path, "rb")
    if f.peek(2)[:2] == b"\x1f\x8b":
        return io.BufferedReader(gzip.GzipFile(fileobj=f))
    return f


class _SalvageLines:
    """readline() wrapper that classifies a corrupt/truncated gzip
    stream into the salvage sink (the rest of a broken deflate stream
    is unrecoverable — no block structure to resync on) instead of
    raising mid-parse."""

    def __init__(self, f, sink):
        self._f = f
        self._sink = sink

    def readline(self) -> bytes:
        try:
            return self._f.readline()
        except (OSError, EOFError, zlib.error):
            self._sink.record("gzip_truncated")
            return b""


def read_fastx(path_or_file, salvage=None) -> Iterator[FastxRecord]:
    """Stream records from a FASTA/FASTQ file (gzip transparent).

    ``salvage`` (a corruption.SalvageSink) selects salvage mode: a
    classified corruption — FASTQ quality/sequence length mismatch,
    stream truncation — books an event and the parser RESYNCS to the
    next line starting with '>'/'@' (the same line-anchored resync the
    native reader implements) instead of raising.  Without it, the
    historical fail-fast raise is preserved."""
    f = _open(path_or_file)
    if salvage is not None:
        f = _SalvageLines(f, salvage)
    line = f.readline()
    # skip leading junk until a record marker (kseq skips to '>'/'@')
    while line and line[:1] not in (b">", b"@"):
        line = f.readline()
    while line:
        marker = line[:1]
        header = line[1:].rstrip(b"\r\n")
        parts = header.split(None, 1)
        name = parts[0].decode() if parts else ""
        comment = parts[1].decode() if len(parts) > 1 else ""
        seq_parts = []
        line = f.readline()
        while line and line[:1] not in (b">", b"@", b"+"):
            seq_parts.append(line.strip())
            line = f.readline()
        seq = b"".join(seq_parts)
        qual = None
        # kseq parity: a '+' line starts a quality section after ANY record,
        # even a '>' one (kseq.h:196 checks only for '+'); quality is
        # reported only for FASTQ records.
        if line[:1] == b"+":
            # quality: read until length matches seq (kseq.h:203-211)
            qual_parts = []
            got = 0
            line = f.readline()
            while line and got < len(seq):
                chunk = line.strip()
                qual_parts.append(chunk)
                got += len(chunk)
                line = f.readline()
            qual = b"".join(qual_parts)
            if len(qual) != len(seq):
                if salvage is not None:
                    # shorter = the stream ended under the record
                    # (truncation); longer = a damaged quality section.
                    # Book it, drop the record, resync to the next
                    # '>'/'@' line anchor (fastx.py:61 primitive)
                    salvage.record("fastx_truncated"
                                   if len(qual) < len(seq)
                                   else "fastx_qual_mismatch")
                    while line and line[:1] not in (b">", b"@"):
                        line = f.readline()
                    continue
                raise FastxError(
                    "fastx_qual_mismatch",
                    f"FASTQ record {name}: quality length {len(qual)} != "
                    f"sequence length {len(seq)}"
                )
            if marker != b"@":
                qual = None
        yield FastxRecord(name=name, comment=comment, seq=seq, qual=qual)
