"""Schema-drift bad twin, snapshot side: emits a key no export tuple
documents ('orphan_key')."""


class Metrics:
    holes_in = 0

    def snapshot(self):
        snap = {
            "holes_in": self.holes_in,
            "orphan_key": 1,
        }
        if self.holes_in:
            snap["elapsed_s"] = 0.0
        return snap
