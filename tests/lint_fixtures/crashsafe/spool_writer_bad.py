"""Bad twin, marker-path variant: not a domain module by name, but
the path expression names a spool artifact."""

import os


def publish(spool_dir, jid, body):
    with open(os.path.join(spool_dir, f"job.{jid}.json"), "w") as f:
        f.write(body)
