"""Resilient execution: dispatch deadlines + a backend circuit breaker.

Why (ISSUE 9): the single worst failure this repo has actually suffered
is a silently HUNG device dispatch — ``BENCH_r05.json`` shipped degraded
with "tpu attempt hung" — and until now the stall watchdog only
*observed* it (stack dump + degraded mark, "never killed",
ARCHITECTURE.md).  A production run must *finish correctly* when a chip
wedges or a backend flakes repeatedly.  Two cooperating mechanisms, both
wired into the shared dispatch/recovery path of ``pipeline/batch.py``:

* **Dispatch deadlines** (``--dispatch-deadline``, 0 = off, the
  default): every device dispatch — and every output materialization —
  runs as a bounded-wait call (``bounded_call``).  On expiry the driver
  ABANDONS the wedged call: the worker thread is left parked (daemon;
  it can never be cancelled mid-XLA-call), its eventual result is
  discarded because nothing holds its result slot anymore (the
  generation-tag discipline: each call gets a fresh slot + thread, so a
  late result from an abandoned generation has nowhere to land), and a
  ``DeadlineExpired`` propagates into the existing recovery ladder,
  whose ``classify_failure`` maps it to the ``hang`` class — routed
  straight down the host-replay rung (re-dispatching onto a wedged
  backend would just burn another deadline).  Output bytes are
  unchanged by construction: the host replay is the bit-exact spec.
  Deadlines are compile-grace-aware like the stall watchdog: the first
  bounded call of each (group, phase) gets ``grace`` x the budget (a
  cold XLA compile is not a hang).

* **Backend circuit breaker** (``--breaker-strikes`` /
  ``--breaker-probe-s``): ``strikes`` qualifying failures — hangs,
  device-OOM ladder-bottoms, compile failures; never per-hole ``data``
  errors — within ``window_s`` trip the breaker OPEN: subsequent shape
  groups skip the device entirely and run on the host path (counted as
  ``host_fallbacks`` with reason ``breaker_open``).  With
  ``probe_s > 0`` the breaker goes HALF-OPEN every ``probe_s`` seconds:
  exactly one group is dispatched as a probe; success closes the
  breaker (device traffic resumes), failure re-opens it and re-arms the
  probe timer.  State (closed/open/half-open), trips, probes, and the
  bounded strike log ride ``Metrics`` -> ``/metrics``, ``/healthz``,
  ``ccsx-tpu stats``, and the HTML report.

Neither mechanism can change output bytes — they only choose WHERE a
request computes (device vs the differential-tested host spec) — which
is what makes the chaos harness's byte-identity assertion
(benchmarks/chaos.py) a fair oracle.
"""

from __future__ import annotations

import collections
import os
import sys
import threading
import time
from typing import Optional

from ccsx_tpu.utils import faultinject

# first-of-(group, phase) bounded calls get grace x the deadline — the
# same cold-compile allowance as the stall watchdog's COMPILE_GRACE.
# Env override (CCSX_DEADLINE_GRACE) exists for tests and chaos runs
# that need deterministic small budgets without minute-long waits.
DEFAULT_GRACE = 10.0


def _grace() -> float:
    try:
        return max(float(os.environ.get("CCSX_DEADLINE_GRACE",
                                        DEFAULT_GRACE)), 1.0)
    except ValueError:
        return DEFAULT_GRACE


class DeadlineExpired(RuntimeError):
    """A bounded device call outlived its deadline and was abandoned.

    classify_failure (pipeline/batch.py) maps this to the ``hang``
    failure class: no resplit, no retry — straight to the host-replay
    rung.  The wedged worker thread keeps running detached; its result,
    if it ever arrives, is discarded by slot identity."""

    def __init__(self, label: str, phase: str, budget_s: float):
        super().__init__(
            f"device {phase} for group {label!r} exceeded its "
            f"{budget_s:g}s dispatch deadline; abandoning the wedged "
            "call and replaying on the host path")
        self.label = label
        self.phase = phase
        self.budget_s = budget_s


def bounded_call(fn, timeout_s: float, label: str = "",
                 phase: str = "dispatch"):
    """Run ``fn()`` with a bounded wait; raise DeadlineExpired on
    expiry.  ``timeout_s <= 0`` calls inline (no thread, no overhead —
    the resilience-off fast path).

    One fresh daemon thread per call: dispatch rates are tens per
    second at most (one per shape group per sweep), so thread-spawn
    cost is noise, and per-call slots make abandonment race-free — a
    wedged call's eventual completion writes into a slot nobody reads.
    The thread is daemonic: a call that never returns (true device
    hang) must not block process exit."""
    if timeout_s is None or timeout_s <= 0:
        return fn()
    done = threading.Event()
    slot = {}

    def _run():
        try:
            slot["result"] = fn()
        except BaseException as e:  # delivered to the waiter
            slot["exc"] = e
        finally:
            done.set()

    # inherit() carries the caller's fault scope into the worker: a
    # serve job's device_hang injection must fire inside ITS bounded
    # dispatch, not whichever tenant's thread spawns next
    t = threading.Thread(target=faultinject.inherit(_run), daemon=True,
                         name=f"ccsx-bounded-{phase}")
    t.start()
    if done.wait(timeout_s):
        if "exc" in slot:
            raise slot["exc"]
        return slot.get("result")
    raise DeadlineExpired(label, phase, timeout_s)


class CircuitBreaker:
    """Thread-safe closed/open/half-open breaker over device dispatch.

    Callers: ``admit()`` before dispatching a shape group (False =
    route the group to the host path), ``strike(kind, group)`` on a
    qualifying failure, ``success()`` after any group materializes.
    The driver thread and the pair-gate pump thread both dispatch
    concurrently, so every transition holds the lock.

    ``strikes <= 0`` disables the breaker entirely (always closed).
    ``probe_s <= 0`` means a tripped breaker stays open for the rest of
    the run (every remaining group completes on the host path).
    """

    LOG_MAX = 32

    def __init__(self, strikes: int = 3, window_s: float = 60.0,
                 probe_s: float = 0.0, metrics=None):
        self.strikes = int(strikes)
        self.window_s = max(float(window_s), 0.0)
        self.probe_s = max(float(probe_s), 0.0)
        self.metrics = metrics
        self.state = "closed"
        self._recent: collections.deque = collections.deque()
        self._log: collections.deque = collections.deque(
            maxlen=self.LOG_MAX)
        self._opened_at: Optional[float] = None
        self._probing = False
        self._lock = threading.Lock()

    # ---- state plumbing --------------------------------------------------

    def _set_state(self, state: str) -> None:
        self.state = state
        if self.metrics is not None:
            self.metrics.breaker_state = state

    def _publish_log(self) -> None:
        if self.metrics is not None:
            self.metrics.breaker_strike_log = list(self._log)

    # ---- the breaker contract -------------------------------------------

    def admit(self) -> str:
        """'closed' = dispatch normally, 'probe' = dispatch as THE
        half-open probe (the caller must resolve it with
        probe_succeeded / strike(probe=True) / settle_probe), 'host' =
        route the group to the host path.  The probe verdict is tied to
        the admitted group through this return value, NOT inferred from
        whichever thread finishes next — the driver and the pair-gate
        pump dispatch concurrently, and a pre-trip group materializing
        mid-probe must neither close the breaker on stale evidence nor
        steal the probe's settlement."""
        if self.strikes <= 0:
            return "closed"
        with self._lock:
            if self.state == "closed":
                return "closed"
            if (self.probe_s > 0 and not self._probing
                    and time.monotonic() - self._opened_at
                    >= self.probe_s):
                self._probing = True
                self._set_state("half-open")
                if self.metrics is not None:
                    self.metrics.bump(breaker_probes=1)
                print("[ccsx-tpu] circuit breaker half-open: probing "
                      "the device with one group", file=sys.stderr)
                return "probe"
            return "host"

    def probe_succeeded(self) -> None:
        """THE probe group materialized cleanly: close the breaker
        (device traffic resumes).  Only the probe's own completion
        carries this verdict — ordinary successes never touch state."""
        if self.strikes <= 0:
            return
        with self._lock:
            if self._probing:
                self._probing = False
                self._recent.clear()
                self._set_state("closed")
                print("[ccsx-tpu] circuit breaker closed: probe "
                      "dispatch succeeded, device traffic resumes",
                      file=sys.stderr)

    def settle_probe(self) -> None:
        """THE probe resolved WITHOUT a verdict on backend health —
        e.g. it failed with a per-hole `data` error, which never
        strikes.  The probe token must still be released (or the
        breaker wedges half-open forever: admit() refuses everything
        while a probe is outstanding) — back to open with a fresh
        probe timer."""
        if self.strikes <= 0:
            return
        with self._lock:
            if self._probing:
                self._probing = False
                self._opened_at = time.monotonic()
                self._set_state("open")
                print("[ccsx-tpu] circuit breaker probe inconclusive "
                      "(non-device failure); re-opening, next probe in "
                      f"{self.probe_s:g}s", file=sys.stderr)

    def strike(self, kind: str, group: str, probe: bool = False) -> None:
        """A qualifying failure (hang / compile / OOM ladder-bottom).
        ``strikes`` of them within ``window_s`` trip the breaker; a
        failed probe (``probe=True`` — the caller dispatched under an
        admit() == 'probe' token) re-opens it immediately."""
        if self.strikes <= 0:
            return
        now = time.monotonic()
        with self._lock:
            self._log.append({"ts": round(time.time(), 3),
                              "kind": kind, "group": group})
            self._publish_log()
            if probe and self._probing:
                self._probing = False
                self._opened_at = now
                self._set_state("open")
                print(f"[ccsx-tpu] circuit breaker re-opened: probe "
                      f"failed ({kind} on {group})", file=sys.stderr)
                return
            if self.state != "closed":
                return
            self._recent.append(now)
            while self._recent and now - self._recent[0] > self.window_s:
                self._recent.popleft()
            if len(self._recent) >= self.strikes:
                self._opened_at = now
                self._set_state("open")
                self._recent.clear()
                if self.metrics is not None:
                    self.metrics.bump(breaker_trips=1)
                probe = (f"; re-probing every {self.probe_s:g}s"
                         if self.probe_s > 0 else
                         "; no re-probe configured "
                         "(--breaker-probe-s), device stays off for "
                         "the rest of the run")
                print(f"[ccsx-tpu] CIRCUIT BREAKER OPEN: {self.strikes} "
                      f"device failures within {self.window_s:g}s "
                      f"(last: {kind} on {group}) — remaining work "
                      f"runs on the host path{probe}", file=sys.stderr)


class Resilience:
    """Per-run facade bundling the deadline runner + breaker; shared by
    BatchExecutor and PairExecutor (pipeline/batch.py) so strikes from
    pair fills and refine dispatches count against one breaker."""

    def __init__(self, cfg, metrics=None):
        self.metrics = metrics
        self.deadline_s = max(
            float(getattr(cfg, "dispatch_deadline_s", 0.0) or 0.0), 0.0)
        self.grace = _grace()
        self.breaker = CircuitBreaker(
            strikes=int(getattr(cfg, "breaker_strikes", 3)),
            window_s=float(getattr(cfg, "breaker_window_s", 60.0)),
            probe_s=float(getattr(cfg, "breaker_probe_s", 0.0)),
            metrics=metrics)
        self._grace_seen: set = set()
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self.deadline_s > 0

    def admit(self) -> str:
        """'closed' | 'probe' | 'host' (CircuitBreaker.admit)."""
        return self.breaker.admit()

    def budget(self, label: str, phase: str) -> float:
        """Deadline for one bounded call: the first call of each
        (group, phase) gets the compile grace (the watchdog's rule —
        a cold XLA compile through a tunnel takes minutes and must not
        be classified a hang)."""
        with self._lock:
            key = (label, phase)
            first = key not in self._grace_seen
            self._grace_seen.add(key)
        return self.deadline_s * (self.grace if first else 1.0)

    def call(self, fn, label: str, phase: str):
        """Deadline-bounded call (inline when deadlines are off)."""
        if not self.enabled:
            return fn()
        return bounded_call(fn, self.budget(label, phase), label, phase)

    def note_hang(self, label: str, exc: BaseException,
                  probe: bool = False) -> None:
        """Book one abandoned dispatch: counter, degraded mark (a run
        that lost a device call is not clean even though its output
        is), and a breaker strike."""
        if self.metrics is not None:
            self.metrics.bump(device_hangs=1)
            if not self.metrics.degraded:
                self.metrics.degraded = (
                    f"dispatch deadline expired: {exc}")
        self.breaker.strike("hang", label, probe=probe)
