"""`ccsx-tpu shepherd` (pipeline/supervisor.py): rank supervision for
sharded runs — launch, heartbeat monitoring, restart-with-backoff,
auto-merge.

THE acceptance case pinned here: a rank SIGKILLed mid-run (rank_death
fault = os._exit at a retirement point) is restarted by the shepherd,
resumes from its shard journal, and the auto-merged output is
byte-identical to the unsharded run — the manual "re-run the dead
rank(s)" instruction in merge_shards, closed into a supervised loop.
"""

import os

import numpy as np
import pytest

from ccsx_tpu import cli, exitcodes
from ccsx_tpu.pipeline import supervisor
from ccsx_tpu.utils import faultinject, synth

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------- units ----------

def test_strip_shepherd_flags():
    argv = ["-A", "--max-rank-restarts", "3", "in.fa",
            "--rank-backoff", "0.5", "--rank-stall-timeout=9", "out.fa",
            "--hosts", "2"]
    assert supervisor.strip_shepherd_flags(argv) == [
        "-A", "in.fa", "out.fa", "--hosts", "2"]


def test_default_prelude_pins_cpu(monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert "jax_platforms" in supervisor.default_prelude()
    monkeypatch.setenv("JAX_PLATFORMS", "")
    assert supervisor.default_prelude() == ""


def test_latest_mtime(tmp_path):
    assert supervisor._latest_mtime([str(tmp_path / "nope")]) is None
    a = tmp_path / "a"
    a.write_text("x")
    m = supervisor._latest_mtime([str(a), str(tmp_path / "nope")])
    assert m == a.stat().st_mtime


def test_shepherd_main_validation(tmp_path, capsys):
    out = str(tmp_path / "o.fa")
    # --hosts is required
    assert supervisor.shepherd_main(["in.fa", out]) == exitcodes.RC_FATAL
    assert "--hosts" in capsys.readouterr().err
    # --host-id belongs to the shepherd
    assert supervisor.shepherd_main(
        ["--hosts", "2", "--host-id", "0", "in.fa", out]) == 1
    assert "--host-id" in capsys.readouterr().err
    # stdin/stdout make no sense for a sharded supervised run
    assert supervisor.shepherd_main(["--hosts", "2"]) == 1
    assert "INPUT/OUTPUT" in capsys.readouterr().err
    # rank config errors are refused up front, not N times over
    assert supervisor.shepherd_main(
        ["--hosts", "2", "--batch", "off", "in.fa", out]) == 1
    assert "--batch off" in capsys.readouterr().err
    # the shepherd subcommand is reachable through the main CLI
    assert cli.main(["shepherd", "in.fa", out]) == exitcodes.RC_FATAL


# ---------- THE acceptance case: SIGKILLed rank, restart, merge ----------

@pytest.fixture(scope="module")
def corpus4(tmp_path_factory):
    """4 holes (so rank 1 of 2 owns two holes and rank_death@1 fires
    mid-shard), same 700 bp / 5-pass geometry as the other fault
    suites (shared in-process jit cache for the unsharded reference)."""
    tmp = tmp_path_factory.mktemp("shep")
    rng = np.random.default_rng(0)
    zs = [synth.make_zmw(rng, template_len=700, n_passes=5, movie="mv",
                         hole=str(100 + h)) for h in range(4)]
    fa = tmp / "in.fa"
    fa.write_text(synth.make_fasta(zs))
    ref = tmp / "ref.fa"
    assert cli.main(["-A", "-m", "1000", "--batch", "on",
                     str(fa), str(ref)]) == 0
    return fa, ref


@pytest.mark.slow  # ~29s: static-shepherd restart + shard-journal
# resume e2e (r20 budget audit); the restart loop stays tier-1 via
# test_shepherd_exhausted_restarts_fails_cleanly, the supervisor
# reap-then-byte-identical pin via test_fleet.py::
# test_fleet_run_sigkilled_worker_rebalances, and the slow chaos soak
# keeps this exact shepherd_rank_death arm
def test_shepherd_restarts_sigkilled_rank_and_merges(corpus4, tmp_path,
                                                     capsys):
    fa, ref = corpus4
    out = tmp_path / "shep.fa"
    fwd = ["-A", "-m", "1000", "--hosts", "2", str(fa), str(out)]
    rc = supervisor.shepherd_run(
        str(fa), str(out), 2, fwd,
        max_restarts=2, backoff_s=0.1, poll_s=0.1,
        env=dict(os.environ, CCSX_JOURNAL_FSYNC_S="0"),
        # attempt 0 of rank 1 dies (os._exit 57) after its first
        # retired hole; the restart runs CLEAN (CCSX_FAULTS stripped)
        # and resumes from the shard journal
        first_launch_env={1: {"CCSX_FAULTS": "rank_death@1"}})
    err = capsys.readouterr().err
    assert rc == 0, err
    assert out.read_bytes() == ref.read_bytes()
    assert f"died (rc {faultinject.EXIT_CODE})" in err
    assert "restarting in" in err
    assert "merged 4 records" in err
    # the rank logs survive for postmortems; rank 1 has two attempts
    log1 = (out.parent / "shep.fa.shard1.log").read_text()
    assert "attempt 0" in log1 and "attempt 1" in log1
    # the injected fault actually fired in attempt 0
    assert "rank_death" in log1


@pytest.mark.slow  # ~25s: full-shepherd budget-accounting A/B (r16
# budget audit; r20 moved the sigkilled-restart e2e slow too — the
# tier-1 keepers are named on its mark)
def test_shepherd_drained_rank_is_not_charged_a_restart(corpus4,
                                                        tmp_path,
                                                        capsys):
    """Satellite fix: a rank that exits rc 75 (SIGTERM graceful drain,
    journal durable) is a VOLUNTARY preemption — the shepherd must
    relaunch it immediately without spending the restart budget or
    backoff.  Before the fix a drained rank burned --max-rank-restarts
    like a crash, so a maintenance drain could fail the whole run."""
    fa, ref = corpus4
    out = tmp_path / "drain.fa"
    fwd = ["-A", "-m", "1000", "--hosts", "2", str(fa), str(out)]
    rc = supervisor.shepherd_run(
        str(fa), str(out), 2, fwd,
        # zero restart budget: the old (buggy) accounting would fail
        # the rank on its first drain; voluntary preemption must not
        # touch this budget at all
        max_restarts=0, backoff_s=0.1, poll_s=0.1,
        env=dict(os.environ, CCSX_JOURNAL_FSYNC_S="0"),
        first_launch_env={1: {"CCSX_FAULTS": "sigterm@1"}})
    err = capsys.readouterr().err
    assert rc == 0, err
    assert out.read_bytes() == ref.read_bytes()
    assert "voluntary preemption" in err
    assert "drained (rc 75)" in err
    # no restart budget/backoff was spent on the drain
    assert "restarting in" not in err
    # the relaunch is still attempt 0 (preemption, not a restart) and
    # runs clean: the sigterm fault must not re-fire on the relaunch
    log1 = (out.parent / "drain.fa.shard1.log").read_text()
    assert log1.count("attempt 0") == 2 and "attempt 1" not in log1


def test_shepherd_budget_abort_is_not_restarted(corpus4, tmp_path,
                                                capsys):
    """rc 2 (--max-failed-holes exceeded) is deterministic — the
    journal carries the failure count across resumes, so a restart
    would re-abort: the shepherd must fail the rank immediately
    instead of burning its restart budget."""
    fa, _ = corpus4
    out = tmp_path / "o.fa"
    fwd = ["-A", "-m", "1000", "--hosts", "1",
           "--max-failed-holes", "0", str(fa), str(out)]
    rc = supervisor.shepherd_run(
        str(fa), str(out), 1, fwd,
        max_restarts=3, backoff_s=0.05, poll_s=0.05,
        first_launch_env={0: {"CCSX_FAULTS": "compute@1+"}})
    # the taxonomy survives supervision: a budget abort is rc 2 from
    # the shepherd too, not a generic rc 1
    assert rc == exitcodes.RC_FAILED_HOLES
    err = capsys.readouterr().err
    assert "not restartable" in err
    # exactly one launch: no restart attempts were burned
    log0 = (tmp_path / "o.fa.shard0.log").read_text()
    assert "attempt 0" in log0 and "attempt 1" not in log0


def test_shepherd_exhausted_restarts_fails_cleanly(corpus4, tmp_path,
                                                   capsys):
    """A rank that dies on EVERY launch (fault armed via base env, so
    restarts inherit it... except the shepherd strips CCSX_FAULTS on
    restarts — so here we make the rank die structurally instead: its
    output directory is unwritable) exhausts max_restarts and the
    shepherd fails with rc 1, naming the rank."""
    fa, _ = corpus4
    dead_dir = tmp_path / "ro"
    dead_dir.mkdir()
    out = dead_dir / "sub" / "o.fa"   # parent dir missing: rank rc 1
    fwd = ["-A", "-m", "1000", "--hosts", "1", str(fa), str(out)]
    rc = supervisor.shepherd_run(
        str(fa), str(out), 1, fwd,
        max_restarts=1, backoff_s=0.05, poll_s=0.05)
    assert rc == exitcodes.RC_FATAL
    err = capsys.readouterr().err
    assert "exhausted" in err and "rank 0" in err
