"""Batched device k-mer seeding: the exact twin of ops/seed.seed_diagonal.

Host seeding is per-pair NumPy (a sort-join per template plus an
O(Q log T) vote per pair) serialized on the prep plane's pump thread;
in the long-template regime (ROADMAP item 4: 100kb+ molecules) that
serialization and the host CPU footprint become the per-node ceiling
the future serve plane pays per tenant.  This op moves the whole vote
to the device as ONE fixed-shape dispatch per (qmax, tmax) bucket —
sort, capped join, diagonal histogram, windowed argmax, and the median
line — batched over every pair of a wave.

Bit-exactness is the contract (differentially fuzz-pinned against
seed_diagonal by tests/test_sketch.py, random + adversarial
repeat-heavy/N-laden corpora): the device path reproduces the host's
stable sort order, its first-MAX_HITS_PER_KMER-in-sorted-order cap,
np.argmax's first-max tie break, and int(np.median(...))'s
truncate-toward-zero on the even-count midpoint average.  The padded
tail is inert by construction (PAD >= 4 makes every window touching it
a bad k-mer, and pad template positions sort into the sentinel tail the
join never reaches).

``--seed-device-min-t`` (config.seed_device_min_t) is the crossover:
templates at least that long seed here, shorter ones keep the host
path with its per-template sorted-index cache (the short regime is
latency-bound and cache-friendly; the long regime is bandwidth-bound
and batch-friendly).  0 disables the device path entirely.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

from ccsx_tpu.ops import seed as seed_mod
from ccsx_tpu.ops import sketch as sketch_mod

MIN_VOTES = 3   # seed_diagonal's default, pinned


@functools.lru_cache(maxsize=32)
def seed_step(qmax: int, tmax: int):
    """Jitted batched seeder: (N, qmax+tmax) uint8 codes + (N, 2) int32
    lengths -> (N, 8) int32 rows
    (found, diag, votes, i0, j0, i1, j1, total).
    ``found`` == 0 exactly when seed_diagonal would return None."""
    import jax
    import jax.numpy as jnp

    nb = (qmax + tmax) // sketch_mod.DIAG_BIN + 2
    # median sentinel: larger than any real diagonal of these shapes
    big = jnp.int32(qmax + tmax + 2 * sketch_mod.DIAG_BIN)

    def one(row, lens):
        q = row[:qmax]
        t = row[qmax:]
        qlen, tlen = lens[0], lens[1]
        cnt, left, order, qpos = sketch_mod._hits_dev(q, t, qlen, tlen)
        total = cnt.sum()
        hist, diags, inhit, lo = sketch_mod._diag_hist_dev(
            cnt, left, order, qpos, qlen, tlen, nb)
        paired = hist[:-1] + hist[1:]
        best = jnp.argmax(paired).astype(jnp.int32)
        votes = paired[best]
        # median of the hit diagonals inside the best 2-bin window,
        # truncated toward zero like int(np.median(...))
        binned = (diags - lo) // sketch_mod.DIAG_BIN
        inb = inhit & ((binned == best) | (binned == best + 1))
        m = inb.sum()
        sorted_d = jnp.sort(jnp.where(inb, diags, big).ravel())
        a = sorted_d[jnp.maximum(m - 1, 0) // 2]
        b = sorted_d[m // 2]
        med2 = a + b
        diag = jnp.where(med2 >= 0, med2 // 2, -((-med2) // 2))
        i0 = jnp.maximum(diag, 0)
        j0 = i0 - diag
        i1 = jnp.minimum(qlen, tlen + diag)
        j1 = i1 - diag
        found = (total > 0) & (votes >= MIN_VOTES)
        z = jnp.int32(0)
        out = jnp.stack([jnp.where(found, 1, 0),
                         jnp.where(found, diag, z),
                         jnp.where(found, votes, z),
                         jnp.where(found, i0, z),
                         jnp.where(found, j0, z),
                         jnp.where(found, i1, z),
                         jnp.where(found, j1, z),
                         total])
        return out.astype(jnp.int32)

    return jax.jit(jax.vmap(one))


def hit_from_row(row) -> Optional[seed_mod.SeedHit]:
    """One device output row -> the host-contract SeedHit (or None),
    so the executor consumes either seeding path identically."""
    row = [int(v) for v in row]
    if not row[0]:
        return None
    return seed_mod.SeedHit(
        diag=row[1], votes=row[2],
        line=np.array(row[3:7], dtype=np.int32))
