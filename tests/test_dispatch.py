"""Compile-lean dispatch (r8): canonical slab shapes, the AOT warmup
precompiler, donated wire buffers, and the fused multi-chip packed
dispatch.

The conftest harness forces 8 virtual CPU devices, so every test here
exercises the REAL multi-chip code path (shard_map over the ('slab',)
mesh); the single-device contrasts pin byte-identity through the
``devices`` seam.  The compile-budget test at the bottom is the CI
regression guard for the r7 compile storm: the 64-hole scale config,
traced, must keep every packed group at or under its canonical-ladder
compile budget.
"""

import json
import threading
import time

import jax
import numpy as np
import pytest

from ccsx_tpu import cli
from ccsx_tpu.config import CcsConfig
from ccsx_tpu.pipeline import pack as pack_mod
from ccsx_tpu.pipeline.batch import BatchExecutor, PairExecutor
from ccsx_tpu.pipeline.warmup import WarmupCompiler
from ccsx_tpu.utils import faultinject, synth, trace
from ccsx_tpu.utils.metrics import Metrics

from test_packing import SPECS, _assert_refine_matches_host, _requests


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# ---- WarmupCompiler unit tier ---------------------------------------------


def test_warmup_compiler_runs_each_key_once():
    wc = WarmupCompiler()
    try:
        ran = []
        for _ in range(3):
            wc.submit("k", lambda: ran.append(1))
        assert wc.drain(timeout=10)
        assert ran == [1]
        # resubmitting a finished key is refused too
        assert not wc.submit("k", lambda: ran.append(1))
    finally:
        wc.close()


def test_warmup_compiler_claim_semantics():
    """queued -> cancelled (dispatch compiles inline); running -> wait
    Event; done/unknown -> None."""
    wc = WarmupCompiler()
    try:
        gate = threading.Event()
        started = threading.Event()
        ran = []

        def slow():
            started.set()
            gate.wait(10)

        wc.submit("slow", slow)
        started.wait(10)
        wc.submit("queued", lambda: ran.append(1))
        # 'queued' never started: claim cancels it
        assert wc.claim("queued") is None
        # 'slow' is mid-build: claim returns its completion event
        ev = wc.claim("slow")
        assert ev is not None and not ev.is_set()
        gate.set()
        assert ev.wait(10)
        assert wc.drain(timeout=10)
        assert ran == []            # the cancelled builder never ran
        assert wc.claim("slow") is None       # done
        assert wc.claim("never-submitted") is None
        # a cancelled key is RESUBMITTABLE (prediction refinement
        # cancels a height the dribble-tail warm re-wants later — a
        # permanent tombstone would drop that warm, r08 bug)
        assert wc.submit("queued", lambda: ran.append(2))
        assert wc.drain(timeout=10)
        assert ran == [2]
    finally:
        wc.close()


def test_warmup_urgent_jumps_debouncing_queue():
    """An urgent (sweep-time exact) job must not wait behind a still-
    debouncing prediction at the FIFO head — its dispatch is imminent
    and would claim it back into an inline compile."""
    wc = WarmupCompiler(debounce_s=5.0, workers=1)
    try:
        ran = []
        wc.submit("pred", lambda: ran.append("pred"))
        wc.submit("exact", lambda: ran.append("exact"), urgent=True)
        t0 = time.monotonic()
        while "exact" not in ran and time.monotonic() - t0 < 3:
            time.sleep(0.02)
        assert ran == ["exact"]  # built while the prediction debounces
    finally:
        wc.close()


def test_warmup_compiler_builder_failure_contained(capsys):
    wc = WarmupCompiler()
    try:
        wc.submit("boom", lambda: 1 / 0)
        ok = []
        wc.submit("ok", lambda: ok.append(1))
        assert wc.drain(timeout=10)
        assert ok == [1]
    finally:
        wc.close()
    assert "warmup compile failed" in capsys.readouterr().err


# ---- fused multi-chip packed dispatch -------------------------------------


def test_fused_multichip_byte_identical_to_single_device(rng):
    """The tentpole acceptance pin: the 8-fake-device fused super-batch
    produces byte-identical results to a single-device run of the same
    requests (and both match the host refinement spec)."""
    cfg = CcsConfig(is_bam=False, slab_rows=16)
    sm, reqs = _requests(rng, cfg)
    ex_multi = BatchExecutor(cfg)
    assert ex_multi._slab_mesh is not None      # fused path active
    ex_single = BatchExecutor(cfg, devices=jax.local_devices()[:1])
    assert ex_single._slab_mesh is None
    rm = ex_multi.run(list(reqs))
    rs = ex_single.run(list(reqs))
    for req, a, b in zip(reqs, rm, rs):
        _assert_refine_matches_host(sm, cfg, req, a)
        np.testing.assert_array_equal(a.draft, b.draft)
        np.testing.assert_array_equal(a.rr.cons, b.rr.cons)
        np.testing.assert_array_equal(a.rr.advance, b.rr.advance)
        assert a.rr.tlen == b.rr.tlen and a.rr.bp == b.rr.bp


def test_fused_one_dispatch_one_compile_per_group_per_wave(rng):
    """The dispatch-count contract the r7 flight recorder demanded:
    with D=2 chips and a plan of 2 slabs per shape group, each group
    issues exactly ceil(slabs/D) fused dispatches (vs one per slab per
    chip under round-robin) and compiles exactly once."""
    cfg = CcsConfig(is_bam=False, slab_rows=16)
    _, reqs = _requests(rng, cfg)
    metrics = Metrics()
    tr = trace.Tracer(None, metrics=metrics)   # attribution only
    trace.install(tr)
    try:
        ex = BatchExecutor(cfg, metrics=metrics,
                           devices=jax.local_devices()[:2])
        ex.run(list(reqs))
    finally:
        trace.uninstall()
        tr.close()
    packed = {k: st for k, st in metrics.group_stats.items()
              if k.startswith("packed:")}
    assert packed, "no packed groups attributed"
    # SPECS pack into 2 slabs of one (qmax, tmax, iters) group: D=2
    # chips -> ONE wave -> one dispatch, one executable
    for key, st in packed.items():
        assert st["dispatches"] == 1, (key, st)
        assert st["compiles"] == 1, (key, st)
    assert metrics.fused_waves == len(packed)
    assert metrics.distinct_slab_shapes == len(packed)


def test_fused_oom_bisect_and_host_ladder(rng):
    """OOM recovery on the fused super-batch: a bisected wave re-plans
    its halves at the smaller covering canonical slab and stays
    bitwise; a persistent OOM rides the ladder down to per-hole host
    replay."""
    cfg = CcsConfig(is_bam=False, slab_rows=16)
    sm, reqs = _requests(rng, cfg)
    try:
        faultinject.arm("device_oom@1")
        m1 = Metrics()
        ex = BatchExecutor(cfg, metrics=m1,
                           devices=jax.local_devices()[:2])
        assert ex._slab_mesh is not None
        res = ex.run(list(reqs))
        assert m1.oom_resplits >= 1 and m1.host_fallbacks == 0
        for req, r in zip(reqs, res):
            _assert_refine_matches_host(sm, cfg, req, r)

        faultinject.arm("device_oom@1+")
        m2 = Metrics()
        res = BatchExecutor(cfg, metrics=m2,
                            devices=jax.local_devices()[:2]).run(
            list(reqs))
        assert m2.host_fallbacks >= 1
        for req, r in zip(reqs, res):
            _assert_refine_matches_host(sm, cfg, req, r)
    finally:
        faultinject.disarm()


# ---- AOT warmup through the executor --------------------------------------


def test_warmup_first_dispatch_books_execute(rng, tmp_path):
    """The overlap proof the tracer must show: after warm_refine +
    drain, every real refine_packed dispatch books as steady-state
    execute — the compile was paid by the warmup spans (warmup: true,
    compile: true), off the dispatch path."""
    cfg = CcsConfig(is_bam=False, slab_rows=16)
    _, reqs = _requests(rng, cfg)
    p = str(tmp_path / "t.jsonl")
    metrics = Metrics()
    tr = trace.Tracer(p, metrics=metrics)
    trace.install(tr)
    wc = WarmupCompiler()
    try:
        ex = BatchExecutor(cfg, metrics=metrics, warmup=wc)
        for req in reqs:
            ex.warm_refine(req)
        assert wc.drain(timeout=120)
        ex.run(list(reqs))
    finally:
        wc.close()
        trace.uninstall()
        tr.close()
    recs = [r for r in _read_jsonl(p) if r.get("ev") == "span"]
    warm = [r for r in recs if r.get("warmup")]
    disp = [r for r in recs if r["name"] == "refine_packed"]
    assert warm and disp
    assert all(r["compile"] is False for r in disp), \
        "a warmed shape's first dispatch must book as execute"
    assert any(r["compile"] for r in warm)
    packed = {k: st for k, st in metrics.group_stats.items()
              if k.startswith("packed:")}
    for key, st in packed.items():
        assert st["compiles"] >= 1
        assert st["execute_s"] > 0
    # stats' summarize() applies the same warmup rule: the re-derived
    # table must agree with the live one on compiles and dispatches
    summ = trace.summarize([p])
    for key, st in packed.items():
        assert summ["groups"][key]["compiles"] == st["compiles"]
        assert summ["groups"][key]["dispatches"] == st["dispatches"]


def test_pair_executor_warm_api(rng):
    """PairExecutor.warm precompiles the padded pair-fill executables
    (benchmarks/prep_share.py's warmup path); a warmed run produces
    identical results."""
    from ccsx_tpu.config import AlignParams
    from ccsx_tpu.consensus import prepare as prep_mod

    pairs = []
    for _ in range(8):
        tpl = rng.integers(0, 4, 600).astype(np.uint8)
        q = synth.mutate(rng, tpl, 0.02, 0.02, 0.02)
        pairs.append(prep_mod.PairRequest(q, tpl, 75))
    cold = PairExecutor(AlignParams()).run(pairs)
    pe = PairExecutor(AlignParams())
    pe.warm(pairs)           # no compiler attached: warms inline
    warmed = pe.run(pairs)
    for (ok_a, a), (ok_b, b) in zip(cold, warmed):
        assert ok_a == ok_b and a.score == b.score and a.qb == b.qb


# ---- CLI plumbing ----------------------------------------------------------


@pytest.mark.slow  # ~15s warmup-on/off CLI A/B (r15 budget audit);
# tier-1 keeps the compile-budget guard (test_compile_budget_scale64)
# and the WarmupCompiler unit pins
def test_cli_no_warmup_and_ladder_flags(tmp_path, rng):
    """--no-warmup and --slab-shape-ladder reach the config, and a
    ladder-1 run (every slab full height) stays byte-identical — the
    canonical ladder is a tiling knob, never semantics."""
    args = cli.build_parser().parse_args(
        ["--no-warmup", "--slab-shape-ladder", "1", "in", "out"])
    cfg = cli.config_from_args(args)
    assert cfg.warmup_compile is False
    assert cfg.slab_shape_ladder == 1
    cfg_d = cli.config_from_args(
        cli.build_parser().parse_args(["in", "out"]))
    assert cfg_d.warmup_compile is True
    assert cfg_d.slab_shape_ladder == 2

    zs = [synth.make_zmw(rng, template_len=600, n_passes=5 + h,
                         movie="mv", hole=str(h)) for h in range(3)]
    fa = tmp_path / "in.fa"
    fa.write_text(synth.make_fasta(zs))
    outs = {}
    for tag, extra in (("default", []),
                       ("lean", ["--no-warmup", "--slab-shape-ladder",
                                 "1"])):
        o = tmp_path / f"{tag}.fa"
        assert cli.main(["-A", "-m", "1000", *extra, "--batch", "on",
                         str(fa), str(o)]) == 0
        outs[tag] = o.read_text()
    assert outs["default"] == outs["lean"]


def test_cli_bad_ladder_rejected(capsys):
    args = cli.build_parser().parse_args(
        ["--slab-shape-ladder", "0", "in", "out"])
    with pytest.raises(SystemExit):
        cli.config_from_args(args)
    assert "--slab-shape-ladder" in capsys.readouterr().err


# ---- stats warning ---------------------------------------------------------


def test_stats_compile_storm_warning():
    """`ccsx-tpu stats` renders the loud compiles>1 warning (the r7
    storm guard) and stays quiet on a clean table."""
    def summary(compiles):
        return {"paths": ["t.jsonl"], "n_spans": 1, "groups_forced": True,
                "groups": {"packed:q512:t1024:i2": {
                    "compiles": compiles, "compile_s": 1.0,
                    "execute_s": 2.0, "dispatches": 5,
                    "dp_cells": 10, "dp_cells_per_sec": 5}},
                "stage_seconds": {}, "slowest": [], "occupancy": {},
                "stalls": [], "degraded": None}

    loud = trace.format_summary(summary(4))
    assert "compiles>1 in steady state" in loud
    assert "x4" in loud
    assert "compiles>1" not in trace.format_summary(summary(1))


# ---- bench.py satellite units ---------------------------------------------


def _bench_mod():
    import importlib
    import sys as _sys
    _sys.path.insert(0, "/root/repo")
    import bench
    return importlib.reload(bench)


def test_bench_vs_prev_group_compile_gate():
    """The regression gate flags a packed group whose compile count
    grows past both the prior artifact and the canonical-ladder budget
    of 2 — and stays quiet for in-budget variation."""
    bench = _bench_mod()

    def line_with(compiles):
        return {"backend": "cpu", "dp_cells_per_sec": 100,
                "e2e": [{"config": 1, "backend": "cpu", "holes_in": 4,
                         "zmws_per_sec": 1.0, "traced": False,
                         "groups": {"packed:q512:t1024:i2":
                                    {"compiles": compiles,
                                     "dispatches": 5}}}]}

    cur, prev = line_with(4), line_with(2)
    bench.compare_with_prev(cur, prev, "BENCH_rX.json")
    assert cur["vs_prev"]["group_compiles_max"]["1"] == {"prev": 2,
                                                         "cur": 4}
    assert any("compile storm" in r for r in cur.get("regressed", []))

    ok = line_with(2)
    bench.compare_with_prev(ok, line_with(1), "BENCH_rX.json")
    assert "regressed" not in ok


def test_bench_vs_prev_quality_gate():
    """The quality leg of vs_prev (ROADMAP item 5 tail): a >20% drop in
    gate_biased Q20 yield vs the prior bench line flags `regressed`
    exactly like a perf drop; in-tolerance drift stays quiet; and the
    current line always embeds the newest quality artifact's yields."""
    bench = _bench_mod()
    line = {"backend": "cpu"}
    vp, reg = {}, []
    bench.compare_quality(line, {"quality":
                                 {"gate_biased_q20_yield": 0.30}},
                          vp, reg)
    # the repo's committed artifact (0.14) is a >20% drop from 0.30
    assert line["quality"]["artifact"].startswith("quality_r")
    assert vp["gate_biased_q20_yield"]["prev"] == 0.30
    assert reg and "q20_yield" in reg[0]
    # drift within tolerance: quiet
    vp2, reg2 = {}, []
    cur_y = line["quality"]["gate_biased_q20_yield"]
    bench.compare_quality({"backend": "cpu"},
                          {"quality":
                           {"gate_biased_q20_yield": cur_y * 1.1}},
                          vp2, reg2)
    assert reg2 == []
    # and the full compare_with_prev path carries it end to end
    cur = {"backend": "cpu", "dp_cells_per_sec": 100, "e2e": []}
    prev = {"backend": "cpu", "dp_cells_per_sec": 100, "e2e": [],
            "quality": {"gate_biased_q20_yield": 0.30}}
    bench.compare_with_prev(cur, prev, "BENCH_rX.json")
    assert any("q20_yield" in r for r in cur.get("regressed", []))


def test_bench_vs_prev_dp_kernel_gate(monkeypatch):
    """The dp-kernel leg of vs_prev (the r14 promotion harness): every
    bench line embeds the newest pallas_ab decision record; a winner
    flip is informational, but the winning arm's round throughput
    dropping >20% on the SAME backend trips `regressed`; a backend
    change gates nothing."""
    bench = _bench_mod()
    rec = {"winner": "rotband", "margin": 1.18,
           "metric": "round_zmw_windows_per_sec",
           "round_rates": {"scan": 80000.0, "pallas": 90000.0,
                           "rotband": 100000.0},
           "backend": "tpu", "interpret": False}
    arts = [("pallas_ab_tpu_r07.json", dict(rec))]
    monkeypatch.setattr(bench, "latest_pallas_ab_artifacts",
                        lambda *a, **k: arts)
    # same backend, winner steady, rate up: embeds + stays quiet
    line, vp, reg = {}, {}, []
    prev = {"dp_kernel": {**rec, "artifact": "pallas_ab_tpu_r06.json",
                          "round_rates": {"rotband": 95000.0}}}
    bench.compare_dp_kernel(line, prev, vp, reg)
    assert line["dp_kernel"]["artifact"] == "pallas_ab_tpu_r07.json"
    assert vp["dp_kernel"]["cur_winner"] == "rotband"
    assert "winner_flipped" not in vp["dp_kernel"]
    assert reg == []
    # winning arm >20% slower on the same backend: tripped
    line, vp, reg = {}, {}, []
    prev_fast = {"dp_kernel": {**rec,
                               "round_rates": {"rotband": 130000.0}}}
    bench.compare_dp_kernel(line, prev_fast, vp, reg)
    assert any("dp-kernel" in r for r in reg)
    # winner flip: informational, not a regression by itself
    line, vp, reg = {}, {}, []
    prev_scan = {"dp_kernel": {**rec, "winner": "scan",
                               "round_rates": {"scan": 80000.0}}}
    bench.compare_dp_kernel(line, prev_scan, vp, reg)
    assert vp["dp_kernel"].get("winner_flipped") is True
    assert reg == []
    # different backend (cpu interpret record vs tpu): no rate gate
    line, vp, reg = {}, {}, []
    prev_cpu = {"dp_kernel": {**rec, "backend": "cpu",
                              "round_rates": {"rotband": 9e9}}}
    bench.compare_dp_kernel(line, prev_cpu, vp, reg)
    assert reg == []
    # no prev record anywhere but a second artifact: it is the baseline
    arts.append(("pallas_ab_tpu_r06.json",
                 {**rec, "round_rates": {"rotband": 130000.0}}))
    line, vp, reg = {}, {}, []
    bench.compare_dp_kernel(line, None, vp, reg)
    assert vp["dp_kernel"]["prev_source"] == "pallas_ab_tpu_r06.json"
    assert any("dp-kernel" in r for r in reg)


def test_bench_device_attempt_report(tmp_path):
    """A degraded CPU-fallback artifact must carry the failed device
    attempt's stall diagnostics: the watchdog's last in-flight shape
    group and a pointer to the persisted stderr report."""
    bench = _bench_mod()
    err = ("noise\n"
           "[ccsx-tpu] STALL WATCHDOG: device dispatch 'refine_packed' "
           "group='packed:q512:t1024:i2' open for 130.2s (> 120s stall "
           "budget) — dumping state\n"
           "stacks...\n"
           "[ccsx-tpu] STALL WATCHDOG: device dispatch 'materialize' "
           "group='packed:q1024:t1536:i2' open for 250.0s (> 120s "
           "stall budget) — dumping state\n")
    rp = tmp_path / "stall.txt"
    rep = bench.device_attempt_report(err, report_path=str(rp))
    assert rep["stall_dumps"] == 2
    assert rep["last_inflight_group"] == "packed:q1024:t1536:i2"
    assert rp.read_text().startswith("noise")
    assert rep["stall_report"] and "stall.txt" in rep["stall_report"]
    # no stderr at all (e.g. an instant spawn failure): still a report
    empty = bench.device_attempt_report("")
    assert empty == {"stall_report": None, "last_inflight_group": None,
                     "stall_dumps": 0}


# ---- CI compile-budget guard (the r7 storm, pinned) ------------------------


def test_compile_budget_scale64(tmp_path, rng):
    """The tier-1 regression guard for the r7 compile storm: the
    64-hole scale config (mixed lognormal-ish pass counts, mixed
    lengths), run traced through the full CLI, must keep EVERY packed
    refine group at or under its canonical-ladder compile budget
    (ladder=2, +1 for an oversize pow2 slab — r7 measured 4-5 here),
    and in aggregate must average ~one compile per group."""
    counts = np.clip(np.round(rng.lognormal(np.log(8), 0.45, 64)),
                     5, 20).astype(int)
    tlens = rng.integers(300, 900, 64)
    zs = [synth.make_zmw(rng, int(tlens[h]), int(counts[h]), movie="mv",
                         hole=str(h)) for h in range(64)]
    fa = tmp_path / "in.fa"
    fa.write_text(synth.make_fasta(zs))
    out, m = tmp_path / "o.fa", tmp_path / "m.jsonl"
    t = tmp_path / "t.jsonl"
    assert cli.main(["-A", "-m", "1000", "--batch", "on", "--inflight",
                     "64", "--metrics", str(m), "--trace", str(t),
                     str(fa), str(out)]) == 0
    final = _read_jsonl(m)[-1]
    assert final["event"] == "final"
    packed = {k: st for k, st in final["groups"].items()
              if k.startswith("packed:")}
    assert packed, "scale config produced no packed groups"
    budget = CcsConfig().slab_shape_ladder + 1
    over = {k: st["compiles"] for k, st in packed.items()
            if st["compiles"] > budget}
    assert not over, (
        f"COMPILE STORM: packed groups exceeded their compile budget "
        f"of {budget}: {over} (r7 paid 4-5 per group; canonical slab "
        f"shapes must hold the line)")
    # aggregate bound: one compile per canonical height per group (the
    # warmup thread may precompile a group's dribble-tail height that a
    # short run never dispatches — overlapped, never on the dispatch
    # path); r7's storm averaged 4-5 per group
    total_c = sum(st["compiles"] for st in packed.values())
    ladder = CcsConfig().slab_shape_ladder
    assert total_c <= ladder * len(packed), (
        f"more XLA programs than canonical heights: "
        f"{total_c}/{len(packed)} groups (ladder {ladder})")
    assert final["distinct_slab_shapes"] is not None
    assert final["compile_share"] is not None
    assert final.get("degraded") is None
    assert out.read_text().count(">mv/") >= 60
