"""Pass orientation and template selection (host side).

Re-implements the semantics of the reference's prepare stage
(main.c:116-453): length clustering at 10% tolerance, template-group
selection with the palindrome/adapter border check, and the outward
orientation walk that alternates expected strand, verifies/clips doubtful
passes by alignment against the template, and keeps only passes whose
clipped length stays in the template length group.

This is control-flow-heavy scalar work (SURVEY.md §7.3) — it stays on the
host; only the pairwise alignments inside it run on the device (via
HostAligner / the batched runner).
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence

import numpy as np

from ccsx_tpu.config import CcsConfig
from ccsx_tpu.ops import encode as enc


@dataclasses.dataclass
class LenGroup:
    ids: List[int]
    sum_len: int

    @property
    def size(self) -> int:
        return len(self.ids)


def len_in_group(g: LenGroup, length: int, tolerance_pct: int) -> bool:
    """|len - mean| < tol% of mean, in integer arithmetic (main.c:124-129)."""
    tmp = length * g.size
    diff = abs(tmp - g.sum_len)
    return diff * 100 < tolerance_pct * g.sum_len


def group_in_group(a: LenGroup, b: LenGroup, tolerance_pct: int) -> bool:
    """Means within tolerance (main.c:131-137)."""
    ma = a.sum_len * b.size
    mb = b.sum_len * a.size
    return abs(ma - mb) * 100 < ma * tolerance_pct


def group_lens(lens: Sequence[int], tolerance_pct: int) -> List[LenGroup]:
    """Greedy length clustering + transitive merge + sort by size
    (init_group_lens, main.c:139-212).  Member ids keep insertion order —
    the "median member" picks ids[size//2] of that order, as the reference
    does (main.c:317,364)."""
    n = len(lens)
    groups: List[LenGroup] = [LenGroup([], 0) for _ in range(n)]
    for i in range(n):
        placed = False
        create_at = None
        for j in range(n):
            if groups[j].size == 0:
                # first truly empty slot: create a new group here (the
                # reference scans j<i then creates at the first free j)
                create_at = j
                break
            if groups[j].sum_len == 0:
                # zero-length-members group: unjoinable, skip — matches the
                # reference's `if (!sum_len) continue` (main.c:150)
                continue
            if len_in_group(groups[j], int(lens[i]), tolerance_pct):
                groups[j].ids.append(i)
                groups[j].sum_len += int(lens[i])
                placed = True
                break
        if not placed:
            groups[create_at].ids.append(i)
            groups[create_at].sum_len = int(lens[i])

    # transitive merge (main.c:169-195)
    changed = True
    while changed:
        changed = False
        for j in range(n):
            if groups[j].size == 0:
                continue
            for k in range(j):
                if groups[k].size and group_in_group(groups[k], groups[j],
                                                     tolerance_pct):
                    groups[k].ids.extend(groups[j].ids)
                    groups[k].sum_len += groups[j].sum_len
                    groups[j] = LenGroup([], 0)
                    changed = True
                    break

    out = [g for g in groups if g.size > 0]
    out.sort(key=lambda g: -g.size)  # stable, like the bubble sort (main.c:208)
    return out


@dataclasses.dataclass
class Segment:
    """Oriented, clipped view into a ZMW's concatenated buffer
    (segment_t, main.c:292-297)."""

    offs: int
    length: int
    reverse: bool
    pos: int = 0


@dataclasses.dataclass
class PairRequest:
    """One strand_match pair alignment (main.c:255-290), requested by a
    prep generator.  The per-hole path satisfies these immediately via
    HostAligner.strand_match; the batched pipeline stacks pairs from many
    holes into padded-bucket device dispatches (pipeline/batch.py
    PairExecutor) — prep measured ~95% of wall time at device-round speed
    when dispatched one pair at a time (benchmarks/prep_share.py)."""

    q: np.ndarray
    t: np.ndarray
    pct: int
    # Optional identity token for ``t``: requests carrying the same token
    # share one template array, so the executor's seeding can sort its
    # k-mers once and reuse the index across the walk's many pairings
    # (ops/seed.sorted_kmer_index).  None = no sharing (one-shot pairs,
    # e.g. the border checks).  Purely a performance hint — never
    # affects results.
    t_token: object = None


@dataclasses.dataclass
class PairBatch:
    """A FIRST-ACCEPT group of PairRequests yielded as one step of the
    walk (the fwd+RC strand speculation the prefilter enables,
    cfg.prefilter).

    Contract: the driver answers with a list aligned to ``requests``;
    every entry up to and including the first accepted one is a real
    (ok, MatchResult), later entries MAY be None (unevaluated).  The
    walk reads results in order and stops at the first ok=True, so the
    two legal evaluation strategies cannot diverge:

    * lazily (drive_pairs / the per-hole spec path): evaluate in order,
      stop at the first accept — exactly the sequential walk's cost;
    * speculatively (PairExecutor): evaluate every arm in ONE batched
      wave — the wrong-strand arm is hopeless at speculation lengths
      and dies in the pre-alignment screen (ops/sketch.py) for the
      cost of a screen row, while the walk saves a sequential
      pair-wave round trip per doubtful pass.
    """

    requests: List[PairRequest]


def _template_grp_gen(codes: np.ndarray, lens, offs, groups: List[LenGroup],
                      cfg: CcsConfig):
    """Template-group adjustment rejecting palindrome/adapter artifacts
    (main.c:300-342): a larger-length candidate group is adopted unless the
    reverse-complement of either 1000bp border matches the rest of the read
    at 70% identity.  Yields PairRequests; receives (ok, MatchResult)."""
    template_grp = 0
    if groups[0].size < 2:
        return 0
    bl = cfg.border_len
    for cg in range(1, len(groups)):
        g = groups[cg]
        if g.size < 2 or g.size * 5 < 4 * groups[0].size:
            continue
        ci = g.ids[g.size // 2]
        clen = int(lens[ci])
        cur = groups[template_grp]
        cur_med = int(lens[cur.ids[cur.size // 2]])
        if clen <= cur_med or clen <= cfg.border_min_template:
            continue
        start = int(offs[ci])
        read = codes[start:start + clen]
        head_rc = enc.revcomp_codes(read[:bl])
        ok, _ = yield PairRequest(head_rc, read[bl:],
                                  cfg.border_identity_pct)
        if ok:
            continue  # palindromic head: artifact, keep current template
        tail_rc = enc.revcomp_codes(read[clen - bl:])
        ok, _ = yield PairRequest(tail_rc, read[:clen - bl],
                                  cfg.border_identity_pct)
        if ok:
            continue
        template_grp = cg
    return template_grp


def ccs_prepare_gen(codes: np.ndarray, lens, offs, cfg: CcsConfig):
    """The outward orientation walk (ccs_prepare, main.c:344-453), in
    generator form: yields PairRequests, receives (ok, MatchResult),
    returns the segment list via StopIteration.value.

    Starting from the template pass, walk outward in both directions,
    alternating the expected strand each step.  In-group passes are trusted
    by parity until a mismatch event; out-of-group or doubtful passes are
    aligned against the template (fwd then RC) at 75% identity, clipped to
    the aligned query span, and kept only if the clipped length is still in
    the template group.  Returns segments with the template first.
    """
    tol = cfg.group_tolerance_pct
    groups = group_lens(lens, tol)
    map_group = {}
    for gi, g in enumerate(groups):
        for i in g.ids:
            map_group[i] = gi

    template_grp = yield from _template_grp_gen(codes, lens, offs, groups,
                                                cfg)
    tg = groups[template_grp]
    template_i = tg.ids[tg.size // 2]
    template_offs = int(offs[template_i])
    template_len = int(lens[template_i])
    tseq = codes[template_offs:template_offs + template_len]
    t2seq = enc.revcomp_codes(tseq)
    # per-template seeding tokens: every doubtful pass in the walk below
    # aligns against tseq (then t2seq), so the executor can k-mer-sort
    # each template once for the whole hole (ops/seed.py cache)
    tok_f, tok_r = object(), object()
    # fwd+RC speculation floor: only where the pre-alignment screen's
    # noise gate has decisive margin over wrong-strand noise
    # (ops/sketch.SPECULATE_MIN_QT) is a speculated wrong arm
    # guaranteed-cheap; below it, speculation trades a sequential wave
    # for a possible full extra DP
    from ccsx_tpu.ops import sketch as sketch_mod

    spec_min = (sketch_mod.SPECULATE_MIN_QT
                if getattr(cfg, "prefilter", True) else None)

    segments = [Segment(template_offs, template_len, False)]

    def walk(indices):
        reverse = False
        strand_adjust = False
        for k in indices:
            reverse = not reverse
            seg = Segment(int(offs[k]), int(lens[k]), reverse)
            if map_group[k] != template_grp:
                strand_adjust = True
                if seg.length < template_len:
                    continue
            elif not strand_adjust:
                segments.append(seg)
                continue
            qseq = codes[seg.offs:seg.offs + seg.length]
            fwd = PairRequest(qseq, tseq, cfg.strand_identity_pct,
                              t_token=tok_f)
            rcq = PairRequest(qseq, t2seq, cfg.strand_identity_pct,
                              t_token=tok_r)
            ok_r, rs_r = False, None
            if (spec_min is not None
                    and map_group[k] == template_grp
                    and min(seg.length, template_len) >= spec_min):
                # IN-GROUP passes only: a single-strand pass can accept
                # on exactly one arm, so the loser is hopeless and the
                # screen eats it; an out-of-group read-through carries
                # both strands and would accept BOTH arms — speculation
                # there burns a full extra DP the lazy order never pays.
                # One first-accept batch instead of two sequential waves
                res = yield PairBatch([fwd, rcq])
                ok_f, rs = res[0]
                if not ok_f:
                    ok_r, rs_r = res[1]
            else:
                ok_f, rs = yield fwd
                if not ok_f:
                    ok_r, rs_r = yield rcq
            # ONE epilogue for both evaluation paths (result precedence
            # fwd-then-RC is fixed by the PairBatch contract, and the
            # accept/clip/strand_adjust logic exists exactly once — so
            # output bytes cannot depend on which branch ran; pinned by
            # tests/test_sketch.py)
            if ok_f:
                reverse = False
            elif ok_r:
                reverse, rs = True, rs_r
            else:
                strand_adjust = True
                continue
            clipped = Segment(seg.offs + rs.qb, rs.qe - rs.qb, reverse)
            if len_in_group(groups[template_grp], clipped.length, tol):
                segments.append(clipped)
            strand_adjust = map_group[k] != template_grp

    yield from walk(range(template_i - 1, -1, -1))
    yield from walk(range(template_i + 1, len(lens)))
    return segments


def drive_pairs(gen, aligner):
    """Run a PairRequest generator to completion with immediate
    (per-pair) strand_match dispatches; returns its result.

    PairBatches are evaluated LAZILY (in order, stopping at the first
    accept) — the sequential walk's exact cost, so the per-hole spec
    path never pays for speculation it cannot amortize."""
    from ccsx_tpu.utils import trace

    def one(req):
        with trace.span("pair_host", cat="prep",
                        q=len(req.q), t=len(req.t)):
            return aligner.strand_match(req.q, req.t, req.pct)

    try:
        req = next(gen)
        while True:
            if isinstance(req, PairBatch):
                res: List = []
                accepted = False
                for sub in req.requests:
                    if accepted:
                        res.append(None)   # first-accept: skip the rest
                    else:
                        r = one(sub)
                        res.append(r)
                        accepted = bool(r[0])
                req = gen.send(res)
            else:
                req = gen.send(one(req))
    except StopIteration as e:
        return e.value


def get_template_grp(codes: np.ndarray, lens, offs, groups: List[LenGroup],
                     aligner, cfg: CcsConfig) -> int:
    """Synchronous wrapper of _template_grp_gen (kept for tests/tools)."""
    return drive_pairs(
        _template_grp_gen(codes, lens, offs, groups, cfg), aligner)


def ccs_prepare(codes: np.ndarray, lens, offs, aligner,
                cfg: CcsConfig) -> List[Segment]:
    """Synchronous ccs_prepare: drives ccs_prepare_gen with immediate
    per-pair dispatches (the per-hole path; batched path uses the
    generator directly)."""
    return drive_pairs(ccs_prepare_gen(codes, lens, offs, cfg), aligner)


def passes_from_segments(codes: np.ndarray, segments: List[Segment],
                         zmw, cfg) -> List[np.ndarray]:
    """Segment dump (-v level 1, main.c:477-479,533-535) + oriented pass
    slicing — the tail of prep shared by the sync (oriented_passes) and
    batched (hole.full_gen_for_zmw) paths, factored so they can't drift."""
    if cfg.verbose >= 1:
        import sys

        for s in segments:
            print(f"[ccsx-tpu] {zmw.movie}/{zmw.hole} segment "
                  f"offs={s.offs} len={s.length} reverse={int(s.reverse)}",
                  file=sys.stderr)
    return [oriented_pass(codes, s) for s in segments]


def oriented_passes(zmw, aligner, cfg):
    """Prep shared by every consensus path: encode, orient/clip, slice.

    Returns the oriented pass code arrays (template pass first), or None
    when the hole has <3 passes (main.c:460,515).
    """
    if zmw.n_passes < 3:
        return None
    codes = enc.encode(zmw.seqs)
    segments = ccs_prepare(codes, zmw.lens, zmw.offs, aligner, cfg)
    return passes_from_segments(codes, segments, zmw, cfg)


def oriented_pass(codes: np.ndarray, seg: Segment) -> np.ndarray:
    """Extract a segment's bases, reverse-complemented when needed
    (the in-place RC at main.c:471-480, done functionally here)."""
    s = codes[seg.offs:seg.offs + seg.length]
    return enc.revcomp_codes(s) if seg.reverse else s
