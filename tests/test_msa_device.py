"""Device MSA primitives (ops/msa.emit_insertions_jax, make_materializer)
vs their host NumPy specs, on randomized inputs — the unit-level pins
behind the fused-refinement bit-parity (tests/test_refine_fused.py
exercises them only through whole windows)."""

import numpy as np

from ccsx_tpu.ops import banded, msa


def test_emit_insertions_device_matches_host_random(rng):
    R = 4
    for case in range(25):
        T = int(rng.integers(1, 200))
        ncov = rng.integers(0, 65, T).astype(np.int32)
        ins_votes = (rng.integers(0, 130, (T, R)) % (ncov[:, None] + 1)
                     ).astype(np.int32)
        ins_base = rng.integers(0, 4, (T, R)).astype(np.uint8)
        for spec in (False, True):
            want = msa.emit_insertions(ins_base, ins_votes, ncov, spec)
            got = np.asarray(
                msa.emit_insertions_jax(ins_base, ins_votes, ncov, spec))
            np.testing.assert_array_equal(got, want,
                                          err_msg=f"case {case} spec={spec}")


def test_materializer_matches_host_random(rng):
    R = 4
    mat = msa.make_materializer(96, 128, R)
    for case in range(25):
        tlen = int(rng.integers(1, 97))
        cons = rng.integers(0, 6, 96).astype(np.uint8)   # bases/gap/pad
        ins_out = np.where(rng.random((96, R)) < 0.3,
                           rng.integers(0, 4, (96, R)),
                           msa.PAD).astype(np.uint8)
        want = msa.materialize(cons, ins_out, tlen)
        out, newlen, ovf = (np.asarray(x) for x in
                            mat(cons, ins_out, np.int32(tlen)))
        assert int(newlen) == len(want)
        assert bool(ovf) == (len(want) > 128)
        keep = min(len(want), 128)
        np.testing.assert_array_equal(out[:keep], want[:keep])
        assert (out[keep:] == banded.PAD).all()


def test_materializer_overflow_flag(rng):
    """Output longer than tmax_out must set the overflow flag and keep
    the prefix exact (the executor then replays the hole on the host)."""
    R = 4
    mat = msa.make_materializer(96, 64, R)
    cons = rng.integers(0, 4, 96).astype(np.uint8)       # all bases kept
    ins_out = rng.integers(0, 4, (96, R)).astype(np.uint8)  # all emitted
    tlen = 96
    want = msa.materialize(cons, ins_out, tlen)          # 480 cells
    out, newlen, ovf = (np.asarray(x) for x in
                        mat(cons, ins_out, np.int32(tlen)))
    assert bool(ovf) and int(newlen) == len(want) == 480
    np.testing.assert_array_equal(out, want[:64])
