"""ccsx_tpu — a TPU-native framework for PacBio circular consensus (CCS/HiFi).

A brand-new implementation of the capabilities of the CPU reference tool
``110allan/ccsx`` (see /root/reference, SURVEY.md), redesigned for TPUs:

* ingest: BAM / (gzipped) FASTA/FASTQ subread streams grouped by ZMW hole
  (reference: seqio.h:152-201, bamlite.c, kseq.h);
* prepare: per-hole pass orientation + clipping against a template pass
  (reference: main.c:116-453);
* consensus: the reference's banded-striped POA (external bsalign/BSPOA,
  main.c:486-492,552-572) is *redesigned* as a template-anchored star MSA
  with banded affine-gap DP batched over (ZMW x pass), majority-vote
  columns and an iterative refinement pass — static shapes, vmap/shard_map
  over a device mesh, Pallas kernels for the DP fill;
* pipeline: 3-stage read/compute/write overlap (reference: kthread.c:172-256)
  as host threads feeding the device asynchronously, order-preserving.

Layout:
  config        — all parity-critical constants (SURVEY.md §2.5)
  io/           — parsers + ZMW streamer (python fallback + C++ native)
  ops/          — encode tables, batched DP, traceback/projection, MSA ops
  consensus/    — prepare (orientation), whole-read + windowed consensus
  parallel/     — mesh construction, shard_map wrappers, multi-host
  pipeline/     — chunked async pipeline, bucketizer, writer
  utils/        — metrics, journal, profiling
"""

__version__ = "0.1.0"

from ccsx_tpu.config import CcsConfig  # noqa: F401
