"""Fused refinement step (pipeline/batch._refine_step): bit-parity with
the host refinement loop (star.refine_host — the spec), per-hole fixpoint
masking, and the overflow -> host-replay fallback."""

import numpy as np
import pytest

from ccsx_tpu.config import CcsConfig
from ccsx_tpu.consensus import windowed as win_mod
from ccsx_tpu.consensus.star import RefineRequest, StarMsa, refine_host
from ccsx_tpu.pipeline import batch as batch_mod
from ccsx_tpu.pipeline.batch import BatchExecutor
from ccsx_tpu.utils import synth
from ccsx_tpu.utils.metrics import Metrics


def _requests(rng, cfg, specs):
    """Build RefineRequests for (n_passes, tlen, err) hole specs; includes
    error-free holes so the fixpoint early-exit path is exercised."""
    sm = StarMsa(cfg.align, cfg.max_ins_per_col, cfg.len_bucket_quant)
    reqs = []
    for n, tlen, err in specs:
        tpl = rng.integers(0, 4, tlen).astype(np.uint8)
        if err == 0.0:
            ps = [tpl.copy() for _ in range(n)]
        else:
            ps = [synth.mutate(rng, tpl, err / 3, err / 3, err / 3)
                  for _ in range(n)]
        qs, qlens, row_mask = sm.pack(ps, cfg.pass_buckets, cfg.max_passes)
        reqs.append(RefineRequest(qs, qlens, row_mask, ps[0],
                                  cfg.refine_iters))
    return sm, reqs


def _assert_matches_host(sm, cfg, req, res):
    want = refine_host(
        sm.round, req.qs, req.qlens, req.row_mask, req.draft, req.iters)
    want_rr = want.rr
    np.testing.assert_array_equal(res.draft, want.draft)
    rr = res.rr
    assert rr.tlen == want_rr.tlen
    T = rr.tlen
    np.testing.assert_array_equal(rr.cons[:T], want_rr.cons[:T])
    np.testing.assert_array_equal(rr.ins_base[:T], want_rr.ins_base[:T])
    np.testing.assert_array_equal(rr.ins_votes[:T], want_rr.ins_votes[:T])
    np.testing.assert_array_equal(rr.ncov[:T], want_rr.ncov[:T])
    # device breakpoint/advance vs the host spec on the host result
    nseq = int(req.row_mask.sum())
    host_bp = win_mod.find_breakpoint(want_rr, nseq, cfg)
    if rr.bp is not None:  # host-replayed results carry bp=None
        assert (rr.bp if rr.bp >= 1 else None) == host_bp
        bp_eff = host_bp if host_bp is not None else max(T - cfg.bp_window, 1)
        np.testing.assert_array_equal(
            rr.advance, win_mod._advance(want_rr, bp_eff).astype(np.int32))
        # the windowed consumer's actual slice must agree too
        if host_bp is not None:
            np.testing.assert_array_equal(
                rr.materialize(upto=host_bp),
                want_rr.materialize(upto=host_bp))


@pytest.mark.slow  # ~18s 5-spec sweep; the mesh/overflow/headroom
# siblings and test_packing's CLI parity pin stay tier-1 (r13 audit)
def test_fused_refine_matches_host_loop(rng):
    """One fused dispatch == the host refinement loop, bitwise, across
    mixed shapes, pass counts, noise levels, and fixpoint holes."""
    cfg = CcsConfig(is_bam=False)
    specs = [(3, 500, 0.12), (5, 700, 0.06), (4, 500, 0.0),
             (9, 1100, 0.12), (6, 700, 0.3)]
    sm, reqs = _requests(rng, cfg, specs)
    metrics = Metrics()
    results = BatchExecutor(cfg, metrics=metrics).run(reqs)
    for req, res in zip(reqs, results):
        _assert_matches_host(sm, cfg, req, res)
    # every window was satisfied by fused dispatches, not host replay
    assert metrics.refine_overflows == 0
    assert metrics.windows == len(reqs)


@pytest.mark.parametrize("mesh", [
    (4, 2),
    # (8,1) is the same invariant on a second mesh shape; (4,2) keeps
    # the fused-refine mesh A/B tier-1 (r16 budget audit)
    pytest.param((8, 1), marks=pytest.mark.slow),
])
def test_fused_refine_under_mesh(rng, mesh):
    """The fused while_loop must survive GSPMD partitioning over the
    (data, pass) mesh bit-exactly (psums inside a while_loop body)."""
    cfg = CcsConfig(is_bam=False, mesh_shape=mesh)
    specs = [(5, 600, 0.1), (7, 900, 0.1), (6, 600, 0.0)]
    sm, reqs = _requests(rng, cfg, specs)
    results = BatchExecutor(cfg).run(reqs)
    for req, res in zip(reqs, results):
        _assert_matches_host(sm, cfg, req, res)


def test_fused_refine_overflow_replays_on_host(rng, monkeypatch):
    """With the fused draft capacity pinned to the request bucket (no
    growth headroom), insert-heavy holes overflow on device and must be
    replayed on the host — bit-faithfully, and counted."""
    cfg = CcsConfig(is_bam=False)
    sm = StarMsa(cfg.align, cfg.max_ins_per_col, cfg.len_bucket_quant)
    # a draft with every 4th template base deleted (450 of 600 bases,
    # bucket 512) against unanimous full-length passes: round 1 re-grows
    # the draft to ~600 — past the pinned capacity
    tpl = rng.integers(0, 4, 600).astype(np.uint8)
    draft = tpl[np.arange(600) % 4 != 3]
    ps = [tpl.copy() for _ in range(6)]
    qs, qlens, row_mask = sm.pack(ps, cfg.pass_buckets, cfg.max_passes)
    req = RefineRequest(qs, qlens, row_mask, draft, cfg.refine_iters)

    monkeypatch.setattr(batch_mod, "_fused_tmax",
                        lambda tlen, quant: batch_mod.bucket_len(tlen, quant))
    metrics = Metrics()
    res = BatchExecutor(cfg, metrics=metrics).run([req])[0]
    assert metrics.refine_overflows >= 1
    _assert_matches_host(sm, cfg, req, res)


def test_fused_tmax_headroom():
    from ccsx_tpu.consensus.star import bucket_len

    for tlen in (100, 512, 700, 2000, 2048):
        b = bucket_len(tlen, 512)
        f = batch_mod._fused_tmax(tlen, 512)
        assert f > b  # always at least one geometric step of growth room
