"""Base encoding/decoding and reverse-complement.

Code space (used across the whole framework, incl. MSA matrices):
  0..3  = A C G T
  4     = gap (in MSA columns) / N (in raw sequence encode)
  5     = PAD: row/column padding, never a real observation

The reference encodes with bsalign's ``base_bit_table`` (A=0 C=1 G=2 T=3,
other=4; main.c:231,237) and its MSA uses the same 0-3 base / >=4 gap codes
(main.c:583-598,635-636).  ASCII reverse-complement mirrors ``seq_comp_table``
/ ``seq_reverse_comp`` (seqio.h:120-148).
"""

from __future__ import annotations

import numpy as np

A, C, G, T = 0, 1, 2, 3
GAP = 4
PAD = 5

BASES = "ACGTN-"

# ASCII -> 2-bit (A/a=0, C/c=1, G/g=2, T/t=3, everything else=4=N)
_ENC = np.full(256, 4, dtype=np.uint8)
for _i, _b in enumerate("ACGT"):
    _ENC[ord(_b)] = _i
    _ENC[ord(_b.lower())] = _i

# ASCII complement table (A<->T, C<->G, case preserved, others unchanged),
# matching seq_comp_table's behavior for the DNA alphabet (seqio.h:120-137).
_COMP = np.arange(256, dtype=np.uint8)
for _x, _y in [("A", "T"), ("C", "G"), ("G", "C"), ("T", "A"),
               ("a", "t"), ("c", "g"), ("g", "c"), ("t", "a"),
               ("U", "A"), ("u", "a"), ("N", "N"), ("n", "n")]:
    _COMP[ord(_x)] = ord(_y)

# 2-bit decode
_DEC = np.frombuffer(BASES.encode(), dtype=np.uint8)


def encode(seq: bytes | str) -> np.ndarray:
    """ASCII sequence -> uint8 codes (0-3 bases, 4 for non-ACGT)."""
    if isinstance(seq, str):
        seq = seq.encode()
    return _ENC[np.frombuffer(seq, dtype=np.uint8)]


def decode(codes: np.ndarray) -> str:
    """uint8 codes -> ASCII string (4 -> 'N', 5 -> '-')."""
    return _DEC[np.asarray(codes, dtype=np.uint8)].tobytes().decode()


def to_record(result):
    """Normalize a consensus-generator result into a writable record.

    codes -> (seq_bytes, None); (codes, phred_quals) -> (seq_bytes,
    phred+33 ASCII bytes); None -> None.  The quality tuple form is
    produced under CcsConfig.emit_quality (--fastq)."""
    if result is None:
        return None
    if isinstance(result, tuple):
        codes, quals = result
        qual = (np.asarray(quals, dtype=np.uint8) + 33).tobytes()
        return decode(codes).encode(), qual
    return decode(result).encode(), None


def revcomp_ascii(seq: bytes) -> bytes:
    """Reverse-complement of an ASCII sequence (seq_reverse_comp, seqio.h:138-148)."""
    arr = np.frombuffer(seq, dtype=np.uint8)
    return _COMP[arr[::-1]].tobytes()


def revcomp_codes(codes: np.ndarray) -> np.ndarray:
    """Reverse-complement of 2-bit codes; N (4) maps to itself.

    The reference computes ``3 - base_bit_table[b]`` (main.c:231); we guard N.
    """
    codes = np.asarray(codes, dtype=np.uint8)
    out = np.where(codes < 4, 3 - codes, codes)
    return out[::-1].copy()
