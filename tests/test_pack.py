"""Fast unit tier for the ragged pass-packer (pipeline/pack.py).

The packer is pure host planning (no jax), so its invariants —
first-fit-decreasing determinism, row-budget and capacity edge cases,
segment-id round-trip — are pinned here in milliseconds; a packer
regression fails in seconds, not via an e2e differential run."""

import numpy as np
import pytest

from ccsx_tpu.pipeline import pack


def test_pow2():
    assert pack.pow2(0) == 1
    assert pack.pow2(1) == 1
    assert pack.pow2(3) == 4
    assert pack.pow2(64) == 64
    assert pack.pow2(65) == 128


def test_slab_shape_full_slab_lands_on_budget():
    R, H = pack.slab_shape([9, 11, 20, 24], 64)
    assert (R, H) == (64, 16)


def test_slab_shape_tail_snaps_to_canonical_heights():
    """Partial slabs snap UP to the smallest of the <= ladder canonical
    heights (budget >> k) that fits — at most 2 XLA programs per shape
    group by default, vs the 4-5 the r7 budget/8 ladder paid (the
    compile storm the flight recorder caught)."""
    assert pack.canonical_heights(64) == [64, 32]
    assert pack.slab_shape([5, 6], 64) == (32, 8)    # 11 -> budget/2
    assert pack.slab_shape([3], 64) == (32, 8)       # tiny tail: same
    assert pack.slab_shape([10, 9, 9, 9], 64) == (64, 16)  # 37 > 32
    assert pack.slab_shape([30, 20], 128) == (64, 16)      # 50 -> 64


def test_slab_shape_ladder_knob():
    """ladder=1 forces every slab full-height (one program per group);
    deeper ladders add halvings for row-fill-sensitive runs."""
    assert pack.canonical_heights(64, ladder=1) == [64]
    assert pack.slab_shape([3], 64, ladder=1) == (64, 16)
    assert pack.canonical_heights(64, ladder=3) == [64, 32, 16]
    assert pack.slab_shape([3], 64, ladder=3) == (16, 4)
    # ladder never walks below one row
    assert pack.canonical_heights(2, ladder=4) == [2, 1, 1, 1]


def test_slab_shape_capacity_floor():
    """Many tiny holes: the SEG_DIV rows-per-slot floor keeps
    H >= n_holes so every packed hole has a segment slot."""
    rows = [1] * 10
    R, H = pack.slab_shape(rows, 64)
    assert H >= len(rows)
    assert R == 64  # seg floor 4*10 = 40 snaps up to the budget


def test_slab_shape_oversize_hole_grows_R():
    R, H = pack.slab_shape([100], 64)
    assert R == 128 and H == 32


def test_slab_shape_empty_raises():
    with pytest.raises(ValueError):
        pack.slab_shape([], 64)


def test_plan_ffd_is_deterministic_and_decreasing():
    rows = [9, 3, 17, 9, 5, 30, 12]
    a = pack.plan_slabs(rows, 32)
    b = pack.plan_slabs(rows, 32)
    assert a == b
    # placement order within a slab is descending rows, index-tiebroken
    for slab in a:
        rs = [rows[i] for i in slab]
        assert rs == sorted(rs, reverse=True)
    # equal-row ties break by original index
    t = pack.plan_slabs([4, 4, 4], 16)
    assert t == [[0, 1, 2]]


def test_plan_covers_every_hole_once():
    rows = [9, 3, 17, 9, 5, 30, 12, 1, 1, 28]
    slabs = pack.plan_slabs(rows, 64)
    got = sorted(i for s in slabs for i in s)
    assert got == list(range(len(rows)))


def test_plan_respects_row_budget():
    rows = [20, 20, 20, 20, 20]
    slabs = pack.plan_slabs(rows, 64)
    for slab in slabs:
        assert sum(rows[i] for i in slab) <= 64
    assert len(slabs) == 2  # 3 + 2, not 5 singletons


def test_plan_respects_segment_capacity():
    """Holes smaller than SEG_DIV rows fill hole slots faster than rows;
    the capacity (budget // SEG_DIV) must cap the slab."""
    rows = [2] * 20
    slabs = pack.plan_slabs(rows, 32)  # cap = 8 holes/slab
    assert all(len(s) <= 8 for s in slabs)
    assert len(slabs) == 3


def test_plan_oversize_hole_gets_dedicated_slab():
    rows = [70, 5, 5]
    slabs = pack.plan_slabs(rows, 64)
    assert [0] in slabs  # nothing can share the over-budget slab
    assert sorted(map(sorted, slabs)) == [[0], [1, 2]]


def test_plan_first_fit_backfills_earlier_slabs():
    """A later small hole must land in the FIRST slab with room, not
    open a new one."""
    rows = [30, 28, 30, 4]
    slabs = pack.plan_slabs(rows, 64)
    # FFD order 30(i0), 30(i2), 28(i1), 4(i3): i1 overflows slab0
    # (60+28), opens slab1; i3 then BACKFILLS slab0 to exactly 64
    assert slabs == [[0, 2, 3], [1]]


def test_segment_ids_round_trip():
    rows = [3, 5, 2]
    R, H = pack.slab_shape(rows, 32)
    seg = pack.segment_ids(rows, R)
    assert seg.dtype == np.int32 and len(seg) == R
    # each hole's rows are contiguous and labeled with its slot
    r0 = 0
    for s, n in enumerate(rows):
        assert (seg[r0:r0 + n] == s).all()
        r0 += n
    # padding tail: in range and sorted (the device segment-sums pass
    # indices_are_sorted)
    assert (seg[r0:] == len(rows) - 1).all()
    assert (np.diff(seg) >= 0).all()
    assert seg.max() < H


def test_segment_ids_overflow_raises():
    with pytest.raises(ValueError):
        pack.segment_ids([10, 10], 16)
