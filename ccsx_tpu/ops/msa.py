"""Star-MSA column voting: consensus call over stacked projections.

The reference's consensus is BSPOA's column/bundle majority over the POA MSA
(g->cns consumed at main.c:495-501; MSA cells 0-3 base / >=4 gap at
main.c:583-598).  Our MSA is the stack of template-anchored projections
(ops/traceback.py): base columns are template columns, insertion columns are
the per-slot insertion cells.  The vote is a pure elementwise reduction over
the pass axis — ideal VPU work, shardable over passes with a psum.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

GAP = 4
PAD = 5


def make_voter(max_ins: int = 4):
    """Jitted column vote.  Shapes: aligned (P, T), ins_cnt (P, T),
    ins_b (P, T, R), row_mask (P,) bool.  Returns:
      cons     (T,) uint8  — 0-3 base, 4 gap (column dropped)
      ins_base (T, R) uint8 — majority inserted base per slot/rank (always
                              computed; emission is the caller's threshold)
      ins_votes(T, R) int32 — passes inserting at least r+1 bases at the slot
      ncov     (T,) int32  — covering passes per column
      match    (P, T) bool — pass agrees with consensus at base column
      nwin     (T,) int32  — passes voting the winning cell (per-base
                             quality derives from the nwin/ncov margin)
    """

    @jax.jit
    def vote(aligned, ins_cnt, ins_b, row_mask):
        mask = row_mask[:, None]
        cnts = jnp.stack(
            [((aligned == c) & mask).sum(0) for c in range(5)]
        )  # (5, T): A C G T gap
        ncov = cnts.sum(0)
        nwin = cnts.max(0)
        cons = jnp.argmax(cnts, axis=0).astype(jnp.uint8)
        cons = jnp.where(ncov == 0, jnp.uint8(GAP), cons)

        bases, votes = [], []
        for r in range(max_ins):
            has = mask & (ins_cnt > r)
            votes.append(has.sum(0))
            bc = jnp.stack(
                [((ins_b[:, :, r] == c) & has).sum(0) for c in range(4)]
            )
            bases.append(jnp.argmax(bc, axis=0).astype(jnp.uint8))
        ins_base = jnp.stack(bases, axis=1)
        ins_votes = jnp.stack(votes, axis=1)

        match = (aligned == cons[None, :]) & mask
        return cons, ins_base, ins_votes, ncov, match, nwin

    return vote


def make_segment_voter(max_ins: int, num_segments: int):
    """Segment-id column vote for the ragged pass-packed pipeline
    (pipeline/pack.py): rows from MANY holes share one (R, T) slab, and
    ``seg`` maps each row to its hole slot in [0, num_segments).

    Shapes: aligned (R, T), ins_cnt (R, T), ins_b (R, T, max_ins),
    row_mask (R,) bool, seg (R,) int32 SORTED ascending (pack.segment_ids
    guarantees it; padding rows carry an in-range id and are masked).
    Returns the same tuple as make_voter with the hole axis H =
    num_segments in front of the per-hole outputs and match staying
    per-ROW:
      cons (H, T), ins_base (H, T, max_ins), ins_votes (H, T, max_ins),
      ncov (H, T), match (R, T), nwin (H, T).

    Bit-parity with make_voter per hole: every reduced quantity is
    pre-masked by row_mask before the segment sum, so a hole's counts
    are the integer sums over exactly its real rows — the same sums the
    fixed-P vote takes over a (P, T) block with padding rows masked —
    and argmax tie-breaking is the same first-max over the stacked base
    axis.  Empty hole slots get ncov == 0 -> cons GAP, like an all-pad
    block.  Integer scatter-adds are order-invariant, so the reduction
    order change cannot perturb results.

    Deliberately UNJITTED (unlike make_voter, which tests and benches
    call standalone): the sole consumer is the fused packed step
    (pipeline/batch._round_body_packed), always inside an outer jit —
    a nested jit there adds a dispatch-cache layer per trace and its
    own executable cache entries per shape for zero benefit, against
    the compile-lean dispatch discipline (r8).
    """
    H = num_segments

    def vote(aligned, ins_cnt, ins_b, row_mask, seg):
        mask = row_mask[:, None]

        def ssum(x):
            return jax.ops.segment_sum(x.astype(jnp.int32), seg,
                                       num_segments=H,
                                       indices_are_sorted=True)

        cnts = jnp.stack(
            [ssum((aligned == c) & mask) for c in range(5)]
        )  # (5, H, T): A C G T gap
        ncov = cnts.sum(0)
        nwin = cnts.max(0)
        cons = jnp.argmax(cnts, axis=0).astype(jnp.uint8)
        cons = jnp.where(ncov == 0, jnp.uint8(GAP), cons)

        bases, votes = [], []
        for r in range(max_ins):
            has = mask & (ins_cnt > r)
            votes.append(ssum(has))
            bc = jnp.stack(
                [ssum((ins_b[:, :, r] == c) & has) for c in range(4)]
            )
            bases.append(jnp.argmax(bc, axis=0).astype(jnp.uint8))
        ins_base = jnp.stack(bases, axis=2)
        ins_votes = jnp.stack(votes, axis=2)

        match = (aligned == cons[seg]) & mask
        return cons, ins_base, ins_votes, ncov, match, nwin

    return vote


def emit_insertions(ins_base: np.ndarray, ins_votes: np.ndarray,
                    ncov: np.ndarray, speculative: bool) -> np.ndarray:
    """Decide which insertion cells become columns (host, NumPy).

    Strict: a majority of covering passes insert at the slot (the POA
    analog: the inserted bundle outweighs the gap bundle).

    Speculative (intermediate refinement rounds): ALSO accept >=2-pass /
    >=1/3 support.  Star MSAs split the votes for a base the draft is
    missing across adjacent slots and substitution cells (unlike a POA
    graph, where one inserted node accumulates all the weight); inserting
    liberally turns the candidate into a *column*, whose vote next round
    does not split — wrong speculations are then deleted by majority gap.
    """
    ins_base = np.asarray(ins_base)
    # widen before arithmetic: the batched round transfers votes/coverage
    # as uint8 (bounded by the pass bucket) and *2 / //3 must not wrap
    ins_votes = np.asarray(ins_votes).astype(np.int32, copy=False)
    n = np.asarray(ncov).astype(np.int32, copy=False)[:, None]
    emit = ins_votes * 2 > n
    if speculative:
        emit |= ins_votes >= np.maximum(2, -(-n // 3))
    # prefix rule: rank r only emits if rank r-1 did
    emit = np.logical_and.accumulate(emit, axis=1)
    return np.where(emit, ins_base, PAD).astype(np.uint8)


def materialize(cons: np.ndarray, ins_out: np.ndarray, tlen: int) -> np.ndarray:
    """Interleave base + insertion columns into the consensus sequence.

    Host-side: output length is data-dependent.  Order: column j's base
    (if not gap), then the insertions after column j.
    """
    cons = np.asarray(cons)[:tlen]
    ins = np.asarray(ins_out)[:tlen]
    m = np.concatenate([cons[:, None], ins], axis=1).ravel()
    return m[m < 4].astype(np.uint8)


def emit_insertions_jax(ins_base, ins_votes, ncov, speculative: bool):
    """jnp equivalent of emit_insertions — bit-identical by construction
    (same int arithmetic; the prefix rule is a cumprod over ranks).  Used
    inside the fused refinement step (pipeline/batch._refine_step), where
    the intermediate speculative drafts never leave the device."""
    iv = jnp.asarray(ins_votes).astype(jnp.int32)
    n = jnp.asarray(ncov).astype(jnp.int32)[:, None]
    emit = iv * 2 > n
    if speculative:
        emit = emit | (iv >= jnp.maximum(2, -(-n // 3)))
    emit = jnp.cumprod(emit.astype(jnp.int32), axis=1).astype(bool)
    return jnp.where(emit, ins_base, jnp.uint8(PAD))


def make_materializer(tmax_in: int, tmax_out: int, max_ins: int):
    """Device materialize: interleave + stable-compact at static shapes.

    Returns f(cons (tmax_in,), ins_out (tmax_in, max_ins), tlen) ->
    (draft (tmax_out,) uint8 padded with PAD, newlen int32, overflow bool).
    Bit-identical to the host materialize on the first ``newlen`` cells
    whenever ``overflow`` is False; on overflow the tail is dropped and the
    caller must fall back to the host path (the flag makes that exact).
    """

    def mat(cons, ins_out, tlen):
        m = jnp.concatenate([cons[:, None], ins_out], axis=1).reshape(-1)
        col = jnp.repeat(jnp.arange(tmax_in, dtype=jnp.int32), 1 + max_ins)
        keep = (m < 4) & (col < tlen)
        pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
        newlen = keep.sum().astype(jnp.int32)
        out = jnp.full((tmax_out,), jnp.uint8(PAD))
        idx = jnp.where(keep, pos, tmax_out)  # parked writes drop below
        out = out.at[idx].set(m.astype(jnp.uint8), mode="drop")
        return out, newlen, newlen > tmax_out

    return mat
