"""Known-bad twin for span-force: a device span that only times the
async dispatch."""

from ccsx_tpu.utils import trace


def dispatch(step, big, small, group):
    with trace.device_span("dispatch", group=group) as sp:
        out = step(big, small)   # enqueue returns immediately
    return out
