"""Fleet chaos soak: elastic leased-range scheduling under fleet churn.

The elastic fleet plane's claim (pipeline/fleet.py + supervisor
``fleet_run``) extends the chaos harness's: fleet membership churn —
a rank SIGKILLed mid-run, a rank SIGTERM-draining out, a rank joining
mid-run (`shepherd --join`), a straggler rank — changes WHICH worker
computes each leased range, never the merged bytes.  Every trial here
runs a K-worker leased-range fleet end-to-end and asserts byte-identity
against the unsharded fault-free reference.

Two extra numbers ride in the summary:

* **scale-out efficiency** — fault-free K-worker wall vs 1-worker wall
  (``bench.py`` gates this ``vs_prev`` across rounds);
* **killed-at-halfway overhead** — wall of a K=4 run with one worker
  SIGKILLed mid-run (zero restart budget: the survivors absorb its
  ranges via reap-time reclaim) over the fault-free K=4 wall.  The
  acceptance bar is ~1.4x: rank loss costs about one range of
  recompute, not 1/K of the run.

The ``--scale64`` mode replays the 64-hole scale config
(benchmarks/e2e_scale.py's corpus: rng(42), 1-5 kb lognormal-pass
BGZF BAM + hole index, ``--batch on --inflight 64``) and checks the
pinned unsharded md5 (``0c83700d…``, the PR7/PR8/PR11 byte-identity
pin) before running the fleet variants against it — the acceptance
corpus for this plane.

The fast deterministic slice runs in tier-1 (tests/test_fleet.py,
`make fleet-chaos` runs this CLI):

    python benchmarks/fleet.py --seed 0 --holes 6 \
        --json benchmarks/fleet_rNN.json
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
import tempfile
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from ccsx_tpu import cli                                     # noqa: E402
from benchmarks.chaos import (                               # noqa: E402
    _base_args, make_corpus, run_reference)

# the pinned unsharded output of the 64-hole scale config (the
# acceptance corpus): any drift here is an output-bytes regression in
# the consensus plane, not a fleet bug — fix that first
SCALE64_MD5 = "0c83700d0fb67e3c89169f99574a9a2d"
SCALE64_BYTES = 188359


def _scale64_args(in_bam: str, out: str, extra=()) -> list:
    return ["--batch", "on", "--inflight", "64", *extra, in_bam, out]


def make_scale64_corpus(tmp: str) -> str:
    """EXACTLY benchmarks/e2e_scale.py's 64-hole scale config: a fresh
    rng(42) into make_big_bam (1-5 kb templates, lognormal pass counts,
    read-throughs every 5th hole), BGZF container + hole index."""
    from benchmarks.e2e_scale import make_big_bam
    from ccsx_tpu.io import bamindex

    rng = np.random.default_rng(42)
    p = os.path.join(tmp, "in64.bam")
    make_big_bam(p, 64, rng)
    bamindex.build_index(p)
    return p


def run_scale64_reference(in_bam: str, tmp: str) -> bytes:
    ref = os.path.join(tmp, "ref64.fa")
    rc = cli.main(_scale64_args(in_bam, ref))
    assert rc == 0, f"fault-free scale64 reference failed rc={rc}"
    return open(ref, "rb").read()


def _fleet_run(in_fa: str, out: str, hosts: int, ranges: int,
               mkargs=_base_args, scale=False, **kw):
    from ccsx_tpu.pipeline.supervisor import fleet_run

    fwd = mkargs(in_fa, out)
    cfg = cli.config_from_args(cli.build_parser().parse_args(fwd))
    kw.setdefault("env", dict(os.environ, CCSX_JOURNAL_FSYNC_S="0"))
    # lease timeout must exceed the worst GIL stall a healthy worker
    # can suffer (jit TRACING holds the GIL and starves the renewer
    # thread; four workers cold-tracing the scale64 corpus under full
    # CPU contention measured >60 s), or the scheduler SIGKILLs live
    # workers — safe (the range requeues and resumes) but it pollutes
    # the wall numbers.  Liveness on real faults does not depend on
    # this: a reaped worker's leases free instantly (reap-time
    # reclaim); the timeout only covers unreapable holders.
    kw.setdefault("lease_timeout", 300.0 if scale else 10.0)
    t0 = time.monotonic()
    rc = fleet_run(in_fa, out, cfg, hosts, fwd, ranges=ranges,
                   poll_s=0.1, backoff_s=0.1, **kw)
    return rc, time.monotonic() - t0


def _trial(kind, in_fa, tmp, ref, hosts, ranges, mkargs=_base_args,
           scale=False, **kw):
    out = os.path.join(tmp, f"o_{kind}.fa")
    rc, wall = _fleet_run(in_fa, out, hosts, ranges, mkargs, scale,
                          **kw)
    got = open(out, "rb").read() if os.path.exists(out) else b""
    return {"kind": kind, "hosts": hosts, "ranges": ranges, "rc": rc,
            "wall_s": round(wall, 2), "identical": got == ref,
            "ok": rc == 0 and got == ref}


def trial_join(in_fa, tmp, ref, ranges, mkargs=_base_args,
               scale=False):
    """One worker runs; a second joins mid-run via the --join path."""
    import threading

    from ccsx_tpu.pipeline import fleet as fleet_mod
    from ccsx_tpu.pipeline.supervisor import fleet_join

    out = os.path.join(tmp, "o_join.fa")
    d = fleet_mod.fleet_dir_for(out)
    join_rc = []

    def joiner():
        for _ in range(600):
            if fleet_mod.load_fleet(d):
                break
            time.sleep(0.05)
        join_rc.append(fleet_join(
            d, 1, poll_s=0.1,
            env=dict(os.environ, CCSX_JOURNAL_FSYNC_S="0")))

    t = threading.Thread(target=joiner)
    t.start()
    rc, wall = _fleet_run(in_fa, out, 1, ranges, mkargs, scale)
    t.join()
    got = open(out, "rb").read() if os.path.exists(out) else b""
    return {"kind": "join", "hosts": "1+1", "ranges": ranges, "rc": rc,
            "join_rc": join_rc, "wall_s": round(wall, 2),
            "identical": got == ref,
            "ok": rc == 0 and join_rc == [0] and got == ref}


def run_trials(seed: int, holes: int, ranges: int = 0,
               scale64: bool = False, tmp: str = None) -> dict:
    """The soak: fault-free K=1 and K=4 walls (scale-out efficiency),
    then the churn trials — SIGKILL at halfway with zero restart
    budget, SIGTERM drain, mid-run join, and a straggler — every one
    against the byte-identity oracle."""
    os.environ.setdefault("CCSX_FAULT_STALL_S", "3")
    rng = np.random.default_rng(seed)
    own_tmp = tmp is None
    tmp = tmp or tempfile.mkdtemp(prefix="ccsx_fleet_")
    t0 = time.monotonic()
    results = []
    try:
        if scale64:
            in_fa = make_scale64_corpus(tmp)
            ref = run_scale64_reference(in_fa, tmp)
            md5 = hashlib.md5(ref).hexdigest()
            pin_ok = md5 == SCALE64_MD5 and len(ref) == SCALE64_BYTES
            results.append({"kind": "scale64_pin", "md5": md5,
                            "bytes": len(ref), "ok": pin_ok})
            mkargs = _scale64_args
        else:
            in_fa = make_corpus(tmp, rng, holes)
            ref = run_reference(in_fa, tmp)
            mkargs = _base_args
        m = ranges or max(8, holes // 2)
        half = max(1, holes // 8)   # ~halfway through a K=4 worker's share
        results.append(_trial("plain_k1", in_fa, tmp, ref, 1, m,
                              mkargs, scale64))
        results.append(_trial("plain_k4", in_fa, tmp, ref, 4, m,
                              mkargs, scale64))
        results.append(_trial(
            "kill_halfway_k4", in_fa, tmp, ref, 4, m, mkargs, scale64,
            max_restarts=0,
            first_launch_env={1: {"CCSX_FAULTS": f"rank_death@{half}"}}))
        results.append(_trial(
            "drain_k2", in_fa, tmp, ref, 2, m, mkargs, scale64,
            max_restarts=0,
            first_launch_env={1: {"CCSX_FAULTS": "sigterm@1"}}))
        results.append(trial_join(in_fa, tmp, ref, m, mkargs, scale64))
        # straggler: worker 1's dispatches stall CCSX_FAULT_STALL_S
        # each — the fast workers must absorb its share via the lease
        # queue, and the bytes must not care
        results.append(_trial(
            "straggler_k4", in_fa, tmp, ref, 4, m, mkargs, scale64,
            first_launch_env={1: {"CCSX_FAULTS": "stall@1+"}}))
    finally:
        if own_tmp:
            import shutil

            shutil.rmtree(tmp, ignore_errors=True)
    by = {r["kind"]: r for r in results}
    walls = {k: by[k]["wall_s"] for k in
             ("plain_k1", "plain_k4", "kill_halfway_k4")
             if k in by and by[k].get("wall_s")}
    derived = {}
    if "plain_k1" in walls and "plain_k4" in walls:
        derived["scaleout_k4"] = round(
            walls["plain_k1"] / walls["plain_k4"], 3)
    if "plain_k4" in walls and "kill_halfway_k4" in walls:
        derived["kill_overhead_x"] = round(
            walls["kill_halfway_k4"] / walls["plain_k4"], 3)
    bad = [r for r in results if not r["ok"]]
    return {"seed": seed, "holes": (64 if scale64 else holes),
            "scale64": scale64, "trials": results,
            "n_trials": len(results), "n_failed": len(bad),
            "derived": derived, "ok": not bad,
            "elapsed_s": round(time.monotonic() - t0, 1)}


def main():
    ap = argparse.ArgumentParser(
        description="Fleet chaos soak: leased-range scheduling under "
                    "rank SIGKILL / drain / join / straggler churn, "
                    "byte-identity oracle (seeded, replayable)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--holes", type=int, default=6)
    ap.add_argument("--ranges", type=int, default=0,
                    help="M (0 = max(8, holes//2))")
    ap.add_argument("--scale64", action="store_true",
                    help="run over the pinned 64-hole scale config "
                         "(the acceptance corpus) instead of the "
                         "small seeded corpus")
    ap.add_argument("--json", default=None)
    a = ap.parse_args()
    summary = run_trials(a.seed, a.holes, a.ranges, scale64=a.scale64)
    print(json.dumps(summary, indent=1))
    if a.json:
        with open(a.json, "w") as f:
            json.dump(summary, f, indent=1)
    return 0 if summary["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
