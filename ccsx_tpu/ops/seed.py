"""Host-side k-mer diagonal seeding (NumPy).

The reference's pairwise aligner is k-mer seeded
(kmer_striped_seqedit_pairwise with k=13, main.c:264): shared 13-mers locate
the alignment diagonal before the banded DP runs.  We keep that division of
labor: seeding runs on the host (tiny, latency-bound, irregular — wrong shape
for the TPU), and its output is the nominal-line hint consumed by the banded
device kernel (ops/banded.py `line=`).

Seeding is sort-join based: O((Q+T) log T) per pair, no hash tables.

Two batching layers keep the sort off prep's critical path (VERDICT r5
Weak #5: per-pair host seeding was a prime suspect in the 22% prep
share):

* ``batch_sorted_indexes`` sorts the k-mers of a WHOLE batch of
  templates in ONE NumPy argsort (pair ids packed into the high bits of
  the sort key), so a pair sweep pays one O(sum T log sum T) sort
  instead of per-pair sort setup;
* a sorted template index is reusable across every pairing of the same
  template (``sorted_kmer_index`` + the caller-held cache keyed by
  ``PairRequest.t_token``): the orientation walk aligns MANY doubtful
  passes against the one template (fwd and RC), and re-sorting it per
  pair was pure waste.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

DEFAULT_K = 13          # main.c:264
MAX_HITS_PER_KMER = 4   # repeat guard
DIAG_BIN = 32           # diagonal histogram bin width


class SeedHit(NamedTuple):
    diag: int        # qpos - tpos of the dominant diagonal
    votes: int       # supporting k-mer hits
    line: np.ndarray  # (4,) int32 nominal line for banded_align


def kmer_codes(seq: np.ndarray, k: int = DEFAULT_K) -> np.ndarray:
    """Packed 2-bit k-mer codes; positions containing N yield code -1."""
    seq = np.asarray(seq, dtype=np.int64)
    n = len(seq) - k + 1
    if n <= 0:
        return np.empty(0, dtype=np.int64)
    # rolling pack via strided cumulative shifts
    codes = np.zeros(n, dtype=np.int64)
    bad = np.zeros(n, dtype=bool)
    for i in range(k):
        w = seq[i:i + n]
        codes = (codes << 2) | (w & 3)
        bad |= w >= 4
    codes[bad] = -1
    return codes


def _bad_sentinel(k: int) -> np.int64:
    """Sort key for an N-containing k-mer: one past the largest valid
    code, so bad k-mers sort to the TAIL of the index and the array
    stays sorted for every valid-code binary search.  (Valid q-side
    codes never equal it, and bad q-side codes are masked by the
    ``cnt[qk < 0] = 0`` rule, so where the bad t-side codes sort cannot
    change any match set.)"""
    return np.int64(1) << np.int64(2 * k)


def sorted_kmer_index(t: np.ndarray,
                      k: int = DEFAULT_K) -> Tuple[np.ndarray, np.ndarray]:
    """(tks, order): the template's k-mer codes sorted ascending (bad
    codes remapped to the tail sentinel) plus the positions they came
    from.  This is the reusable half of seed_diagonal — one sort serves
    every pairing against the same template (the orientation walk's
    common case; PairExecutor caches these by ``PairRequest.t_token``)."""
    tk = kmer_codes(t, k)
    vals = np.where(tk < 0, _bad_sentinel(k), tk)
    order = np.argsort(vals, kind="stable")
    return vals[order], order


def batch_sorted_indexes(ts: Sequence[np.ndarray],
                         k: int = DEFAULT_K) -> List[tuple]:
    """sorted_kmer_index for a whole batch of templates via ONE argsort:
    each template's k-mers are offset into a disjoint key range
    (pair_id * (4^k + 1) + code, bad codes at the range's top slot), the
    concatenation is sorted once, and the per-template blocks — which
    land contiguous and in pair order — are sliced back out.  Replaces
    a pair sweep's per-pair sorts with one vectorized sort over the
    batch (the prep-plane seeding optimization, ISSUE 8)."""
    if not ts:
        return []
    kms = [kmer_codes(t, k) for t in ts]
    sizes = np.array([len(a) for a in kms], dtype=np.int64)
    if int(sizes.sum()) == 0:
        return [(a, np.empty(0, np.int64)) for a in kms]
    base = _bad_sentinel(k) + 1
    cat = np.concatenate(kms)
    vals = np.where(cat < 0, base - 1, cat)
    pid = np.repeat(np.arange(len(ts), dtype=np.int64), sizes)
    order_g = np.argsort(pid * base + vals, kind="stable")
    starts = np.zeros(len(ts) + 1, dtype=np.int64)
    np.cumsum(sizes, out=starts[1:])
    out = []
    for i in range(len(ts)):
        block = order_g[starts[i]:starts[i + 1]]
        out.append((vals[block], block - starts[i]))
    return out


def seed_diagonal(
    q: np.ndarray,
    t: np.ndarray,
    k: int = DEFAULT_K,
    min_votes: int = 3,
    t_index: Optional[tuple] = None,
) -> Optional[SeedHit]:
    """Find the dominant alignment diagonal (qpos - tpos) by k-mer voting.

    Returns None when fewer than ``min_votes`` k-mer hits support any
    diagonal band — the caller can reject the pair without running the DP
    (the reference gets the same early-out from a seedless k-mer alignment).

    ``t_index`` (optional) is a precomputed ``sorted_kmer_index(t, k)``
    — from the per-template cache or a ``batch_sorted_indexes`` sweep —
    and must describe exactly ``t``; results are identical with or
    without it (pinned by tests/test_seed.py).
    """
    qk = kmer_codes(q, k)
    if t_index is None:
        t_index = sorted_kmer_index(t, k)
    tks, order = t_index
    if len(qk) == 0 or len(tks) == 0:
        return None
    left = np.searchsorted(tks, qk, side="left")
    right = np.searchsorted(tks, qk, side="right")
    cnt = np.minimum(right - left, MAX_HITS_PER_KMER)
    cnt[qk < 0] = 0
    total = int(cnt.sum())
    if total == 0:
        return None
    qpos = np.repeat(np.arange(len(qk)), cnt)
    starts = np.repeat(left, cnt)
    # within-run offsets 0..cnt-1
    run_ids = np.repeat(np.cumsum(cnt) - cnt, cnt)
    offs = np.arange(total) - run_ids
    tpos = order[starts + offs]
    diags = qpos - tpos

    lo = -len(t)
    nbins = (len(q) + len(t)) // DIAG_BIN + 2
    binned = (diags - lo) // DIAG_BIN
    hist = np.bincount(binned, minlength=nbins)
    # sum adjacent bins so a diagonal straddling a boundary still wins
    paired = hist[:-1] + hist[1:]
    best = int(np.argmax(paired))
    votes = int(paired[best])
    if votes < min_votes:
        return None
    in_best = (binned == best) | (binned == best + 1)
    diag = int(np.median(diags[in_best]))

    Q, T = len(q), len(t)
    i0 = max(diag, 0)
    j0 = i0 - diag
    i1 = min(Q, T + diag)
    j1 = i1 - diag
    line = np.array([i0, j0, i1, j1], dtype=np.int32)
    return SeedHit(diag=diag, votes=votes, line=line)
