"""ccsx-lint: the repo-native static-analysis plane.

Pure ``ast``/``tokenize`` — importing this package MUST NOT import jax
(or anything that transitively does): the linter is a tier-1 test and
a pre-review gate on the 1-core box, so it has to run in seconds.

The checkers pin the defect families this codebase has actually
shipped and hand-reviewed out, one checker per family:

- ``int32-overflow``   the silent traced-int32 wrap in index
                       interpolation (the pre-r11 ``_line_interp`` and
                       pre-r14 ``compute_offsets`` expressions)
- ``bare-write``       crash-safety writes in lease/journal/spool/fleet
                       domains that bypass ``write_json_atomic`` /
                       ``write_json_exclusive`` / ``O_EXCL``
- ``metrics-lock``     read-modify-write on Metrics counters outside
                       ``bump()``/``add_stage()``
- ``contextvar-restore`` ``ContextVar.set()`` with no token restore in
                       a ``finally`` (the r17 cid cross-stamp shape)
- ``span-force``       ``device_span`` blocks that close without
                       forcing execution (lazy-runtime timing lies)
- ``schema-drift``     the static complement of the runtime telemetry
                       schema guard: consumed keys exist in
                       ``Metrics.snapshot()`` and snapshot keys reach
                       /metrics or the structured allowlist

See ``ccsx_tpu/lint/core.py`` for the findings format, the inline
pragma (``# lint: ok[<check>] <reason>``), and the committed baseline
(``lint_baseline.json``) that records deliberate suppressions.
"""

from ccsx_tpu.lint.core import Finding, LintResult, lint_main, run_lint

__all__ = ["Finding", "LintResult", "lint_main", "run_lint"]
