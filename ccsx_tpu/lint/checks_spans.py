"""span-force: device spans must force execution before they close.

jax dispatch is asynchronous — a ``device_span`` that wraps only the
dispatch call times the enqueue, not the device: every kernel looks
free and the compile/execute attribution table (the thing BENCH
rounds and the promotion harness read) becomes fiction.  The r09
methodology rule: the attributed path inside a device span must reach
a ``jax.block_until_ready`` or ``Span.force`` (which calls it) before
the span closes.

Rule: a ``with ... device_span(...)`` block whose body contains
neither a ``block_until_ready`` call nor a ``.force(...)`` call is
flagged.  Lambdas and nested defs inside the body count (the deadline
runner receives the forcing closure), which errs toward silence —
the checker guards against the span that *cannot* force, not against
conditional paths that sometimes don't.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Sequence

from ccsx_tpu.lint.core import Finding

CHECK = "span-force"

MESSAGE = ("device_span closes without forcing execution — add "
           "jax.block_until_ready(...) or sp.force(...) on the "
           "attributed path, or the span times the async dispatch, "
           "not the device")


def _is_device_span_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    fn = node.func
    name = fn.id if isinstance(fn, ast.Name) else (
        fn.attr if isinstance(fn, ast.Attribute) else None)
    return name == "device_span"


def _forces(body: Sequence[ast.stmt]) -> bool:
    for stmt in body:
        for sub in ast.walk(stmt):
            if not isinstance(sub, ast.Call):
                continue
            fn = sub.func
            name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None)
            if name in ("block_until_ready", "force"):
                return True
    return False


def _line_text(lines: Sequence[str], lineno: int) -> str:
    return lines[lineno - 1].strip() if 1 <= lineno <= len(lines) else ""


def check(tree: ast.AST, src: str, lines: Sequence[str],
          relpath: str) -> Iterable[Finding]:
    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        if not any(_is_device_span_call(item.context_expr)
                   for item in node.items):
            continue
        if _forces(node.body):
            continue
        out.append(Finding(CHECK, relpath, node.lineno, node.col_offset,
                           MESSAGE, _line_text(lines, node.lineno)))
    return out
