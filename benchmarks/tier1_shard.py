"""Sharded tier-1 runner: the repo's own test suite as a lease domain.

`make tier1` runs tests/ serially inside the 870 s ROADMAP budget.
This runner splits the wall clock across K workers using the SAME
machinery r16 ships for serve jobs (utils/lease.py + the exclusive
done-marker fence): every test FILE is a leasable work unit in a
shared domain directory, each worker pulls the next free file with the
kernel-arbitrated O_EXCL acquire, runs pytest on just that file, and
retires it with write_json_exclusive — so a crashed worker's file is
re-runnable (its lease expires), two workers can never double-run a
file, and the domain directory doubles as the result ledger.

Workers here are processes on one box (`make tier1-shard N=4`), but
the domain is just a directory: point --dir at a shared filesystem and
start the runner on several boxes for a cross-machine shard, exactly
like `serve --fleet`.

    python benchmarks/tier1_shard.py --workers 4
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys
import tempfile
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from ccsx_tpu.utils import lease as leaselib                  # noqa: E402
from ccsx_tpu.utils.journal import write_json_exclusive       # noqa: E402

PYTEST_FLAGS = ["-q", "-m", "not slow", "-p", "no:cacheprovider",
                "-p", "no:xdist", "-p", "no:randomly"]
# pytest rc 5 = "no tests collected" — a file whose every test is
# deselected by `-m 'not slow'` is a pass, not a failure
OK_RCS = (0, 5)


def test_files(tests_dir: str):
    return sorted(os.path.basename(p)
                  for p in glob.glob(os.path.join(tests_dir, "test_*.py")))


def _marker(d: str, key: str) -> str:
    return os.path.join(d, f"done.{key}.json")


def run_worker(d: str, tests_dir: str, worker: str,
               lease_timeout: float = 600.0) -> None:
    """Pull file leases until the domain is drained.  One full sweep
    with no acquirable free file ends the worker (files leased by a
    LIVE sibling are its problem; files with markers are done)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    while True:
        progressed = pending = False
        for key in test_files(tests_dir):
            if os.path.exists(_marker(d, key)):
                continue
            # a crashed sibling's lease frees after lease_timeout (no
            # kill: its pytest child died with it)
            leaselib.expire_lease(d, key, lease_timeout, kill=False)
            rec = leaselib.try_acquire(d, key, worker)
            if rec is None:
                pending = True                   # leased by a sibling
                continue
            t0 = time.monotonic()
            proc = subprocess.run(
                [sys.executable, "-m", "pytest",
                 os.path.join(tests_dir, key)] + PYTEST_FLAGS,
                env=env, cwd=_REPO, capture_output=True, text=True)
            write_json_exclusive(_marker(d, key), {
                "file": key, "rc": proc.returncode, "worker": worker,
                "elapsed_s": round(time.monotonic() - t0, 1),
                "tail": proc.stdout[-2000:]})
            leaselib.release(d, key, rec)
            progressed = True
        if not progressed and not pending:
            return
        if not progressed:
            time.sleep(1.0)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--workers", "-n", type=int, default=2,
                    help="worker processes pulling file leases [2]")
    ap.add_argument("--dir", default=None,
                    help="shared lease-domain directory (default: a "
                         "fresh temp dir; set it to a shared mount to "
                         "shard across machines)")
    ap.add_argument("--tests", default=os.path.join(_REPO, "tests"))
    ap.add_argument("--worker-name", default=None,
                    help=argparse.SUPPRESS)   # internal: child mode
    a = ap.parse_args(argv)

    if a.worker_name:                         # child: pull until drained
        run_worker(a.dir, a.tests, a.worker_name)
        return 0

    own_tmp = None
    d = a.dir
    if d is None:
        own_tmp = tempfile.TemporaryDirectory(prefix="tier1_shard.")
        d = own_tmp.name
    os.makedirs(d, exist_ok=True)
    t0 = time.monotonic()
    kids = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--dir", d,
         "--tests", a.tests, "--worker-name", f"w{k}"])
        for k in range(max(1, a.workers))]
    for p in kids:
        p.wait()
    wall = time.monotonic() - t0

    results = []
    for key in test_files(a.tests):
        try:
            with open(_marker(d, key)) as f:
                results.append(json.load(f))
        except (OSError, ValueError):
            results.append({"file": key, "rc": None, "worker": None})
    bad = [r for r in results if r["rc"] not in OK_RCS]
    serial = sum(r.get("elapsed_s") or 0 for r in results)
    for r in sorted(results, key=lambda r: -(r.get("elapsed_s") or 0)):
        mark = "ok " if r["rc"] in OK_RCS else "FAIL"
        print(f"  {mark} {r['file']:<36} {r.get('elapsed_s') or '?':>7}s"
              f"  [{r.get('worker')}]")
    print(f"tier1-shard: {len(results) - len(bad)}/{len(results)} files"
          f" ok, {a.workers} workers, wall {wall:.0f}s"
          f" (serial-equivalent {serial:.0f}s)")
    for r in bad:
        print(f"  FAILED {r['file']} rc={r['rc']}\n{r.get('tail', '')}",
              file=sys.stderr)
    if own_tmp:
        own_tmp.cleanup()
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
