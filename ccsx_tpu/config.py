"""Configuration for ccsx_tpu.

All parity-critical constants of the reference are collected here with their
source citations (reference = /root/reference, catalogued in SURVEY.md §2.5).
TPU-specific knobs (buckets, band widths, microbatch sizes) are grouped at the
bottom; they control tiling only, never semantics.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class AlignParams:
    """Alignment scoring parameters.

    Defaults mirror the BSPOA parameters the reference wires up at
    main.c:841-850 (M=2 X=-6 O=-3 E=-2, bandwidth=128).  The reference's
    second affine channel is disabled there (Q=P=0), so we model a single
    affine gap.
    """

    match: int = 2
    mismatch: int = -6
    gap_open: int = -3     # charged on the first gap base *in addition* to gap_extend
    gap_extend: int = -2
    band: int = 128        # main.c:849 bandwidth=128 == TPU lane width


@dataclasses.dataclass
class CcsConfig:
    # ---- CLI-equivalent options (reference main.c:751-800) ----
    min_subread_len: int = 5000        # -m, main.c:753
    max_subread_len: int = 500000      # -M, main.c:753
    min_fulllen_count: int = 3         # -c (>=3 enforced, main.c:786-789);
    #   a hole is kept iff its subread count >= min_fulllen_count + 2 (main.c:659)
    split_subread: bool = True         # default shred mode; -P selects whole-read (main.c:754,766)
    is_bam: bool = True                # -A selects FASTA/Q (main.c:770)
    exclude_holes: Optional[frozenset] = None   # -X comma list (main.c:772-783)
    threads: int = 1                   # -j host-side worker threads (main.c:754)
    verbose: int = 0                   # -v repeatable (main.c:791-793)

    # ---- prepare / orientation (main.c:116-453) ----
    group_tolerance_pct: int = 10      # length-cluster tolerance (main.c:350)
    strand_identity_pct: int = 75      # strand_match accept identity (main.c:392)
    border_identity_pct: int = 70      # template border RC check (main.c:326,332)
    border_len: int = 1000             # border length for template check (main.c:324)
    border_min_template: int = 2000    # candidate median len must exceed (main.c:320)
    # candidate group must have >= 2 members and size*5 >= 4*size(best) (main.c:312-313)

    # ---- windowed consensus (ccs_for2, main.c:541-546) ----
    bp_window: int = 10                # breakpoint window: consecutive MSA cols
    bp_minwin: int = 5                 # min consensus-base cols in the window
    bp_rowrate: int = 80               # per-row agreement %, main.c:541
    bp_colrate: int = 80               # per-col agreement % (60 if <10 passes, main.c:546)
    bp_colrate_lowpass: int = 60
    window_init: int = 2048            # reference initlen=2000; we round to a lane
    window_add: int = 2048             # reference addlen=2000
    window_minlen: int = 1024          # reference minlen=1000: min tail beyond window
    max_window: int = 8192             # growth cap before force-flush (TPU memory bound)
    window_growth: str = "flush"       # at max_window: "flush" force-flushes a
    #   breakpoint (bounded shapes; documented delta), "grow" keeps growing like
    #   the reference's unbounded window (main.c:550,613-616) — geometric length
    #   buckets keep the compile count logarithmic, so parity mode stays viable

    # ---- consensus redesign knobs (no reference equivalent) ----
    refine_iters: int = 2              # realign-to-draft refinement rounds;
    #   intermediate rounds use liberal-insert/strict-delete (ops/msa.py)
    max_ins_per_col: int = 4           # inserted bases stored per (pass, template col)

    # ---- per-base quality output (extension; the reference writes FASTA
    #      only, main.c:714 — no qualities exist to compare against) ----
    emit_quality: bool = False         # CLI --fastq: write FASTQ with
    #   vote-margin Phred qualities (star.RoundResult.materialize_with_qual)
    bam_out: bool = False              # CLI --bam: unaligned BAM output with
    #   qual fields filled (implies emit_quality) + an rq aux tag
    # Coverage-conditioned vote-margin QV: Q = qv_base + qv_per_support*s
    # - qv_per_dissent*d for a column with s supporting / d dissenting
    # passes.  A dissenting pass is far stronger evidence of a real
    # ambiguity than a missing supporter (measured per-(s,d) error on the
    # synthetic pass distribution, r4: one dissent costs ~8 Q at fixed
    # support while each supporter adds ~3) — a single net-vote slope
    # cannot express both, which produced the r3 mid-range calibration
    # dip (quality_r03.json: predicted [15,20) observed worse than
    # [10,15)).
    qv_base: float = 8.0
    qv_per_support: float = 3.0
    qv_per_dissent: float = 6.0
    # The support slope flattens past qv_knee supporters: residual
    # consensus errors at moderate+ coverage are dominated by correlated
    # effects (homopolymer indels, window stitching) that extra coverage
    # does not vote away — the measured unanimous-column error plateaus
    # near Q27-28 at s=6-7 instead of following the low-coverage slope.
    # Past the knee each supporter adds qv_per_support_tail.  Unanimous
    # s=16 predicts Q34, tracking the measured Q37@16 (BASELINE.md);
    # the full coefficient fit is the r4 per-(s,d) error study — these
    # values give a 9/9-bin monotone calibration table at 5-Q
    # granularity, observed error conservative in every bin.
    qv_knee: int = 5
    qv_per_support_tail: float = 1.0
    # Homopolymer-run penalty: a consensus base inside a length-R run
    # loses qv_per_hp * min(R-1, qv_hp_cap) Q.  Fitted to the r5
    # correlated-error study (benchmarks/quality.py, hp_factor=0.6
    # hp_ins_same=0.7): at fixed predicted Q, observed Q drops ~6-9 per
    # run unit because homopolymer indels are CORRELATED across passes
    # — unanimous columns in long runs can be unanimously wrong, which
    # vote margins cannot see.  The cap reflects the measured flattening
    # past run ~5.  Under i.i.d. errors the penalty is merely
    # conservative (hp columns are no worse there); under realistic
    # correlated errors it is what keeps the calibration monotone.
    qv_per_hp: float = 7.0
    qv_hp_cap: int = 4
    qv_cap: int = 60                   # quality ceiling (vote margins with
    #   <=64 passes justify no more)

    # ---- alignment scoring ----
    align: AlignParams = dataclasses.field(default_factory=AlignParams)

    # ---- pipeline (worker_pipeline, main.c:649-720) ----
    chunk_size: int = 1024             # main.c:833; grows x4 to cap (main.c:686-691)
    chunk_growth: int = 4
    chunk_cap: int = 16384

    # ---- TPU tiling ----
    pass_buckets: tuple = (4, 8, 16, 32)   # passes padded to the next bucket
    #   (request/tensor shapes, the per-hole path, the mesh path, and the
    #   --pass-buckets bucketed A/B control; the packed batched path
    #   strips this padding back off before dispatch)
    pass_packing: bool = True          # batched pipeline: pack (hole, pass)
    #   rows into fixed (slab_rows, qmax) slabs (pipeline/pack.py) instead
    #   of grouping by pass bucket — kills pass-bucket and partial-Z
    #   padding at byte-identical output.  CLI --pass-buckets selects the
    #   bucketed control; a device mesh also keeps the bucketed layout
    max_passes: int = 32               # extra passes beyond this are dropped (deepest
    #   passes add negligible consensus signal; reference keeps all — documented delta)
    slab_rows: int = 128               # packed-slab row budget (power of two;
    #   the Z-bucket analog for packed dispatches)
    slab_shape_ladder: int = 2         # canonical tail-slab heights per
    #   (qmax, tmax, iters) group: budget >> k for k < ladder (CLI
    #   --slab-shape-ladder).  Bounds a packed group to <= ladder XLA
    #   programs ever (the r7 flight recorder caught the finer budget/8
    #   ladder paying 4-5 compiles per group); 1 = every slab dispatches
    #   at the full budget
    warmup_compile: bool = True        # AOT warmup precompiler (pipeline/
    #   warmup.py): a background thread compiles each packed group's
    #   canonical executables as soon as prep predicts them, overlapping
    #   cold compiles with ingest instead of stalling the first dispatch
    #   of every shape.  CLI --no-warmup disables
    zmw_microbatch: int = 64           # ZMWs per device dispatch; also the
    #   ADAPTIVE admission-window cap of the batched driver: without an
    #   explicit --inflight the window starts at cap/chunk_growth^2 and
    #   multiplies by chunk_growth per filled admission round — the
    #   reference's 1024 -> x4 -> 16384 policy (main.c:686-691) scaled
    prep_threads: Optional[int] = None  # overlapped prep plane (pipeline/
    #   prep_pool.py): background threads that ingest + run the
    #   orientation walk ahead of the admission window, feeding the
    #   batched driver through a ready queue so host prep overlaps
    #   device compute instead of adding to it.  None = auto-size to
    #   the host; 0 = the old inline behavior (CLI --prep-threads).
    #   Output bytes are identical either way
    # ---- pre-alignment plane (ops/sketch.py + ops/seed_device.py;
    #      ROADMAP item 4: the RASSA/SeGraM filter-before-DP lineage) ----
    prefilter: bool = True             # CLI --prefilter {on,off}: a
    #   batched device screen scores every wave of strand_match pair
    #   candidates (capped k-mer hits + best diagonal-window votes,
    #   bit-equal to the host seed gate's statistics) and rejects
    #   hopeless pairings BEFORE the banded DP — the long-template
    #   regime's dominant waste (a wrong-strand 100kb pair passes the
    #   legacy votes>=3 gate essentially always and pays a multi-second
    #   doomed DP).  Rejection is conservative (ops/sketch.py rules:
    #   seed-gate parity, margin-analyzed noise gate, provable band-
    #   overlap geometry); output bytes are identical on/off (pinned).
    #   On also lets the orientation walk speculate fwd+RC strand pairs
    #   as ONE batch (prepare.PairBatch) — the hopeless arm dies in the
    #   screen, halving the walk's sequential pair waves
    seed_device_min_t: int = 16384     # CLI --seed-device-min-t: the
    #   host/device seeding crossover — pairs whose template is at
    #   least this long take the batched device k-mer seeder
    #   (ops/seed_device.py, bit-equal to ops/seed.seed_diagonal);
    #   shorter ones keep the host sort-join with its per-template
    #   index cache.  0 disables device seeding entirely.  Purely a
    #   performance routing knob — either path yields the same hint
    len_bucket_quant: int = 512        # whole-read mode: lengths padded to multiple

    # ---- device/mesh ----
    device: str = "auto"               # {auto, tpu, cpu}
    banded_impl: str = ""              # CLI --banded-impl: banded DP-fill
    #   implementation {scan, pallas, rotband}; "" = scan (the spec).
    #   All three are bit-identical (consensus/star.banded_impl docstring
    #   has the promotion protocol) — a pure performance A/B knob, so it
    #   rides fingerprint._NON_SEMANTIC
    mesh_shape: Optional[tuple] = None  # (data, pass) for the batched
    #   pipeline's device mesh, e.g. (4, 2); (D,) means (D, 1); None =
    #   all local devices on the data axis (CLI: --mesh D,P)

    # ---- observability (SURVEY.md §5.1/5.5: absent in the reference) ----
    metrics_path: Optional[str] = None  # JSON-lines metrics events
    trace_path: Optional[str] = None    # CLI --trace: dispatch flight
    #   recorder (utils/trace.py) — span JSONL + Chrome trace export,
    #   forced-execution device spans, per-shape-group compile/execute
    #   attribution merged into every metrics event
    stall_timeout_s: float = 120.0      # CLI --stall-timeout: the hang
    #   watchdog fires when a device-dispatch span stays open this long,
    #   dumping thread stacks + the in-flight shape group (0 disables)
    # ---- resilient execution (pipeline/resilience.py; the reference
    #      has no failure story at all beyond abort-or-soldier-on) ----
    dispatch_deadline_s: float = 0.0    # CLI --dispatch-deadline: bound
    #   every device dispatch/materialize wait; on expiry the wedged
    #   call is abandoned (thread parked, result discarded) and the
    #   group replays on the bit-exact host path.  First call of each
    #   (group, phase) gets the compile grace (x10, like the stall
    #   watchdog).  0 = off: a hung dispatch stalls the run forever
    #   (the watchdog observes but never kills — today's behavior)
    breaker_strikes: int = 3            # CLI --breaker-strikes: device
    #   failures (hangs, OOM ladder-bottoms, compile failures) within
    #   breaker_window_s that trip the circuit breaker open — remaining
    #   work runs on the host path.  0 disables the breaker
    breaker_window_s: float = 60.0      # strike-counting window
    breaker_probe_s: float = 0.0        # CLI --breaker-probe-s: half-
    #   open re-probe interval for a tripped breaker (one group is
    #   dispatched as a probe; success closes the breaker).  0 = a
    #   tripped breaker stays open for the rest of the run
    # ---- hostile-input ingest plane (io/corruption.py) ----
    salvage: bool = False              # CLI --salvage: classified input
    #   corruption (torn BGZF blocks, corrupt records, bad names,
    #   truncated FASTQ — the pinned taxonomy) is booked + RESYNCED
    #   past instead of killing the run: BGZF scans for the next valid
    #   block header, BAM scans for the next plausible record, FASTA/Q
    #   re-anchors on the next '>'/'@' line.  Off (default) = the
    #   historical fail-fast rc-1, byte-identical.  Corrupt events
    #   count into holes_corrupt, mark the run degraded, and feed the
    #   --max-failed-holes budget
    max_record_bytes: int = 256 * 1024 * 1024  # CLI --max-record-bytes:
    #   allocation bound on one BAM alignment record, enforced BEFORE
    #   allocating — a corrupt int32 length must not drive a multi-GB
    #   allocation (both reader stacks, salvage on or off)
    max_failed_holes: Optional[float] = None  # CLI --max-failed-holes:
    #   quarantine budget — an integer count (>= 0, checked per
    #   failure) or a fraction of processed holes in (0, 1) (checked at
    #   end of run / against a known total).  Exceeding it aborts with
    #   rc 2 (exitcodes.RC_FAILED_HOLES) instead of emitting a
    #   near-empty output at rc 0.  None = unbounded (historical)
    telemetry_port: int = 0             # CLI --telemetry-port: live
    #   telemetry endpoints (utils/telemetry.py — GET /metrics
    #   Prometheus text, /healthz ok|degraded, /progress JSON) served
    #   by a daemon thread for the run's duration.  0 = off (default);
    #   the port auto-bumps upward when taken, and sharded runs offset
    #   it per rank (parallel/distributed.py) so every rank is
    #   scrapeable — `ccsx-tpu top` aggregates them

    def metrics_stream(self):
        return open(self.metrics_path, "a") if self.metrics_path else None

    def __post_init__(self):
        if self.min_fulllen_count < 3:
            raise ValueError(
                f"min fulllen count={self.min_fulllen_count} (>=3)!"  # main.c:787
            )

    @property
    def min_pass_count(self) -> int:
        """A hole is kept iff subread count >= this (main.c:659)."""
        return self.min_fulllen_count + 2

    @property
    def qv_coeffs(self) -> tuple:
        """(base, per_support, per_dissent, knee, per_support_tail,
        per_hp, hp_cap) for materialize_with_qual."""
        return (self.qv_base, self.qv_per_support, self.qv_per_dissent,
                self.qv_knee, self.qv_per_support_tail,
                self.qv_per_hp, self.qv_hp_cap)
