"""Known-bad twin for the int32-overflow checker: BOTH historical
wrap expressions, verbatim.  Never imported — parsed only."""

import jax.numpy as jnp


def _line_interp_pre_r11(ip, span, denom):
    # the r11 bug: ip*span exceeds 2**31 past ~47kb templates and the
    # traced int32 product wraps silently, truncating the band
    return ip * span // denom


def compute_offsets_pre_r14(i, li0, lj0, li1, lj1):
    # the r14 twin: compute_offsets re-derived the same interpolation
    # instead of importing the fixed _line_interp
    nom_j = lj0 + (i - li0) * (lj1 - lj0) // jnp.maximum(li1 - li0, 1)
    return nom_j


def pack_key(hole_id, bits):
    # traced value shifted by a traced amount: same silent wrap
    return hole_id << bits
