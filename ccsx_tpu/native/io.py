"""ctypes wrappers over the native IO library.

Same Python-facing types as the fallback parsers (FastxRecord, Zmw), so the
pipeline can switch between paths transparently.  The native streamer does
the record parse, group-by-hole, and count/length filters in C++
(seqio.h:152-201, main.c:659-672 semantics); the rare hole-exclusion check
(-X) stays here.
"""

from __future__ import annotations

import ctypes
from typing import Iterator, Optional

import numpy as np

from ccsx_tpu.config import CcsConfig
from ccsx_tpu.io.corruption import CorruptionError
from ccsx_tpu.io.fastx import FastxRecord
from ccsx_tpu.io.zmw import InvalidZmwName, Zmw
from ccsx_tpu import native
from ccsx_tpu.utils import trace


class NativeStreamError(CorruptionError):
    """Stream error surfaced by the native reader, carrying the stable
    taxonomy code the C++ side classified it with (io/corruption.py)."""

    def __init__(self, msg: str, reason: str = "bam_bad_record"):
        super().__init__(reason or "bam_bad_record", msg)


def salvage_supported() -> bool:
    """True when the loaded native library exports the salvage entry
    points (a stale prebuilt .so degrades to the Python salvage
    readers, never to a load failure)."""
    L = native.lib()
    return L is not None and hasattr(L, "ccsx_set_salvage") \
        and hasattr(L, "ccsx_prefetch_open_s")


def _reason(L, h, fn_name: str) -> str:
    fn = getattr(L, fn_name, None)
    if fn is None:
        return ""
    val = fn(h)
    return val.decode() if val else ""


def _open(path: str, is_bam: bool):
    L = native.lib()
    if L is None:
        raise RuntimeError("native IO library unavailable")
    h = L.ccsx_open(path.encode(), 1 if is_bam else 0)
    if not h:
        raise OSError(f"cannot open {path!r}")
    return L, h


def read_records_native(path: str, is_bam: bool) -> Iterator[FastxRecord]:
    """Record-level stream (FASTA/Q or BAM) through the native parser."""
    L, h = _open(path, is_bam)
    c = ctypes
    name, comment = c.c_char_p(), c.c_char_p()
    seq, qual = c.POINTER(c.c_uint8)(), c.POINTER(c.c_uint8)()
    seq_len, qual_len = c.c_int64(), c.c_int64()
    try:
        while True:
            rc = L.ccsx_next_record(h, c.byref(name), c.byref(comment),
                                    c.byref(seq), c.byref(seq_len),
                                    c.byref(qual), c.byref(qual_len))
            if rc == 0:
                return
            if rc < 0:
                raise NativeStreamError(L.ccsx_error(h).decode())
            s = c.string_at(seq, seq_len.value)
            q = (c.string_at(qual, qual_len.value)
                 if qual_len.value >= 0 else None)
            yield FastxRecord(
                name=name.value.decode(),
                comment=comment.value.decode(),
                seq=s, qual=q)
    finally:
        L.ccsx_close(h)


def stream_zmws_native(path: str, cfg: CcsConfig,
                       metrics=None) -> Iterator[Zmw]:
    """Filtered ZMW stream through the native group-by-hole streamer.

    Opens eagerly — a bad path raises OSError here, not at first next().
    """
    L, h = _open(path, cfg.is_bam)
    L.ccsx_set_filter(h, cfg.min_pass_count, cfg.min_subread_len,
                      cfg.max_subread_len)
    if hasattr(L, "ccsx_set_salvage"):
        # the --max-record-bytes allocation bound applies salvage ON OR
        # OFF; on=1 additionally enables the resync behavior
        L.ccsx_set_salvage(h, 1 if getattr(cfg, "salvage", False) else 0,
                           getattr(cfg, "max_record_bytes", 0) or 0)
    return _zmw_gen(h, cfg, L.ccsx_next_zmw, L.ccsx_error, L.ccsx_close,
                    counts_fn=getattr(L, "ccsx_filter_counts", None),
                    metrics=metrics, reason_fn_name="ccsx_error_reason",
                    corrupt_fns=("ccsx_corrupt_events",
                                 "ccsx_corrupt_summary"))


def _surface_filter_counts(h, counts_fn, excluded: int, metrics) -> None:
    """At stream EOF, fold the native reader's in-library filter counts
    (plus the Python-side -X exclusions) into Metrics — the native path
    used to report nothing, silently under-reporting filtering in every
    traced native run (the span-table blind spot ARCHITECTURE.md
    documents).  A zero-filter stream books nothing."""
    buckets = {}
    if counts_fn is not None:
        few = ctypes.c_int64()
        short = ctypes.c_int64()
        long_ = ctypes.c_int64()
        counts_fn(h, ctypes.byref(few), ctypes.byref(short),
                  ctypes.byref(long_))
        buckets = {"few_passes": few.value, "too_short": short.value,
                   "too_long": long_.value}
    if excluded:
        buckets["excluded"] = excluded
    buckets = {k: v for k, v in buckets.items() if v}
    if not buckets:
        return
    total = sum(buckets.values())
    if metrics is not None:
        metrics.holes_filtered += total
        for k, v in buckets.items():
            metrics.filtered_reasons[k] = (
                metrics.filtered_reasons.get(k, 0) + v)
    # one aggregate instant (the native reader has no per-hole
    # identity to report), so a trace of a native run still shows that
    # — and why — holes were dropped
    trace.instant("zmw_filtered_native", cat="ingest", holes=total,
                  **buckets)


def _surface_corrupt_counts(L, h, summary_fn_name: str, metrics,
                            prebooked: dict) -> None:
    """At stream EOF, fold the native salvage accounting's per-reason
    buckets into Metrics (the live event total was already polled per
    yield — the full reason breakdown waits for EOF, where the C side
    can summarize it race-free).  ``prebooked`` holds reasons already
    booked live (the budget-exempt ones, polled via their own atomic so
    --max-failed-holes math stays exact mid-stream) — subtracted here
    so they are not double-counted."""
    summary = _reason(L, h, summary_fn_name)
    if not summary or metrics is None:
        return
    with metrics._count_lock:
        for item in summary.split(","):
            reason, _, count = item.partition(":")
            if reason and count:
                n = int(count) - prebooked.get(reason, 0)
                if n:
                    metrics.corrupt_reasons[reason] = (
                        metrics.corrupt_reasons.get(reason, 0) + n)


def _zmw_gen(h, cfg: CcsConfig, next_fn, error_fn, close_fn,
             counts_fn=None, metrics=None, reason_fn_name="",
             corrupt_fns=(None, None)) -> Iterator[Zmw]:
    """Shared drain loop for both native streamers (plain and prefetching)."""
    c = ctypes
    L = native.lib()
    movie, hole = c.c_char_p(), c.c_char_p()
    seqs = c.POINTER(c.c_uint8)()
    total = c.c_int64()
    lens = c.POINTER(c.c_int32)()
    n = c.c_int32()
    excluded = 0
    events_fn = getattr(L, corrupt_fns[0], None) \
        if getattr(cfg, "salvage", False) and corrupt_fns[0] else None
    exempt_fn = getattr(L, corrupt_fns[0].replace("_events", "_exempt"),
                        None) if events_fn is not None else None
    corrupt_seen = 0
    exempt_seen = 0

    def poll_corrupt():
        # live salvage accounting: the event total is an atomic the C
        # side bumps as it classifies; full per-reason buckets land at
        # EOF.  Budget-EXEMPT events (bgzf_missing_eof) ride their own
        # atomic and are booked into corrupt_reasons immediately, so a
        # --max-failed-holes check on holes yielded after the event
        # cannot misread a zero-loss degradation as a lost hole
        nonlocal corrupt_seen, exempt_seen
        if events_fn is None:
            return
        ev = int(events_fn(h))
        ex = int(exempt_fn(h)) if exempt_fn is not None else 0
        if ev > corrupt_seen:
            if metrics is not None:
                metrics.bump(holes_corrupt=ev - corrupt_seen)
                if ex > exempt_seen:
                    with metrics._count_lock:
                        metrics.corrupt_reasons["bgzf_missing_eof"] = (
                            metrics.corrupt_reasons.get(
                                "bgzf_missing_eof", 0)
                            + (ex - exempt_seen))
                if not metrics.degraded:
                    metrics.degraded = "input corruption (salvaged)"
            corrupt_seen = ev
            exempt_seen = max(exempt_seen, ex)
    try:
        while True:
            rc = next_fn(h, c.byref(movie), c.byref(hole),
                         c.byref(seqs), c.byref(total),
                         c.byref(lens), c.byref(n))
            poll_corrupt()
            if rc == -1:
                _surface_filter_counts(h, counts_fn, excluded, metrics)
                if events_fn is not None and corrupt_fns[1]:
                    _surface_corrupt_counts(
                        L, h, corrupt_fns[1], metrics,
                        {"bgzf_missing_eof": exempt_seen})
                return
            if rc == -2:
                raise InvalidZmwName(error_fn(h).decode())
            if rc < 0:
                raise NativeStreamError(error_fn(h).decode(),
                                        _reason(L, h, reason_fn_name))
            hole_s = hole.value.decode()
            if cfg.exclude_holes and hole_s in cfg.exclude_holes:
                excluded += 1
                continue
            lens_np = np.ctypeslib.as_array(lens, shape=(n.value,)).copy()
            offs = np.zeros(n.value, dtype=np.int32)
            if n.value > 1:
                np.cumsum(lens_np[:-1], out=offs[1:])
            yield Zmw(
                movie=movie.value.decode(), hole=hole_s,
                seqs=c.string_at(seqs, total.value),
                lens=lens_np, offs=offs)
    finally:
        close_fn(h)


def stream_zmws_prefetch(path: str, cfg: CcsConfig,
                         queue_cap: int = 64,
                         metrics=None) -> Iterator[Zmw]:
    """Like stream_zmws_native, but parsing/grouping/filtering run on a
    background C++ thread feeding a bounded queue — the native read step of
    the 3-stage pipeline (kt_pipeline step 0, kthread.c:172-256).

    Opens eagerly — a bad path raises OSError here, not at first next().
    """
    L = native.lib()
    if L is None:
        raise RuntimeError("native IO library unavailable")
    if hasattr(L, "ccsx_prefetch_open_s"):
        # the salvage-capable open also carries the --max-record-bytes
        # bound, which applies salvage on or off
        h = L.ccsx_prefetch_open_s(
            path.encode(), 1 if cfg.is_bam else 0, cfg.min_pass_count,
            cfg.min_subread_len, cfg.max_subread_len, queue_cap,
            1 if getattr(cfg, "salvage", False) else 0,
            getattr(cfg, "max_record_bytes", 0) or 0)
    else:
        h = L.ccsx_prefetch_open(path.encode(), 1 if cfg.is_bam else 0,
                                 cfg.min_pass_count, cfg.min_subread_len,
                                 cfg.max_subread_len, queue_cap)
    if not h:
        raise OSError(f"cannot open {path!r}")
    return _zmw_gen(h, cfg, L.ccsx_prefetch_next, L.ccsx_prefetch_error,
                    L.ccsx_prefetch_close,
                    counts_fn=getattr(L, "ccsx_prefetch_filter_counts",
                                      None),
                    metrics=metrics,
                    reason_fn_name="ccsx_prefetch_error_reason",
                    corrupt_fns=("ccsx_prefetch_corrupt_events",
                                 "ccsx_prefetch_corrupt_summary"))


class NativeFastaWriter:
    """Async ordered FASTA writer: fwrite runs on a C++ thread off the GIL.

    Records appear in put() order (single consumer thread drains a FIFO),
    matching the reference's ordered write step (main.c:707-718).
    """

    def __init__(self, path: str, append: bool = False):
        L = native.lib()
        if L is None:
            raise RuntimeError("native IO library unavailable")
        self._L = L
        self._h = L.ccsx_writer_open(path.encode(), 1 if append else 0)
        if not self._h:
            raise OSError(f"cannot open {path!r} for write")

    def put(self, name: str, seq: bytes, qual: bytes | None = None) -> None:
        """FASTA record, or FASTQ when ``qual`` (phred+33 ASCII, same
        length as seq) is given."""
        if not self._h:
            raise ValueError("writer is closed")
        if qual is not None and len(qual) != len(seq):
            # the C side appends len(qual) bytes from BOTH buffers; a
            # mismatch must fail here, not as a native over-read
            raise ValueError(
                f"qual length {len(qual)} != seq length {len(seq)}")
        if qual is None:
            rc = self._L.ccsx_writer_put_fasta(
                self._h, name.encode(),
                ctypes.cast(ctypes.c_char_p(seq),
                            ctypes.POINTER(ctypes.c_uint8)), len(seq))
        else:
            rc = self._L.ccsx_writer_put_fastq(
                self._h, name.encode(),
                ctypes.cast(ctypes.c_char_p(seq),
                            ctypes.POINTER(ctypes.c_uint8)),
                ctypes.cast(ctypes.c_char_p(qual),
                            ctypes.POINTER(ctypes.c_uint8)), len(qual))
        if rc != 0:
            raise OSError("write failed")

    def close(self) -> None:
        if self._h:
            rc = self._L.ccsx_writer_close(self._h)
            self._h = None
            if rc != 0:
                raise OSError("write failed")


def encode_native(seq: bytes) -> Optional[np.ndarray]:
    L = native.lib()
    if L is None:
        return None
    n = len(seq)
    out = np.empty(n, dtype=np.uint8)
    L.ccsx_encode(
        ctypes.cast(ctypes.c_char_p(seq), ctypes.POINTER(ctypes.c_uint8)),
        n, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    return out


def revcomp_codes_native(codes: np.ndarray) -> Optional[np.ndarray]:
    L = native.lib()
    if L is None:
        return None
    codes = np.ascontiguousarray(codes, dtype=np.uint8)
    out = np.empty(len(codes), dtype=np.uint8)
    L.ccsx_revcomp_codes(
        codes.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        len(codes), out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    return out
