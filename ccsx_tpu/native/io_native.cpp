// ccsx_tpu native IO: gzip-streamed FASTA/FASTQ + BAM readers and the
// ZMW group-by-hole streamer, as a C shared library consumed via ctypes.
//
// This is the [NATIVE] L1 of the framework (SURVEY.md §7.1 io_native),
// re-implementing the semantics of the reference's IO stack:
//   * FASTA/FASTQ state machine  — kseq.h:177-218 (records at '>'/'@',
//     multi-line seq, '+' quality section read until length match);
//   * BAM record walk            — bamlite.c:78-165 (BAM-through-gzip,
//     magic+header parse, record parse, 4-bit nibble seq decode via the
//     =ACMGRSVTWYHKDBN table bamlite.h:86/seqio.h:92, qual phred+33
//     clamped at 126 seqio.h:113);
//   * ZMW group-by-hole streamer — seqio.h:152-201 (name split on '/'
//     expecting movie/hole/region, consecutive same-hole records
//     concatenated, one-record lookahead carry);
//   * read-step filters          — main.c:659-672 (min pass count, total
//     length bounds); hole exclusion stays host-side (tiny set, rare).
//   * 2-bit encode / reverse-complement tables — main.c:222-241,
//     seqio.h:120-148.
//
// Ownership: all pointers returned through the API reference buffers owned
// by the reader handle and are valid until the next next_* call on that
// handle. The Python wrapper copies them out immediately.

#include <zlib.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr int64_t kBufSize = 1 << 16;

// ---- corruption taxonomy + salvage accounting ----------------------------
//
// Mirrors io/corruption.py: the reason codes, the allocation bound, the
// BGZF block-resync rules, and the plausible-record scan are a shared
// contract — the differential fuzz tests (tests/test_fuzz_ingest.py)
// hold the two stacks to the same classification and the same salvaged
// record set on the same mutant.

constexpr int64_t kDefaultMaxRecordBytes = 256LL * 1024 * 1024;
constexpr int64_t kMinRecordBlock = 34;     // 32 fixed + 2-byte name
constexpr int64_t kScanLookahead = 4 + 32 + 255;

struct Salvage {
  bool on = false;
  int64_t max_record_bytes = kDefaultMaxRecordBytes;
  // events/exempt are read live across the ctypes boundary (the
  // prefetch consumer polls while the producer parses) — atomic; the
  // full reason buckets are only summarized after EOF.  exempt counts
  // the budget-exempt reasons (corruption.NON_BUDGET_REASONS — today
  // only bgzf_missing_eof) so a --max-failed-holes check on holes
  // yielded AFTER the event but BEFORE EOF cannot misread a zero-loss
  // degradation as a lost hole.
  std::atomic<int64_t> events{0};
  std::atomic<int64_t> exempt{0};
  std::map<std::string, int64_t> counts;
  std::string summary;   // built by build_summary(), owned here

  void record(const char* reason) {
    events.fetch_add(1, std::memory_order_relaxed);
    if (std::strcmp(reason, "bgzf_missing_eof") == 0)
      exempt.fetch_add(1, std::memory_order_relaxed);
    counts[reason]++;
  }
  const char* build_summary() {
    summary.clear();
    for (const auto& kv : counts) {
      if (!summary.empty()) summary.push_back(',');
      summary += kv.first;
      summary.push_back(':');
      summary += std::to_string(kv.second);
    }
    return summary.c_str();
  }
};

// ---- decode tables -------------------------------------------------------

// 4-bit BAM code -> ASCII (bamlite.h:86, seqio.h:92)
const char kNt16[] = "=ACMGRSVTWYHKDBN";

struct Tables {
  uint8_t enc[256];      // ASCII -> 0..3 base, 4 other
  uint8_t comp[256];     // ASCII complement (seqio.h:120-137)
  uint8_t nib[256][2];   // packed byte -> two ASCII bases
  Tables() {
    for (int i = 0; i < 256; i++) enc[i] = 4;
    const char* b = "ACGT";
    for (int i = 0; i < 4; i++) {
      enc[(uint8_t)b[i]] = (uint8_t)i;
      enc[(uint8_t)(b[i] + 32)] = (uint8_t)i;
    }
    for (int i = 0; i < 256; i++) comp[i] = (uint8_t)i;
    const char* from = "ACGTacgtUuNn";
    const char* to = "TGCAtgcaAaNn";
    for (int i = 0; from[i]; i++) comp[(uint8_t)from[i]] = (uint8_t)to[i];
    for (int i = 0; i < 256; i++) {
      nib[i][0] = (uint8_t)kNt16[i >> 4];
      nib[i][1] = (uint8_t)kNt16[i & 0xF];
    }
  }
};
const Tables kT;

// ---- BGZF block-parallel inflate -----------------------------------------
//
// Real subreads.bam files are BGZF: gzip members <=64KB each carrying a
// "BC" extra subfield with the compressed block size.  The reference reads
// them as one sequential gzip stream (bamlite.h:13-19 uses the plain gz
// API), which caps ingest at single-thread inflate speed — SURVEY.md §7.3
// item 6 flags multithreaded BGZF inflate as load-bearing for the 8x
// target.  This reader parses block boundaries from the BC field, hands
// whole compressed members to a worker pool, and delivers decompressed
// blocks in file order.  Threads: CCSX_BGZF_THREADS or
// hardware_concurrency clamped to [1, 8]; at 1, inflate runs inline
// (no pool) so single-core machines pay no synchronization.

struct BgzfMT {
  struct Job {
    std::vector<uint8_t> comp;   // raw deflate payload (no hdr/crc)
    std::vector<uint8_t> out;
    uint32_t crc = 0, isize = 0;
    bool done = false, bad = false;
    bool gap_before = false;     // salvage: dropped bytes precede this
  };

  FILE* f = nullptr;
  bool raw_eof = false, err = false;
  const char* err_reason = nullptr;  // taxonomy code for err (fail-fast)
  bool last_was_eof_marker = false;  // saw the 28-byte empty EOF block
  Salvage* sv = nullptr;             // non-null + on = salvage mode
  long file_size = -1;               // computed lazily for salvage scans
  bool gap_pending = false;          // skipped data since the last job
  bool pending_gap_out = false;      // job dropped at delivery time
  bool end_counted = false;          // torn-tail end event booked once
  int nthreads = 1;
  size_t depth = 64;                         // blocks in flight
  std::deque<std::shared_ptr<Job>> order;    // file order
  std::deque<std::shared_ptr<Job>> queue;    // pending work
  std::mutex mu;
  std::condition_variable cv_work, cv_done;
  std::vector<std::thread> workers;
  bool shutdown = false;

  static int env_threads() {
    const char* e = getenv("CCSX_BGZF_THREADS");
    // clamp explicit values too: an absurd count would throw
    // std::system_error from thread creation with no handler across
    // the ctypes boundary (std::terminate).  64 is far above any
    // useful inflate parallelism but far below failure territory,
    // so legitimate big-host settings are honored
    if (e && *e) return std::min(std::max(1, atoi(e)), 64);
    unsigned hc = std::thread::hardware_concurrency();
    return hc > 1 ? (int)std::min(hc, 8u) : 1;
  }

  void open(FILE* file) {
    f = file;
    nthreads = env_threads();
    for (int i = 1; i < nthreads; i++)
      workers.emplace_back([this] { worker(); });
  }

  void close() {
    {
      std::lock_guard<std::mutex> lk(mu);
      shutdown = true;
    }
    cv_work.notify_all();
    for (auto& t : workers) t.join();
    workers.clear();
    if (f) { fclose(f); f = nullptr; }
  }

  static bool inflate_job(Job* j) {
    uint8_t scratch = 0;
    j->out.resize(j->isize);
    z_stream zs;
    std::memset(&zs, 0, sizeof zs);
    if (inflateInit2(&zs, -15) != Z_OK) return false;
    zs.next_in = j->comp.data();
    zs.avail_in = (uInt)j->comp.size();
    zs.next_out = j->isize ? j->out.data() : &scratch;
    zs.avail_out = j->isize ? (uInt)j->out.size() : 1;
    int rc = inflate(&zs, Z_FINISH);
    bool ok = rc == Z_STREAM_END && zs.total_out == j->isize;
    inflateEnd(&zs);
    if (ok && j->isize &&
        crc32(crc32(0, Z_NULL, 0), j->out.data(), (uInt)j->out.size())
            != j->crc)
      ok = false;
    return ok;
  }

  void worker() {
    std::unique_lock<std::mutex> lk(mu);
    for (;;) {
      cv_work.wait(lk, [this] { return shutdown || !queue.empty(); });
      if (queue.empty()) {
        if (shutdown) return;
        continue;
      }
      auto j = queue.front();
      queue.pop_front();
      lk.unlock();
      bool ok = inflate_job(j.get());
      lk.lock();
      j->bad = !ok;
      j->done = true;
      cv_done.notify_all();
    }
  }

  bool salv() const { return sv != nullptr && sv->on; }

  long fsize() {
    if (file_size < 0) {
      long cur = std::ftell(f);
      std::fseek(f, 0, SEEK_END);
      file_size = std::ftell(f);
      std::fseek(f, cur, SEEK_SET);
    }
    return file_size;
  }

  // salvage resync candidate check at `cand` (mirrors the Python
  // rescan in io/bam.py _bgzf_salvage_chunks: magic + FEXTRA + a BC
  // subfield whose BSIZE chains exactly to EOF or to another magic)
  bool try_candidate(long cand, long sz) {
    std::fseek(f, cand, SEEK_SET);
    uint8_t hdr[12];
    if (fread(hdr, 1, 12, f) != 12) return false;
    if (!(hdr[0] == 0x1f && hdr[1] == 0x8b && hdr[2] == 8 &&
          (hdr[3] & 4)))
      return false;
    uint16_t xlen = (uint16_t)(hdr[10] | (hdr[11] << 8));
    std::vector<uint8_t> extra(xlen);
    if (fread(extra.data(), 1, xlen, f) != xlen) return false;
    int64_t bsize = -1;
    for (size_t i = 0; i + 4 <= extra.size();) {
      uint16_t slen = (uint16_t)(extra[i + 2] | (extra[i + 3] << 8));
      if (extra[i] == 'B' && extra[i + 1] == 'C' && slen == 2 &&
          i + 6 <= extra.size()) {
        bsize = (extra[i + 4] | (extra[i + 5] << 8)) + 1;
        break;
      }
      i += 4 + slen;
    }
    if (bsize < (int64_t)(12 + xlen + 8)) return false;
    if (cand + bsize > sz) return false;
    if (cand + bsize == sz) return true;
    std::fseek(f, cand + bsize, SEEK_SET);
    uint8_t m[3];
    if (fread(m, 1, 3, f) != 3) return false;
    return m[0] == 0x1f && m[1] == 0x8b && m[2] == 8;
  }

  // scan forward from `from` for the next valid chained block header;
  // repositions f and returns true, or false when none remains
  bool rescan_from(long from) {
    long sz = fsize();
    uint8_t w[4096];
    for (long o = from; o + 12 <= sz;) {
      std::fseek(f, o, SEEK_SET);
      size_t n = fread(w, 1, sizeof w, f);
      if (n < 3) break;
      for (size_t i = 0; i + 3 <= n; i++) {
        if (w[i] == 0x1f && w[i + 1] == 0x8b && w[i + 2] == 0x08) {
          long cand = o + (long)i;
          if (try_candidate(cand, sz)) {
            std::fseek(f, cand, SEEK_SET);
            return true;
          }
        }
      }
      o += (long)(n >= 2 ? n - 2 : n);  // overlap: magic spans reads
    }
    return false;
  }

  // parse one raw BGZF member from f; null at EOF (err set on a
  // malformed header/truncation — or, in salvage mode, the damage is
  // classified, the stream resyncs on the next valid chained block
  // header, and the next job carries gap_before)
  std::shared_ptr<Job> read_raw() {
    for (;;) {
      long start = salv() ? std::ftell(f) : 0;
      uint8_t hdr[12];
      size_t n = fread(hdr, 1, 12, f);
      if (n == 0) { raw_eof = true; return nullptr; }
      bool hdr_ok = n == 12 && hdr[0] == 0x1f && hdr[1] == 0x8b &&
                    hdr[2] == 8 && (hdr[3] & 4);
      uint16_t xlen = 0;
      std::vector<uint8_t> extra;
      int64_t bsize = -1;
      if (hdr_ok) {
        xlen = (uint16_t)(hdr[10] | (hdr[11] << 8));
        extra.resize(xlen);
        if (fread(extra.data(), 1, xlen, f) != xlen) {
          hdr_ok = false;
        } else {
          for (size_t i = 0; i + 4 <= extra.size();) {
            uint16_t slen = (uint16_t)(extra[i + 2] | (extra[i + 3] << 8));
            if (extra[i] == 'B' && extra[i + 1] == 'C' && slen == 2 &&
                i + 6 <= extra.size()) {
              bsize = (extra[i + 4] | (extra[i + 5] << 8)) + 1;
              break;
            }
            i += 4 + slen;
          }
          if (bsize < (int64_t)(12 + xlen + 8)) hdr_ok = false;
        }
      }
      if (hdr_ok && salv() && start + bsize > fsize()) hdr_ok = false;
      if (!hdr_ok) {
        if (!salv()) {
          err = true; raw_eof = true;
          err_reason = n < 12 ? "bgzf_torn_tail" : "bgzf_bad_block";
          return nullptr;
        }
        // classification mirrors io/bam.py: fewer than a full fixed
        // header left (or a block running past EOF) = torn tail,
        // otherwise a damaged block header
        sv->record(n < 12 || (bsize >= (int64_t)(12 + xlen + 8) &&
                              start + bsize > fsize())
                       ? "bgzf_torn_tail" : "bgzf_bad_block");
        last_was_eof_marker = false;
        if (!rescan_from(start + 1)) { raw_eof = true; return nullptr; }
        gap_pending = true;
        continue;
      }
      size_t payload = (size_t)(bsize - 12 - xlen - 8);
      auto j = std::make_shared<Job>();
      j->comp.resize(payload);
      uint8_t tail[8];
      if (fread(j->comp.data(), 1, payload, f) != payload ||
          fread(tail, 1, 8, f) != 8) {
        // non-salvage can reach this on streams where fsize() was not
        // consulted; classification parity keeps it torn-tail
        if (!salv()) {
          err = true; raw_eof = true;
          err_reason = "bgzf_torn_tail";
          return nullptr;
        }
        sv->record("bgzf_torn_tail");
        last_was_eof_marker = false;
        raw_eof = true;
        return nullptr;
      }
      std::memcpy(&j->crc, tail, 4);
      std::memcpy(&j->isize, tail + 4, 4);
      last_was_eof_marker = payload <= 4 && j->isize == 0;
      // BGZF caps the uncompressed block at 64KB; a larger ISIZE is
      // file corruption — reject it here rather than letting
      // inflate_job value-initialize an attacker-sized buffer
      if (j->isize > (1u << 16)) {
        if (!salv()) {
          err = true; raw_eof = true;
          err_reason = "bgzf_bad_deflate";
          return nullptr;
        }
        sv->record("bgzf_bad_deflate");
        gap_pending = true;
        continue;
      }
      j->gap_before = gap_pending;
      gap_pending = false;
      return j;
    }
  }

  // next decompressed block into *dst: size, 0 = clean EOF, -1 = error.
  // *gap_before (may be null) reports salvage-dropped bytes preceding
  // this block's data.
  int64_t next_block(std::vector<uint8_t>* dst, bool* gap_before) {
    if (gap_before) *gap_before = false;
    for (;;) {
      while (!raw_eof && order.size() < depth) {
        auto j = read_raw();
        if (!j) break;
        order.push_back(j);
        if (workers.empty()) {
          j->bad = !inflate_job(j.get());
          j->done = true;
        } else {
          {
            std::lock_guard<std::mutex> lk(mu);
            queue.push_back(j);
          }
          cv_work.notify_one();
        }
      }
      if (order.empty()) {
        // a clean BGZF stream ends with the empty EOF-marker block
        // (write_bgzf/htslib emit it); missing it means the file was
        // truncated at a block boundary — surface that as an error
        // (or, in salvage mode, one classified torn-tail event)
        if (!err && !last_was_eof_marker) {
          if (salv()) {
            // budget-exempt reason (corruption.NON_BUDGET_REASONS):
            // a healthy file that merely lost its marker emits every
            // hole intact
            if (!end_counted) {
              end_counted = true;
              sv->record("bgzf_missing_eof");
            }
          } else {
            err = true;
            err_reason = "bgzf_missing_eof";
          }
        }
        return err ? -1 : 0;
      }
      auto j = order.front();
      order.pop_front();
      if (!workers.empty()) {
        std::unique_lock<std::mutex> lk(mu);
        cv_done.wait(lk, [&] { return j->done; });
      }
      if (j->bad) {
        if (salv()) {
          sv->record("bgzf_bad_deflate");
          pending_gap_out = true;
          continue;
        }
        err = true;
        err_reason = "bgzf_bad_deflate";
        return -1;
      }
      if (j->out.empty()) {           // empty block (e.g. EOF marker)
        pending_gap_out |= j->gap_before;
        continue;
      }
      if (gap_before) *gap_before = j->gap_before || pending_gap_out;
      pending_gap_out = false;
      dst->swap(j->out);
      return (int64_t)dst->size();
    }
  }
};

// ---- buffered gz stream --------------------------------------------------

struct GzStream {
  gzFile gz = nullptr;
  std::unique_ptr<BgzfMT> bgzf;  // non-null: BGZF block-parallel mode
  std::vector<uint8_t> buf;
  int64_t begin = 0, end = 0;
  bool eof = false;
  bool err = false;  // corrupt/truncated gzip stream (gzread < 0)
  const char* err_reason = nullptr;  // taxonomy code for err
  Salvage* sv = nullptr;
  // salvage: the CURRENT buffer is preceded by dropped (damaged)
  // bytes; consumers must not parse across the boundary.  gap_events
  // counts deliveries for readers that only need "did one happen".
  bool gap_before = false;
  int64_t gap_events = 0;

  bool salv() const { return sv != nullptr && sv->on; }

  void set_salvage(Salvage* s) {
    sv = s;
    if (bgzf) bgzf->sv = s;
  }

  bool open(const char* path) {
    if (std::strcmp(path, "-") != 0) {
      // sniff BGZF (regular files only; stdin can't rewind): gzip magic
      // + FEXTRA with a leading BC subfield, as htslib writes it
      FILE* f = fopen(path, "rb");
      if (!f) return false;
      uint8_t m[14];
      size_t n = fread(m, 1, sizeof m, f);
      bool is_bgzf = n == sizeof m && m[0] == 0x1f && m[1] == 0x8b &&
                     m[2] == 8 && (m[3] & 4) && m[12] == 'B' &&
                     m[13] == 'C';
      if (is_bgzf) {
        std::fseek(f, 0, SEEK_SET);
        bgzf.reset(new BgzfMT());
        bgzf->open(f);
        return true;
      }
      std::fclose(f);
    }
    if (std::strcmp(path, "-") == 0)
      gz = gzdopen(0, "r");
    else
      gz = gzopen(path, "r");
    if (gz) { buf.resize(kBufSize); return true; }
    return false;
  }
  void close() {
    if (gz) { gzclose(gz); gz = nullptr; }
    if (bgzf) { bgzf->close(); bgzf.reset(); }
  }
  bool fill() {
    if (eof) return false;
    if (bgzf) {
      bool gap = false;
      int64_t n = bgzf->next_block(&buf, &gap);
      begin = 0;
      end = n > 0 ? n : 0;
      if (gap) { gap_before = true; gap_events++; }
      if (n < 0) {
        eof = true; err = true;
        err_reason = bgzf->err_reason;
        return false;
      }
      if (n == 0) { eof = true; return false; }
      return true;
    }
    int n = gzread(gz, buf.data(), (unsigned)buf.size());
    begin = 0;
    end = n > 0 ? n : 0;
    if (n < 0) {
      eof = true; err = true;
      err_reason = "gzip_truncated";
      // a broken deflate stream has no block structure to resync on:
      // salvage classifies it once and ends the stream (the records
      // already delivered are the salvage)
      if (salv()) sv->record("gzip_truncated");
      return false;
    }
    if (n == 0) {
      // distinguish clean EOF from a truncated deflate stream
      int errnum = Z_OK;
      gzerror(gz, &errnum);
      if (errnum != Z_OK && errnum != Z_STREAM_END) {
        err = true;
        err_reason = "gzip_truncated";
        if (salv()) sv->record("gzip_truncated");
      }
      eof = true;
      return false;
    }
    return true;
  }
  // next byte or -1 at EOF
  int getc() {
    if (begin >= end && !fill()) return -1;
    return buf[begin++];
  }
  // read exactly n bytes; returns bytes read
  int64_t read(uint8_t* dst, int64_t n) {
    int64_t got = 0;
    while (got < n) {
      if (begin >= end && !fill()) break;
      int64_t take = end - begin;
      if (take > n - got) take = n - got;
      std::memcpy(dst + got, buf.data() + begin, (size_t)take);
      begin += take;
      got += take;
    }
    return got;
  }
  // append bytes into `out` until delimiter class hit (dropped from out).
  // delim: 0 = isspace, 1 = line ('\n', with '\r' stripped by caller).
  // returns: >=0 delimiter byte consumed, -1 EOF (out may hold a tail).
  int getuntil(int delim, std::string* out) {
    for (;;) {
      if (begin >= end && !fill()) return -1;
      int64_t i = begin;
      if (delim == 0) {
        while (i < end && !isspace(buf[i])) i++;
      } else {
        while (i < end && buf[i] != '\n') i++;
      }
      out->append((const char*)buf.data() + begin, (size_t)(i - begin));
      if (i < end) {
        int c = buf[i];
        begin = i + 1;
        return c;
      }
      begin = i;
    }
  }
};

// ---- record (one subread) ------------------------------------------------

struct Record {
  std::string name, comment, seq, qual;
  bool has_qual = false;
  void clear() {
    name.clear(); comment.clear(); seq.clear(); qual.clear();
    has_qual = false;
  }
};

// ---- FASTA/FASTQ reader (kseq.h:177-218 semantics) ----------------------

struct FastxReader {
  GzStream s;
  int last_char = 0;  // 0 = need to scan for marker; else the marker byte
  Salvage* sv = nullptr;

  bool salv() const { return sv != nullptr && sv->on; }

  // salvage resync: skip to the next line STARTING with '>'/'@' (the
  // line-anchored rule io/fastx.py uses — a '@' inside a quality line
  // must not anchor).  Called at a line boundary.
  void line_resync() {
    for (;;) {
      int c = s.getc();
      if (c == -1) { last_char = 0; return; }
      if (c == '>' || c == '@') { last_char = c; return; }
      // a blank line: the consumed '\n' already leaves us at the next
      // line start — consuming another line here would swallow a
      // record header after a blank line (io/fastx.py keeps it)
      if (c == '\n') continue;
      std::string skip;
      if (s.getuntil(1, &skip) == -1) { last_char = 0; return; }
    }
  }

  // returns: 1 record, 0 EOF, -2 malformed (qual length mismatch),
  // -3 corrupt gzip stream.  Salvage mode never returns -2/-3: the
  // corruption is classified, the parser resyncs, and the next good
  // record (or EOF) is returned.
  int next(Record* r) {
    for (;;) {
      int rc = next_impl(r);
      if (rc != -9) return rc;   // -9 = salvage drop, retry
    }
  }

  int next_impl(Record* r) {
    r->clear();
    int64_t gap0 = s.gap_events;
    int c = last_char;
    if (c == 0) {
      while ((c = s.getc()) != -1 && c != '>' && c != '@') {}
      if (c == -1) return (s.err && !salv()) ? -3 : 0;
    }
    last_char = 0;
    int marker = c;
    // name = first whitespace token; comment = rest of line
    c = s.getuntil(0, &r->name);
    if (c == -1) {
      if (s.err && !salv()) return -3;
      if (r->name.empty()) return 0;
      return finish_record(r, marker, gap0, false);
    }
    if (c != '\n') {
      c = s.getuntil(1, &r->comment);
      // byte-parity with io/fastx.py: strip only line terminators; keep
      // any interior/trailing spaces exactly as Python's split(None, 1)
      while (!r->comment.empty() && r->comment.back() == '\r')
        r->comment.pop_back();
      // leading whitespace from the delimiter run
      size_t b = 0;
      while (b < r->comment.size() &&
             (r->comment[b] == ' ' || r->comment[b] == '\t' ||
              r->comment[b] == '\r'))
        b++;
      r->comment.erase(0, b);
    }
    // sequence lines until '>', '@' or '+'
    while ((c = s.getc()) != -1 && c != '>' && c != '@' && c != '+') {
      if (c == '\n' || c == '\r') continue;
      r->seq.push_back((char)c);
      std::string tmp;
      int d = s.getuntil(1, &tmp);
      while (!tmp.empty() && tmp.back() == '\r') tmp.pop_back();
      r->seq.append(tmp);
      if (d == -1) { c = -1; break; }
    }
    if (c == '>' || c == '@') {
      last_char = c;
      return finish_record(r, marker, gap0, false);
    }
    if (s.err && !salv()) return -3;    // truncated gzip mid-sequence
    if (c != '+') return finish_record(r, marker, gap0, false);
    // '+' line: skip to end of line, then read quality until length match
    {
      std::string skip;
      if (s.getuntil(1, &skip) == -1) {
        if (!salv()) return -2;
        if (r->seq.empty()) return finish_record(r, marker, gap0, true);
        sv->record("fastx_truncated");
        return 0;   // EOF: nothing to resync onto
      }
    }
    while (r->qual.size() < r->seq.size()) {
      std::string line;
      int d = s.getuntil(1, &line);
      while (!line.empty() && line.back() == '\r') line.pop_back();
      r->qual.append(line);
      if (d == -1) break;
    }
    if (s.err && !salv()) return -3;
    if (r->qual.size() != r->seq.size()) {
      if (!salv()) return -2;
      // shorter = the stream ended under the record; longer = a
      // damaged quality section (mirrors io/fastx.py)
      sv->record(r->qual.size() < r->seq.size() ? "fastx_truncated"
                                                : "fastx_qual_mismatch");
      line_resync();
      return -9;
    }
    return finish_record(r, marker, gap0, true);
  }

  int finish_record(Record* r, int marker, int64_t gap0, bool has_q) {
    if (salv() && s.gap_events != gap0) {
      // the record's bytes span a BGZF salvage gap: a chimera of two
      // damaged regions — drop it and re-anchor (the gap itself was
      // already classified by the block layer)
      line_resync();
      return -9;
    }
    if (has_q) {
      // kseq parity: the quality section is *parsed* after any record,
      // but reported only for '@' records (io/fastx.py does the same).
      r->has_qual = (marker == '@');
      if (!r->has_qual) r->qual.clear();
    }
    return 1;
  }
};

// ---- BAM reader (bamlite.c:78-165 semantics) ----------------------------

// plausible-record predicate for the salvage resync scan — MUST match
// io/corruption.py record_plausible (the shared contract the
// differential fuzz tests pin)
inline bool record_plausible(const uint8_t* b, size_t avail,
                             int64_t max_rec) {
  if (avail < 36) return false;
  int32_t block_size, refid, pos, l_seq;
  uint16_t n_cigar;
  std::memcpy(&block_size, b, 4);
  if (block_size < kMinRecordBlock || block_size > max_rec) return false;
  std::memcpy(&refid, b + 4, 4);
  std::memcpy(&pos, b + 8, 4);
  if (!(refid == -1 || (refid >= 0 && refid < 100000)) || pos < -1)
    return false;
  uint8_t lrn = b[12];
  if (lrn < 2) return false;
  std::memcpy(&n_cigar, b + 16, 2);
  std::memcpy(&l_seq, b + 20, 4);
  if (l_seq < 0) return false;
  // 64-bit arithmetic: (l_seq + 1) on an attacker-controlled INT32_MAX
  // would be signed-overflow UB in 32 bits
  if (32 + (int64_t)lrn + 4 * (int64_t)n_cigar +
          ((int64_t)l_seq + 1) / 2 + (int64_t)l_seq > (int64_t)block_size)
    return false;
  if (avail < (size_t)36 + lrn) return false;
  if (b[36 + lrn - 1] != 0) return false;
  for (size_t i = 0; i + 1 < lrn; i++)
    if (b[36 + i] < 0x21 || b[36 + i] > 0x7E) return false;
  return true;
}

struct BamReader {
  GzStream s;
  bool header_done = false;
  std::vector<uint8_t> block;
  Salvage* sv = nullptr;

  // salvage feed: records are parsed out of `pend` so the scan can
  // look arbitrarily far ahead and a BGZF gap can be surfaced exactly
  // between the bytes on its two sides (io/bam.py _SalvageFeed mirror)
  std::string pend;
  size_t pos = 0;
  bool resync = false;

  bool salv() const { return sv != nullptr && sv->on; }

  // returns 0 ok, -3 bad header
  int read_header() {
    uint8_t magic[4];
    if (s.read(magic, 4) != 4 || std::memcmp(magic, "BAM\1", 4) != 0)
      return -3;
    int32_t l_text;
    if (s.read((uint8_t*)&l_text, 4) != 4 || l_text < 0 ||
        l_text > max_rec())
      return -3;
    std::vector<uint8_t> skip((size_t)l_text);
    if (s.read(skip.data(), l_text) != l_text) return -3;
    int32_t n_ref;
    if (s.read((uint8_t*)&n_ref, 4) != 4 || n_ref < 0 ||
        n_ref > 1 << 24)
      return -3;
    for (int32_t i = 0; i < n_ref; i++) {
      int32_t l_name;
      if (s.read((uint8_t*)&l_name, 4) != 4 || l_name < 1 ||
          l_name > 4096)
        return -3;
      skip.resize((size_t)l_name + 4);
      if (s.read(skip.data(), l_name + 4) != l_name + 4) return -3;
    }
    header_done = true;
    return 0;
  }

  // decode one alignment block at p (block_size bytes after the length
  // int) into r; false on inconsistent fields.  Shared by the fail-
  // fast and salvage paths so decode semantics can never diverge.
  bool decode_block(const uint8_t* p, int32_t block_size, Record* r) {
    uint8_t l_read_name = p[8];
    uint16_t n_cigar;
    int32_t l_seq;
    std::memcpy(&n_cigar, p + 12, 2);
    std::memcpy(&l_seq, p + 16, 4);
    if (l_read_name < 1) return false;  // io/bam.py decode_record parity
    if (l_seq < 0) return false;  // corrupt record; resize would throw
    int64_t off = 32;
    if (off + l_read_name > block_size) return false;
    r->name.assign((const char*)p + off,
                   l_read_name > 0 ? (size_t)(l_read_name - 1) : 0);
    off += l_read_name;
    off += 4 * (int64_t)n_cigar;
    // 64-bit: (l_seq + 1) at INT32_MAX would be signed-overflow UB
    int64_t nseq_bytes = ((int64_t)l_seq + 1) / 2;
    if (off + nseq_bytes + l_seq > block_size) return false;
    r->seq.resize((size_t)l_seq);
    for (int64_t i = 0; i < nseq_bytes; i++) {
      const uint8_t* two = kT.nib[p[off + i]];
      r->seq[(size_t)(2 * i)] = (char)two[0];
      if (2 * i + 1 < l_seq) r->seq[(size_t)(2 * i + 1)] = (char)two[1];
    }
    off += nseq_bytes;
    r->qual.resize((size_t)l_seq);
    for (int64_t i = 0; i < l_seq; i++) {
      int q = p[off + i] + 33;            // seqio.h:113
      r->qual[(size_t)i] = (char)(q > 126 ? 126 : q);
    }
    r->has_qual = true;
    return true;
  }

  const char* err_reason = nullptr;  // taxonomy code for a -3 here

  // the --max-record-bytes bound applies salvage ON OR OFF: the
  // Salvage struct is wired at open either way (sv->on gates only the
  // resync behavior)
  int64_t max_rec() const {
    return sv ? sv->max_record_bytes : kDefaultMaxRecordBytes;
  }

  // returns: 1 record, 0 clean EOF, -3 truncated/bad stream
  int next(Record* r) {
    if (salv()) return next_salvage(r);
    if (!header_done) {
      int rc = read_header();
      if (rc != 0) { err_reason = "bam_bad_header"; return rc; }
    }
    r->clear();
    int32_t block_size;
    int64_t got = s.read((uint8_t*)&block_size, 4);
    if (got == 0) return s.err ? -3 : 0;  // clean EOF (bamlite.c:141)
    if (got != 4 || block_size < 32 || block_size > max_rec()) {
      // the allocation bound: a corrupt int32 must be rejected BEFORE
      // block.resize() commits to it
      err_reason = (got == 4 && block_size > max_rec())
                       ? "bam_record_oversize" : "bam_bad_record";
      return -3;
    }
    block.resize((size_t)block_size);
    if (s.read(block.data(), block_size) != block_size) {
      err_reason = "bam_bad_record";
      return -3;
    }
    if (!decode_block(block.data(), block_size, r)) {
      err_reason = "bam_bad_record";
      return -3;
    }
    return 1;
  }

  // ---- salvage path (io/bam.py _read_bam_salvage mirror) ----------------

  // 0 ok, 1 gap (call take_gap), 2 eof
  int ensure(size_t n) {
    while (pend.size() - pos < n) {
      if (s.begin >= s.end) {
        if (!s.fill()) {
          if (s.gap_before) { s.gap_before = false; return 1; }
          return 2;
        }
        if (s.gap_before) { s.gap_before = false; return 1; }
      }
      pend.append((const char*)s.buf.data() + s.begin,
                  (size_t)(s.end - s.begin));
      s.begin = s.end;
    }
    return 0;
  }

  void take_gap() { pend.resize(pos); }

  void compact() {
    if (pos > (size_t)(1 << 16)) {
      pend.erase(0, pos);
      pos = 0;
    }
  }

  // 0 found, 2 eof (tail consumed)
  int scan_for_record() {
    int64_t max_rec = sv->max_record_bytes;
    for (;;) {
      int st = ensure((size_t)kScanLookahead);
      if (st == 1) { take_gap(); continue; }
      size_t avail = pend.size() - pos;
      if (st == 2 && avail < 36) { pos = pend.size(); return 2; }
      if (record_plausible((const uint8_t*)pend.data() + pos, avail,
                           max_rec))
        return 0;
      pos++;
      compact();
    }
  }

  // tolerant header parse over the feed; false = damaged (fall back
  // to the record scan).  Mirrors io/bam.py _salvage_header.
  bool salvage_header() {
    if (ensure(12) != 0 ||
        std::memcmp(pend.data() + pos, "BAM\1", 4) != 0)
      return false;
    int32_t l_text, n_ref;
    std::memcpy(&l_text, pend.data() + pos + 4, 4);
    if (l_text < 0 || l_text > kDefaultMaxRecordBytes) return false;
    if (ensure(12 + (size_t)l_text) != 0) return false;
    std::memcpy(&n_ref, pend.data() + pos + 8 + l_text, 4);
    if (n_ref < 0 || n_ref > 1 << 24) return false;
    pos += 12 + (size_t)l_text;
    for (int32_t i = 0; i < n_ref; i++) {
      if (ensure(4) != 0) return false;
      int32_t l_name;
      std::memcpy(&l_name, pend.data() + pos, 4);
      if (l_name < 1 || l_name > 4096) return false;
      if (ensure(8 + (size_t)l_name) != 0) return false;
      pos += 8 + (size_t)l_name;
    }
    return true;
  }

  int next_salvage(Record* r) {
    int64_t max_rec = sv->max_record_bytes;
    if (!header_done) {
      if (!salvage_header()) {
        sv->record("bam_bad_header");
        resync = true;
      }
      header_done = true;
    }
    r->clear();
    for (;;) {
      compact();
      if (resync) {
        if (scan_for_record() == 2) return 0;
        resync = false;
      }
      int st = ensure(4);
      if (st == 1) { take_gap(); resync = true; continue; }
      if (st == 2) {
        if (pend.size() - pos > 0) {
          sv->record("bam_bad_record");
          pos = pend.size();
        }
        return 0;
      }
      int32_t block_size;
      std::memcpy(&block_size, pend.data() + pos, 4);
      if (block_size < kMinRecordBlock || block_size > max_rec) {
        sv->record(block_size > max_rec ? "bam_record_oversize"
                                        : "bam_bad_record");
        pos++;
        resync = true;
        continue;
      }
      st = ensure(4 + (size_t)block_size);
      if (st == 1) { take_gap(); resync = true; continue; }
      if (st == 2) {
        sv->record("bam_bad_record");
        pos = pend.size();
        return 0;
      }
      if (!decode_block((const uint8_t*)pend.data() + pos + 4,
                        block_size, r)) {
        sv->record("bam_bad_record");
        pos++;
        resync = true;
        continue;
      }
      pos += 4 + (size_t)block_size;
      return 1;
    }
  }
};

// ---- ZMW group-by-hole streamer (seqio.h:152-201) ------------------------

struct Reader {
  bool is_bam = false;
  FastxReader fx;
  BamReader bam;
  std::string error;
  std::string reason;   // stable taxonomy code for `error` (corruption.py)
  Salvage salvage;      // salvage-mode switch + per-reason accounting

  // wire the shared Salvage into every layer (called at open; the
  // --max-record-bytes bound applies even with salvage OFF — sv->on
  // gates only the resync behavior)
  void wire_salvage() {
    fx.sv = &salvage;
    bam.sv = &salvage;
    (is_bam ? bam.s : fx.s).set_salvage(&salvage);
  }

  void set_max_record_bytes(int64_t max_record_bytes) {
    if (max_record_bytes > 0)
      salvage.max_record_bytes = max_record_bytes;
  }

  void enable_salvage(int64_t max_record_bytes) {
    salvage.on = true;
    set_max_record_bytes(max_record_bytes);
  }

  // filters (main.c:659-672); 0/absent = keep everything
  int32_t min_passes = 0;
  int64_t min_total = 0, max_total = 0;

  // filter accounting, bucketed by reason (the pure-Python path emits
  // per-hole zmw_filtered trace instants; the in-library filter here
  // was a blind spot — ccsx_filter_counts surfaces these so traced
  // native runs stop silently under-reporting filtering)
  int64_t filt_few_passes = 0, filt_short = 0, filt_long = 0;

  // lookahead carry (seqio.h:158-163)
  Record carry;
  bool have_carry = false;
  bool stream_done = false;

  // current hole output
  std::string movie, hole;
  std::string seqs;
  std::vector<int32_t> lens;

  // split "movie/hole/region"; returns false if not exactly 3 fields
  static bool split3(const std::string& name, std::string* m, std::string* h) {
    size_t a = name.find('/');
    if (a == std::string::npos) return false;
    size_t b = name.find('/', a + 1);
    if (b == std::string::npos) return false;
    if (name.find('/', b + 1) != std::string::npos) return false;
    m->assign(name, 0, a);
    h->assign(name, a + 1, b - a - 1);
    return true;
  }

  int next_record(Record* r) {
    return is_bam ? bam.next(r) : fx.next(r);
  }

  // taxonomy code for a -3 stream error in fail-fast mode: the
  // container layer's classification wins (it is causal), then the
  // record layer's, then the format's generic truncation code
  const char* stream_reason() {
    GzStream& s = is_bam ? bam.s : fx.s;
    if (s.err_reason) return s.err_reason;
    if (is_bam && bam.err_reason) return bam.err_reason;
    return is_bam ? "bam_bad_record" : "fastx_truncated";
  }

  bool keep() const {
    if (min_passes > 0 && (int32_t)lens.size() < min_passes) return false;
    int64_t total = (int64_t)seqs.size();
    if (max_total > 0 && total > max_total) return false;
    if (total < min_total) return false;
    return true;
  }

  // returns n_passes >= 0; -1 EOF; -2 invalid name; -3 stream error
  int next_zmw() {
    for (;;) {
      movie.clear(); hole.clear(); seqs.clear(); lens.clear();
      if (stream_done && !have_carry) return -1;
      if (have_carry) {
        if (!split3(carry.name, &movie, &hole)) {
          error = "invalid zmw name :" + carry.name;
          reason = "zmw_bad_name";
          return -2;
        }
        seqs.append(carry.seq);
        lens.push_back((int32_t)carry.seq.size());
        have_carry = false;
      }
      for (;;) {
        Record r;
        int rc = next_record(&r);
        if (rc == 0) { stream_done = true; break; }
        if (rc == -2) {
          error = "malformed FASTQ record: " + r.name;
          reason = "fastx_qual_mismatch";
          return -3;
        }
        if (rc < 0) {
          error = "truncated or corrupt input stream";
          if (reason.empty()) reason = stream_reason();
          return -3;
        }
        std::string m, h;
        if (!split3(r.name, &m, &h)) {
          if (salvage.on) {
            // salvage: the poisoned record is dropped and booked;
            // grouping re-anchors on the next record (io/zmw.py
            // group_zmws applies the same rule)
            salvage.record("zmw_bad_name");
            continue;
          }
          error = "invalid zmw name :" + r.name;
          reason = "zmw_bad_name";
          return -2;
        }
        if (lens.empty()) {
          movie.swap(m); hole.swap(h);
        } else if (m != movie || h != hole) {
          carry = std::move(r);
          have_carry = true;
          break;
        }
        seqs.append(r.seq);
        lens.push_back((int32_t)r.seq.size());
      }
      if (lens.empty()) return -1;
      if (keep()) return (int)lens.size();
      // filtered: count by reason (same precedence as keep()), then
      // loop to the next hole without crossing the API boundary
      if (min_passes > 0 && (int32_t)lens.size() < min_passes) {
        filt_few_passes++;
      } else if (max_total > 0 && (int64_t)seqs.size() > max_total) {
        filt_long++;
      } else {
        filt_short++;
      }
    }
  }
};

// ---- prefetching reader + ordered writer ---------------------------------
//
// The native equivalent of the reference's 3-step ordered pipeline
// (kt_pipeline, kthread.c:172-256; wired 2 threads x 3 steps at main.c:856):
// step 0 (read/group/filter) runs on a background thread here, step 1
// (consensus) runs in the caller, step 2 (write) on the writer thread
// below.  Chunk-order determinism is preserved because holes leave the
// queue in stream order and the caller feeds the writer in that order.

struct Hole {
  std::string movie, hole, seqs;
  std::vector<int32_t> lens;
};

struct Prefetcher {
  Reader reader;
  std::deque<Hole> queue;
  std::mutex mu;
  std::condition_variable cv_pop, cv_push;
  size_t cap = 64;
  int rc_final = 1;       // pending; set to <=-1 code when producer ends
  bool done = false;
  std::thread th;
  Hole current;           // last popped: owns buffers handed to the caller

  void run() {
    for (;;) {
      int rc = reader.next_zmw();
      std::unique_lock<std::mutex> lk(mu);
      if (rc < 0) { rc_final = rc; done = true; cv_pop.notify_all(); return; }
      cv_push.wait(lk, [&] { return queue.size() < cap || done; });
      if (done) return;  // closed under us
      Hole h;
      h.movie = reader.movie;
      h.hole = reader.hole;
      h.seqs.swap(reader.seqs);
      h.lens.swap(reader.lens);
      queue.push_back(std::move(h));
      cv_pop.notify_one();
    }
  }

  // same return codes as Reader::next_zmw
  int pop() {
    std::unique_lock<std::mutex> lk(mu);
    cv_pop.wait(lk, [&] { return !queue.empty() || done; });
    if (queue.empty()) return rc_final;
    current = std::move(queue.front());
    queue.pop_front();
    cv_push.notify_one();
    return (int)current.lens.size();
  }

  void close() {
    {
      std::lock_guard<std::mutex> lk(mu);
      done = true;
    }
    cv_push.notify_all();
    cv_pop.notify_all();
    if (th.joinable()) th.join();
  }
};

struct Writer {
  FILE* f = nullptr;
  bool own = false;
  std::deque<std::string> queue;
  std::mutex mu;
  std::condition_variable cv_pop, cv_push;
  size_t cap = 256;
  bool done = false, io_error = false;
  std::thread th;

  void run() {
    for (;;) {
      std::string item;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_pop.wait(lk, [&] { return !queue.empty() || done; });
        if (queue.empty()) break;
        item = std::move(queue.front());
        queue.pop_front();
        cv_push.notify_one();
      }
      if (!io_error &&
          fwrite(item.data(), 1, item.size(), f) != item.size()) {
        std::lock_guard<std::mutex> lk(mu);
        io_error = true;
      }
    }
  }

  bool put(std::string s) {
    std::unique_lock<std::mutex> lk(mu);
    if (io_error) return false;
    cv_push.wait(lk, [&] { return queue.size() < cap || done; });
    if (done) return false;
    queue.push_back(std::move(s));
    cv_pop.notify_one();
    return true;
  }

  // returns false on a prior write error
  bool close() {
    {
      std::lock_guard<std::mutex> lk(mu);
      done = true;
    }
    cv_pop.notify_all();
    cv_push.notify_all();
    if (th.joinable()) th.join();
    if (f) {
      if (fflush(f) != 0) io_error = true;
      if (own && fclose(f) != 0) io_error = true;
    }
    return !io_error;
  }
};

}  // namespace

// ---- C API ---------------------------------------------------------------

extern "C" {

void* ccsx_open(const char* path, int is_bam) {
  Reader* r = new Reader();
  r->is_bam = is_bam != 0;
  GzStream& s = r->is_bam ? r->bam.s : r->fx.s;
  if (!s.open(path)) { delete r; return nullptr; }
  r->wire_salvage();
  return r;
}

void ccsx_set_filter(void* h, int32_t min_passes, int64_t min_total,
                     int64_t max_total) {
  Reader* r = (Reader*)h;
  r->min_passes = min_passes;
  r->min_total = min_total;
  r->max_total = max_total;
}

// Salvage mode (--salvage): classified corruption is booked + resynced
// past instead of erroring the stream.  Must be called before the
// first next_* call.  max_record_bytes <= 0 keeps the default bound;
// with on == 0 only the bound is applied (fail-fast keeps its
// behavior, just with the caller's allocation limit).
void ccsx_set_salvage(void* h, int on, int64_t max_record_bytes) {
  Reader* r = (Reader*)h;
  r->set_max_record_bytes(max_record_bytes);
  if (on) r->enable_salvage(max_record_bytes);
}

// Fetch the next (filtered) hole. Returns n_passes>=0, -1 EOF, -2 invalid
// name, -3 stream error. Out pointers are valid until the next call.
int ccsx_next_zmw(void* h, const char** movie, const char** hole,
                  const uint8_t** seqs, int64_t* total_len,
                  const int32_t** lens, int32_t* n_passes) {
  Reader* r = (Reader*)h;
  int rc = r->next_zmw();
  if (rc >= 0) {
    *movie = r->movie.c_str();
    *hole = r->hole.c_str();
    *seqs = (const uint8_t*)r->seqs.data();
    *total_len = (int64_t)r->seqs.size();
    *lens = r->lens.data();
    *n_passes = (int32_t)r->lens.size();
  }
  return rc;
}

// Record-level access (no grouping). Returns 1 record, 0 EOF, -3 error.
// qual_len is -1 when the record carries no quality (FASTA).
int ccsx_next_record(void* h, const char** name, const char** comment,
                     const uint8_t** seq, int64_t* seq_len,
                     const uint8_t** qual, int64_t* qual_len) {
  Reader* r = (Reader*)h;
  r->carry.clear();
  int rc = r->next_record(&r->carry);
  if (rc == 1) {
    *name = r->carry.name.c_str();
    *comment = r->carry.comment.c_str();
    *seq = (const uint8_t*)r->carry.seq.data();
    *seq_len = (int64_t)r->carry.seq.size();
    *qual = (const uint8_t*)r->carry.qual.data();
    *qual_len = r->carry.has_qual ? (int64_t)r->carry.qual.size() : -1;
  } else if (rc == -2) {
    r->error = "malformed FASTQ record: " + r->carry.name;
    r->reason = "fastx_qual_mismatch";
    rc = -3;
  } else if (rc < 0) {
    if (r->error.empty()) r->error = "truncated or invalid stream";
    if (r->reason.empty()) r->reason = r->stream_reason();
    rc = -3;
  }
  return rc;
}

const char* ccsx_error(void* h) { return ((Reader*)h)->error.c_str(); }

// Stable taxonomy code (io/corruption.py REASONS) for the last error
// reported by this handle; empty when none.
const char* ccsx_error_reason(void* h) {
  return ((Reader*)h)->reason.c_str();
}

// Salvage accounting: total classified corruption events (live-safe —
// atomic), and the per-reason summary "reason:count,..." (call only
// after EOF; the buffer is owned by the handle).
int64_t ccsx_corrupt_events(void* h) {
  return ((Reader*)h)->salvage.events.load(std::memory_order_relaxed);
}

int64_t ccsx_corrupt_exempt(void* h) {
  return ((Reader*)h)->salvage.exempt.load(std::memory_order_relaxed);
}

const char* ccsx_corrupt_summary(void* h) {
  return ((Reader*)h)->salvage.build_summary();
}

// Filter accounting (reason-bucketed counts of holes the in-library
// filters dropped).  Valid at any point; complete once next_zmw
// returned EOF.
void ccsx_filter_counts(void* h, int64_t* few_passes, int64_t* too_short,
                        int64_t* too_long) {
  Reader* r = (Reader*)h;
  *few_passes = r->filt_few_passes;
  *too_short = r->filt_short;
  *too_long = r->filt_long;
}

void ccsx_close(void* h) {
  Reader* r = (Reader*)h;
  GzStream& s = r->is_bam ? r->bam.s : r->fx.s;
  s.close();
  delete r;
}

// ---- prefetching reader (background read step) ---------------------------

void* ccsx_prefetch_open(const char* path, int is_bam, int32_t min_passes,
                         int64_t min_total, int64_t max_total,
                         int32_t queue_cap) {
  Prefetcher* p = new Prefetcher();
  p->reader.is_bam = is_bam != 0;
  GzStream& s = p->reader.is_bam ? p->reader.bam.s : p->reader.fx.s;
  if (!s.open(path)) { delete p; return nullptr; }
  p->reader.wire_salvage();
  p->reader.min_passes = min_passes;
  p->reader.min_total = min_total;
  p->reader.max_total = max_total;
  if (queue_cap > 0) p->cap = (size_t)queue_cap;
  p->th = std::thread([p] { p->run(); });
  return p;
}

// Salvage-capable prefetch open: salvage must be fixed before the
// producer thread starts, hence a distinct entry point rather than a
// set_* call (the plain open keeps its historical signature).
void* ccsx_prefetch_open_s(const char* path, int is_bam,
                           int32_t min_passes, int64_t min_total,
                           int64_t max_total, int32_t queue_cap,
                           int salvage, int64_t max_record_bytes) {
  Prefetcher* p = new Prefetcher();
  p->reader.is_bam = is_bam != 0;
  GzStream& s = p->reader.is_bam ? p->reader.bam.s : p->reader.fx.s;
  if (!s.open(path)) { delete p; return nullptr; }
  p->reader.wire_salvage();
  p->reader.set_max_record_bytes(max_record_bytes);
  if (salvage) p->reader.enable_salvage(max_record_bytes);
  p->reader.min_passes = min_passes;
  p->reader.min_total = min_total;
  p->reader.max_total = max_total;
  if (queue_cap > 0) p->cap = (size_t)queue_cap;
  p->th = std::thread([p] { p->run(); });
  return p;
}

int ccsx_prefetch_next(void* h, const char** movie, const char** hole,
                       const uint8_t** seqs, int64_t* total_len,
                       const int32_t** lens, int32_t* n_passes) {
  Prefetcher* p = (Prefetcher*)h;
  int rc = p->pop();
  if (rc >= 0) {
    *movie = p->current.movie.c_str();
    *hole = p->current.hole.c_str();
    *seqs = (const uint8_t*)p->current.seqs.data();
    *total_len = (int64_t)p->current.seqs.size();
    *lens = p->current.lens.data();
    *n_passes = (int32_t)p->current.lens.size();
  }
  return rc;
}

const char* ccsx_prefetch_error(void* h) {
  return ((Prefetcher*)h)->reader.error.c_str();
}

const char* ccsx_prefetch_error_reason(void* h) {
  return ((Prefetcher*)h)->reader.reason.c_str();
}

// Live classified-corruption event count (atomic: the producer thread
// books while the consumer polls).
int64_t ccsx_prefetch_corrupt_events(void* h) {
  return ((Prefetcher*)h)
      ->reader.salvage.events.load(std::memory_order_relaxed);
}

int64_t ccsx_prefetch_corrupt_exempt(void* h) {
  return ((Prefetcher*)h)
      ->reader.salvage.exempt.load(std::memory_order_relaxed);
}

// Per-reason summary; call after EOF (pop() returned rc_final) — the
// queue-mutex handoff orders the producer's final writes before this.
const char* ccsx_prefetch_corrupt_summary(void* h) {
  Prefetcher* p = (Prefetcher*)h;
  std::lock_guard<std::mutex> lk(p->mu);
  return p->reader.salvage.build_summary();
}

// Same accounting for the prefetching streamer.  The counters are
// written by the producer thread; the consumer calls this after EOF
// (pop() returned rc_final), whose queue-mutex handoff orders the
// producer's final writes before this read.
void ccsx_prefetch_filter_counts(void* h, int64_t* few_passes,
                                 int64_t* too_short, int64_t* too_long) {
  Prefetcher* p = (Prefetcher*)h;
  std::lock_guard<std::mutex> lk(p->mu);
  *few_passes = p->reader.filt_few_passes;
  *too_short = p->reader.filt_short;
  *too_long = p->reader.filt_long;
}

void ccsx_prefetch_close(void* h) {
  Prefetcher* p = (Prefetcher*)h;
  p->close();
  GzStream& s = p->reader.is_bam ? p->reader.bam.s : p->reader.fx.s;
  s.close();
  delete p;
}

// ---- ordered async writer (background write step) ------------------------

void* ccsx_writer_open(const char* path, int append) {
  Writer* w = new Writer();
  if (std::strcmp(path, "-") == 0) {
    w->f = stdout;
  } else {
    w->f = fopen(path, append ? "a" : "w");
    w->own = true;
  }
  if (!w->f) { delete w; return nullptr; }
  w->th = std::thread([w] { w->run(); });
  return w;
}

// append one FASTA record (">name\nseq\n"); returns 0 ok, -1 on io error
int ccsx_writer_put_fasta(void* h, const char* name, const uint8_t* seq,
                          int64_t len) {
  Writer* w = (Writer*)h;
  std::string s;
  s.reserve((size_t)len + std::strlen(name) + 3);
  s.push_back('>');
  s.append(name);
  s.push_back('\n');
  s.append((const char*)seq, (size_t)len);
  s.push_back('\n');
  return w->put(std::move(s)) ? 0 : -1;
}

// FASTQ record: @name / seq / + / qual (qual = phred+33 ASCII, len bytes)
int ccsx_writer_put_fastq(void* h, const char* name, const uint8_t* seq,
                          const uint8_t* qual, int64_t len) {
  Writer* w = (Writer*)h;
  std::string s;
  s.reserve(2 * (size_t)len + std::strlen(name) + 6);
  s.push_back('@');
  s.append(name);
  s.push_back('\n');
  s.append((const char*)seq, (size_t)len);
  s.append("\n+\n", 3);
  s.append((const char*)qual, (size_t)len);
  s.push_back('\n');
  return w->put(std::move(s)) ? 0 : -1;
}

// returns 0 ok, -1 if any write failed
int ccsx_writer_close(void* h) {
  Writer* w = (Writer*)h;
  bool ok = w->close();
  delete w;
  return ok ? 0 : -1;
}

// ---- BGZF pool bench (decoupled from the reader) -------------------------

// Pre-reads every compressed block of a BGZF file into memory, then times
// `threads` workers inflating the whole set with atomic work-claiming (the
// same claim discipline as the reference's kt_for, kthread.c:39) — no file
// IO, no record parse, no ordered hand-off.  This isolates the inflate
// pool's scaling from everything BgzfMT::next_block interleaves with it,
// so the curve measures the pool, not the reader (SURVEY §7.3 item 6).
// Returns best-of-`iters` uncompressed MB/s, or -1 on a malformed file.
double ccsx_bgzf_pool_bench(const char* path, int threads, int iters) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1.0;
  BgzfMT rd;
  rd.f = f;
  std::vector<std::shared_ptr<BgzfMT::Job>> jobs;
  uint64_t total = 0;
  while (auto j = rd.read_raw()) {
    total += j->isize;
    jobs.push_back(std::move(j));
  }
  bool bad = rd.err;
  fclose(f);
  rd.f = nullptr;
  if (bad || jobs.empty() || total == 0) return -1.0;
  if (threads < 1) threads = 1;
  if (iters < 1) iters = 1;
  double best = 0.0;
  for (int it = 0; it < iters; it++) {
    std::atomic<size_t> next{0};
    std::atomic<bool> ok{true};
    auto run = [&] {
      for (;;) {
        size_t i = next.fetch_add(1);
        if (i >= jobs.size()) return;
        if (!BgzfMT::inflate_job(jobs[i].get())) ok = false;
      }
    };
    auto t0 = std::chrono::steady_clock::now();
    std::vector<std::thread> ws;
    for (int t = 1; t < threads; t++) ws.emplace_back(run);
    run();
    for (auto& t : ws) t.join();
    double dt = std::chrono::duration<double>(
                    std::chrono::steady_clock::now() - t0)
                    .count();
    if (!ok.load()) return -1.0;
    if (dt > 0) best = std::max(best, total / dt / (1 << 20));
  }
  return best;
}

// ---- encode / reverse-complement (main.c:222-241, seqio.h:120-148) ------

void ccsx_encode(const uint8_t* ascii, int64_t n, uint8_t* out) {
  for (int64_t i = 0; i < n; i++) out[i] = kT.enc[ascii[i]];
}

void ccsx_revcomp_ascii(const uint8_t* in, int64_t n, uint8_t* out) {
  for (int64_t i = 0; i < n; i++) out[i] = kT.comp[in[n - 1 - i]];
}

void ccsx_revcomp_codes(const uint8_t* in, int64_t n, uint8_t* out) {
  for (int64_t i = 0; i < n; i++) {
    uint8_t c = in[n - 1 - i];
    out[i] = c < 4 ? (uint8_t)(3 - c) : c;
  }
}

}  // extern "C"
