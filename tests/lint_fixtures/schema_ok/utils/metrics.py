"""Schema-drift fixed sibling, snapshot side.  MUST be consistent
with its telemetry twin."""


class Metrics:
    holes_in = 0

    def snapshot(self):
        snap = {
            "holes_in": self.holes_in,
        }
        if self.holes_in:
            snap["elapsed_s"] = 0.0
        return snap
