# Repo-level convenience targets (the native layer has its own
# Makefile at ccsx_tpu/native/Makefile, auto-invoked on import).

PY ?= python
PYTEST_FLAGS = -q -p no:cacheprovider -p no:xdist -p no:randomly

.PHONY: chaos chaos-soak fleet-chaos serve-chaos serve-fleet-chaos fuzz fuzz-sweep tier1 tier1-shard native long-molecule pallas-ab lint

# the static-analysis plane (ccsx_tpu/lint/): the repo-native checkers
# over the tree against the committed baseline (lint_baseline.json),
# then ruff with the pinned config in pyproject.toml when available
# (the container doesn't ship it; the gate is the repo-native pass,
# which tests/test_lint.py also runs as a tier-1 test).  Exit 0 iff
# zero unsuppressed findings.
lint:
	$(PY) -m ccsx_tpu.cli lint
	@if command -v ruff >/dev/null 2>&1; then \
	  ruff check ccsx_tpu tests benchmarks; \
	else \
	  echo "ruff not installed; skipping (config pinned in pyproject.toml)"; \
	fi

# the long-template (ultra-long-read) A/B: prefilter + device seeding
# vs the legacy host path, interleaved arms, bytes asserted identical
# (also directly: python benchmarks/long_molecule.py --scenarios ...)
long-molecule:
	JAX_PLATFORMS=cpu $(PY) benchmarks/long_molecule.py \
	  --scenarios 4x50000,4x50000d4,1x100000d4 --passes 8 \
	  --json benchmarks/long_molecule_r11.json

# the deterministic tier-1 chaos slice (tests/test_chaos.py fast
# tests): seeded fault schedules through the full CLI with the
# byte-identity oracle — the recovery ladder, dispatch deadline,
# circuit breaker, and shepherd restart in one command
chaos:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_chaos.py -m 'not slow' $(PYTEST_FLAGS)

# the deterministic tier-1 corruption-fuzz slice (tests/
# test_corrupt_fuzz.py fast tests): seeded hostile-input mutants
# through the full CLI with the salvage invariant as oracle
fuzz:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_corrupt_fuzz.py -m 'not slow' $(PYTEST_FLAGS)

# the full >= 50-mutants-per-format sweep (also directly:
# python benchmarks/corrupt.py --seed N --mutants 50)
fuzz-sweep:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_corrupt_fuzz.py $(PYTEST_FLAGS)
	JAX_PLATFORMS=cpu $(PY) benchmarks/corrupt.py --seed 0 --mutants 50

# elastic fleet churn: the deterministic tier-1 slice (tests/
# test_fleet.py fast tests: lease crash-consistency + SIGKILL/drain/
# join byte-identity) then the seeded soak mixing rank SIGKILL,
# mid-run --join, SIGTERM drain, and a straggler against the
# byte-identity oracle (also directly:
# python benchmarks/fleet.py --seed N [--scale64])
fleet-chaos:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_fleet.py $(PYTEST_FLAGS)
	JAX_PLATFORMS=cpu $(PY) benchmarks/fleet.py --seed 0 --holes 6

# the serving plane: the deterministic tier-1 slice (tests/
# test_serve.py: concurrent byte identity + zero steady-state
# recompiles, 429/cancel/drain-resume, per-tenant hang isolation)
# then the seeded multi-tenant soak — cancel, device hang, salvage,
# ENOSPC retry, drain/restart — against the blast-radius oracle
# (also directly: python benchmarks/serve_chaos.py --seed N)
serve-chaos:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_serve.py $(PYTEST_FLAGS)
	JAX_PLATFORMS=cpu $(PY) benchmarks/serve_chaos.py --seed 0 --holes 6

# the replica-fleet plane: the deterministic tier-1 slice (tests/
# test_lease.py crash-consistency + tests/test_serve_fleet.py:
# cross-replica handoff, dead-replica requeue, exclusive retirement,
# gateway routing, fan-out) then the seeded 3-replica subprocess soak —
# SIGKILL mid-wave, mid-run join, SIGTERM drain — against the
# zero-lost/zero-duplicate/byte-identity oracle (also directly:
# python benchmarks/serve_fleet_chaos.py --seed N)
serve-fleet-chaos:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_lease.py tests/test_serve_fleet.py -m 'not slow' $(PYTEST_FLAGS)
	JAX_PLATFORMS=cpu $(PY) benchmarks/serve_fleet_chaos.py --seed 0

# the full randomized soak (also available directly:
# python benchmarks/chaos.py --seed N --trials T)
chaos-soak:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_chaos.py $(PYTEST_FLAGS)
	JAX_PLATFORMS=cpu $(PY) benchmarks/chaos.py --seed 0 --trials 8 --holes 4

# the DP-kernel promotion harness, check mode (scan vs Pallas v1 vs
# rotband v2 bit-identity, interpret mode on CPU).  The timed three-arm
# run that emits the decision record needs the real chip — it is step 4
# of benchmarks/tpu_battery.sh, not a make target.
pallas-ab:
	JAX_PLATFORMS=cpu $(PY) benchmarks/pallas_ab.py --mode check

# the ROADMAP tier-1 suite (same flags as the verify command)
tier1:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -m 'not slow' --continue-on-collection-errors $(PYTEST_FLAGS)

# tier-1 split across N workers pulling per-file leases through the
# r16 lease domain (utils/lease.py + exclusive done markers): same
# suite, 1/N-ish the wall clock, crash-safe work handoff
N ?= 2
tier1-shard:
	$(PY) benchmarks/tier1_shard.py --workers $(N)

native:
	$(MAKE) -C ccsx_tpu/native
