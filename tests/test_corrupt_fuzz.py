"""Seeded corruption fuzzer (benchmarks/corrupt.py) through the full
CLI: the salvage invariant as an executable contract.

Per mutant: no crash, no hang (every run is dispatch-deadlined), rc
from the pinned exit-code taxonomy, and with --salvage every hole
whose bytes are UNDAMAGED emits byte-identical to the clean run (the
fuzzer's layout maps each mutation's blast radius to the exact hole
set it may legally affect — text spans directly, BGZF through the
block table).

The FAST deterministic slice runs in tier-1 (`make fuzz` runs exactly
this file's not-slow tests); the full >= 50-mutants-per-format sweep
is the `slow` mark and the benchmarks/corrupt.py CLI.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "benchmarks"))

import corrupt  # noqa: E402

from ccsx_tpu.utils import faultinject  # noqa: E402


@pytest.fixture(autouse=True)
def _disarm():
    faultinject.disarm()
    yield
    faultinject.disarm()


def test_fuzz_fast_slice(tmp_path):
    """2 seeded mutants per format (+ the clean-input salvage
    byte-identity check per format) through the full CLI.  Seeded:
    any red mutant replays with the same seed."""
    summary = corrupt.run_sweep(seed=0, mutants=2, tmp=str(tmp_path))
    assert summary["n_trials"] == 3 * (2 + 1)
    assert summary["ok"], summary["failed"]
    # replayability is the seeded np.random.default_rng stream (version-
    # stable); the old second full sweep here re-executed every mutant
    # to assert it — pure duplicate wall in the tier-1 slice (r11
    # duration audit), and the slow-tier 50-mutant sweep keeps the
    # deeper coverage
    assert summary["elapsed_s"] >= 0


def test_damage_mapping_bgzf(tmp_path):
    """The oracle itself: a mutation inside one BGZF block damages
    exactly the holes whose records overlap that block — not the whole
    file (which would make the invariant vacuous)."""
    rng = np.random.default_rng(3)
    corpus = corrupt.build_corpus(str(tmp_path), "bam", rng, holes=4,
                                  template_len=6000, n_passes=5)
    assert len(corpus.blocks) >= 3, "corpus must span multiple blocks"
    blk = corpus.blocks[1]
    mut = corrupt.Mutation("flip", blk[0] + 30, blk[0] + 31, "t")
    dam = corrupt.damaged_holes(corpus, mut)
    assert 0 < len(dam) < len(corpus.hole_spans), \
        f"blast radius should be partial, got {dam}"
    # a flip inside the EOF marker damages nothing
    eof = corpus.blocks[-1]
    mut = corrupt.Mutation("flip", eof[0] + 5, eof[0] + 6, "t")
    assert corrupt.damaged_holes(corpus, mut) == set()
    # truncation damages everything from its block on
    mut = corrupt.Mutation("truncate", blk[0] + 10, len(corpus.data),
                           "t")
    dam = corrupt.damaged_holes(corpus, mut)
    assert dam  # at least the tail holes
    lo = min(corpus.hole_spans[h][0] for h in dam)
    for h, (s0, s1) in corpus.hole_spans.items():
        if s1 <= lo:
            assert h not in dam


def test_damage_mapping_text(tmp_path):
    rng = np.random.default_rng(4)
    corpus = corrupt.build_corpus(str(tmp_path), "fastq", rng, holes=4)
    holes = sorted(corpus.hole_spans)
    lo, hi = corpus.hole_spans[holes[1]]
    mut = corrupt.Mutation("zeros", lo + 5, lo + 20, "t")
    assert corrupt.damaged_holes(corpus, mut) == {holes[1]}


@pytest.mark.slow
def test_fuzz_full_sweep(tmp_path):
    """The acceptance sweep: >= 50 mutants per format through the full
    CLI — zero crashes/hangs, taxonomy rcs, salvage invariant on every
    undamaged hole."""
    summary = corrupt.run_sweep(seed=0, mutants=50, tmp=str(tmp_path))
    assert summary["n_trials"] >= 3 * 50
    assert summary["ok"], summary["failed"]
