"""Test harness config: force JAX onto an 8-device virtual CPU mesh.

Must run before jax is imported anywhere (pytest imports conftest first).
The driver validates real multi-chip sharding separately via
__graft_entry__.dryrun_multichip.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
