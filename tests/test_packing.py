"""Ragged pass-packing (pipeline/pack.py + batch._refine_step_packed):
byte-parity with the host refinement spec and the bucketed control path,
the hole-level OOM-resplit ladder, and the packing occupancy counters.

The packer's own invariants live in the fast unit tier
(tests/test_pack.py); here the packed DEVICE path is differential-tested
— the acceptance pin that lets packing be the batched default."""

import numpy as np
import pytest

from ccsx_tpu import cli
from ccsx_tpu.config import CcsConfig
from ccsx_tpu.consensus import windowed as win_mod
from ccsx_tpu.consensus.star import RefineRequest, StarMsa, refine_host
from ccsx_tpu.pipeline.batch import BatchExecutor
from ccsx_tpu.utils import faultinject, synth
from ccsx_tpu.utils.metrics import Metrics

# mixed pass counts around and past the old {4, 8, 16, 32} bucket edges,
# one shared length bucket so the whole set packs into few slabs (cheap
# compiles); the error-free hole exercises the fixpoint freeze inside a
# shared slab
SPECS = [(3, 500, 0.12), (5, 500, 0.06), (4, 500, 0.0), (9, 500, 0.12),
         (11, 500, 0.1)]


def _requests(rng, cfg, specs=SPECS):
    sm = StarMsa(cfg.align, cfg.max_ins_per_col, cfg.len_bucket_quant)
    reqs = []
    for n, tlen, err in specs:
        tpl = rng.integers(0, 4, tlen).astype(np.uint8)
        if err == 0.0:
            ps = [tpl.copy() for _ in range(n)]
        else:
            ps = [synth.mutate(rng, tpl, err / 3, err / 3, err / 3)
                  for _ in range(n)]
        qs, qlens, row_mask = sm.pack(ps, cfg.pass_buckets, cfg.max_passes)
        reqs.append(RefineRequest(qs, qlens, row_mask, ps[0],
                                  cfg.refine_iters))
    return sm, reqs


def _assert_refine_matches_host(sm, cfg, req, res):
    want = refine_host(sm.round, req.qs, req.qlens, req.row_mask,
                       req.draft, req.iters)
    np.testing.assert_array_equal(res.draft, want.draft)
    rr, wr = res.rr, want.rr
    assert rr.tlen == wr.tlen
    T = rr.tlen
    np.testing.assert_array_equal(rr.cons[:T], wr.cons[:T])
    np.testing.assert_array_equal(rr.ins_base[:T], wr.ins_base[:T])
    np.testing.assert_array_equal(rr.ins_votes[:T], wr.ins_votes[:T])
    np.testing.assert_array_equal(rr.ncov[:T], wr.ncov[:T])
    nseq = int(req.row_mask.sum())
    host_bp = win_mod.find_breakpoint(wr, nseq, cfg)
    if rr.bp is not None:  # host-replayed results carry bp=None
        assert (rr.bp if rr.bp >= 1 else None) == host_bp
        bp_eff = host_bp if host_bp is not None else max(
            T - cfg.bp_window, 1)
        np.testing.assert_array_equal(
            rr.advance, win_mod._advance(wr, bp_eff).astype(np.int32))


def test_packed_refine_matches_host_and_counts(rng):
    """Slab-packed fused dispatches == the host refinement loop,
    bitwise, across pass counts spanning the old bucket edges — with a
    row budget small enough to force multiple slabs, tail shrinking,
    and cross-hole slab sharing.  The packing counters must tell the
    same story the dispatch plan does."""
    cfg = CcsConfig(is_bam=False, slab_rows=16)
    sm, reqs = _requests(rng, cfg)
    metrics = Metrics()
    ex = BatchExecutor(cfg, metrics=metrics)
    assert ex._packing
    results = ex.run(reqs)
    for req, res in zip(reqs, results):
        _assert_refine_matches_host(sm, cfg, req, res)
    assert metrics.refine_overflows == 0
    assert metrics.windows == len(reqs)
    # 32 rows over a 16-row budget: more than one slab, all real rows
    # dispatched exactly once.  Under the test harness's 8 fake devices
    # the slabs stack into ONE fused multi-chip wave (one dispatch);
    # fused_slabs_real still counts every planned slab
    assert metrics.packed_dispatches >= 1
    assert metrics.fused_slabs_real >= 2
    assert metrics.fused_waves == metrics.packed_dispatches
    assert metrics.dp_rows_real == sum(n for n, _, _ in SPECS)
    assert 0 < metrics.dp_rows_real <= metrics.dp_rows_dispatched
    snap = metrics.snapshot()
    assert snap["dp_z_fill"] == 1.0  # a slab IS the dispatch: no Z pad
    assert 0 < snap["dp_row_fill"] <= 1
    assert snap["packed_holes_per_dispatch"] >= 1
    assert 0 < snap["fused_slot_fill"] <= 1
    assert snap["distinct_slab_shapes"] >= 1


def test_packed_slab_rows_knob_output_invariant(rng):
    """The row budget changes only slab tiling, never results: the
    byte-identity that makes --slab-rows a safe tuning knob."""
    cfg_a = CcsConfig(is_bam=False, slab_rows=16)
    cfg_b = CcsConfig(is_bam=False, slab_rows=64)
    _, reqs = _requests(rng, cfg_a)
    ra = BatchExecutor(cfg_a).run(reqs)
    rb = BatchExecutor(cfg_b).run(reqs)
    for a, b in zip(ra, rb):
        assert a.rr.tlen == b.rr.tlen
        assert a.rr.bp == b.rr.bp
        np.testing.assert_array_equal(a.rr.cons, b.rr.cons)
        np.testing.assert_array_equal(a.rr.advance, b.rr.advance)
        np.testing.assert_array_equal(a.draft, b.draft)


def test_packed_oom_bisects_by_hole_then_replays_on_host(rng):
    """The recovery ladder on a packed slab: an OOM bisects the slab BY
    HOLE and re-packs each half at the smaller covering slab (results
    must stay bitwise); a persistent OOM runs the ladder to the
    per-hole host replay — the packed analog of the Z-bucket resplit
    acceptance cases in test_faults.py."""
    cfg = CcsConfig(is_bam=False, slab_rows=16)
    sm, reqs = _requests(rng, cfg)
    try:
        faultinject.arm("device_oom@1")
        m1 = Metrics()
        res = BatchExecutor(cfg, metrics=m1).run(reqs)
        assert m1.oom_resplits >= 1 and m1.host_fallbacks == 0
        for req, r in zip(reqs, res):
            _assert_refine_matches_host(sm, cfg, req, r)

        faultinject.arm("device_oom@1+")
        m2 = Metrics()
        res = BatchExecutor(cfg, metrics=m2).run(reqs)
        assert m2.oom_resplits >= 1 and m2.host_fallbacks >= 1
        for req, r in zip(reqs, res):
            _assert_refine_matches_host(sm, cfg, req, r)
    finally:
        faultinject.disarm()


@pytest.mark.slow  # ~20s three-arm CLI A/B (r15 budget audit); tier-1
# keeps the executor-level packed==bucketed pins in test_batch.py
# (packed_transfer_protocol, executor_matches_per_hole) and the CLI
# batched==per-hole pin (test_cli_batched_equals_per_hole)
def test_cli_packed_equals_bucketed_equals_per_hole(tmp_path, rng):
    """The tentpole acceptance pin on a mixed-pass synth corpus: the
    packed default, the --pass-buckets bucketed control, and the
    per-hole path must produce byte-identical FASTQ, while the
    occupancy counters show which grouping ran."""
    import json

    zs = [synth.make_zmw(rng, template_len=700, n_passes=5 + 2 * h,
                         movie="mv", hole=str(h)) for h in range(4)]
    fa = tmp_path / "in.fa"
    fa.write_text(synth.make_fasta(zs))
    outs, finals = {}, {}
    for tag, extra in (
            ("packed", ["--batch", "on"]),
            ("bucketed", ["--batch", "on", "--pass-buckets", "4,8,16,32"]),
            ("perhole", ["--batch", "off"])):
        o = tmp_path / f"{tag}.fq"
        m = tmp_path / f"{tag}.jsonl"
        assert cli.main(["-A", "-m", "1000", "--fastq", "--metrics",
                         str(m), *extra, str(fa), str(o)]) == 0
        outs[tag] = o.read_text()
        finals[tag] = [json.loads(ln)
                       for ln in m.read_text().splitlines()][-1]
    assert outs["packed"] == outs["bucketed"] == outs["perhole"]
    assert outs["packed"].count("@mv/") == 4
    assert finals["packed"]["dp_row_fill"] is not None
    assert finals["packed"]["packed_holes_per_dispatch"] >= 1
    assert finals["bucketed"]["dp_row_fill"] is None  # control ran bucketed
