"""Minimal BAM reader over a plain gzip stream (Python fallback path).

Replicates the semantics of the reference's bamlite (bamlite.c:78-165):
BAM-through-gzip — BGZF files are valid multi-member gzip streams, so
sequential reading works without BGZF block handling (bamlite.h:13-19 makes
the same choice; no random access).  Per record we decode the read name,
the 4-bit packed sequence via the =ACMGRSVTWYHKDBN table (seqio.h:92,
bamlite.h:86) and qualities as phred+33 clamped at 126 (seqio.h:113).

Truncated-stream handling mirrors bamlite: a clean EOF at a record boundary
ends the stream; a partial record raises.
"""

from __future__ import annotations

import gzip
import io
import struct
from typing import Iterator

import numpy as np

from ccsx_tpu.io.fastx import FastxRecord

SEQ_NT16 = b"=ACMGRSVTWYHKDBN"

# 2x256 lookup: byte -> two ASCII bases (high nibble first, bamlite.h:86)
_NIB = np.empty((256, 2), dtype=np.uint8)
for _b in range(256):
    _NIB[_b, 0] = SEQ_NT16[_b >> 4]
    _NIB[_b, 1] = SEQ_NT16[_b & 0xF]


class BamError(ValueError):
    pass


def _read_exact(f, n: int, what: str) -> bytes:
    buf = f.read(n)
    if len(buf) != n:
        raise BamError(f"truncated BAM: short read in {what}")
    return buf


def read_bam_header(f) -> dict:
    magic = _read_exact(f, 4, "magic")
    if magic != b"BAM\x01":
        raise BamError("invalid BAM header")  # bamlite.c:84
    (l_text,) = struct.unpack("<i", _read_exact(f, 4, "l_text"))
    text = _read_exact(f, l_text, "text").rstrip(b"\x00").decode(
        errors="replace")
    (n_ref,) = struct.unpack("<i", _read_exact(f, 4, "n_ref"))
    refs = []
    for _ in range(n_ref):
        (l_name,) = struct.unpack("<i", _read_exact(f, 4, "ref name len"))
        name = _read_exact(f, l_name, "ref name")[:-1].decode(errors="replace")
        (l_ref,) = struct.unpack("<i", _read_exact(f, 4, "ref len"))
        refs.append((name, l_ref))
    return {"text": text, "refs": refs}


def read_bam_records(path_or_file) -> Iterator[FastxRecord]:
    """Stream BAM alignment records as FastxRecords (name/seq/qual)."""
    if hasattr(path_or_file, "read"):
        raw = path_or_file
    else:
        raw = open(path_or_file, "rb")
    # transparent gzip/BGZF
    if not hasattr(raw, "peek"):
        raw = io.BufferedReader(raw)
    if raw.peek(2)[:2] == b"\x1f\x8b":
        f = io.BufferedReader(gzip.GzipFile(fileobj=raw))
    else:
        f = raw

    read_bam_header(f)
    while True:
        head = f.read(4)
        if len(head) == 0:
            return  # clean EOF (bamlite.c:141 returns -1)
        if len(head) < 4:
            raise BamError("truncated BAM: partial block size")
        (block_size,) = struct.unpack("<i", head)
        block = _read_exact(f, block_size, "alignment block")
        (refid, pos, l_read_name, mapq, bin_, n_cigar, flag, l_seq,
         next_ref, next_pos, tlen) = struct.unpack("<iiBBHHHiiii", block[:32])
        off = 32
        name = block[off:off + l_read_name - 1].decode(errors="replace")
        off += l_read_name
        off += 4 * n_cigar
        nseq_bytes = (l_seq + 1) // 2
        packed = np.frombuffer(block, dtype=np.uint8,
                               count=nseq_bytes, offset=off)
        seq = _NIB[packed].reshape(-1)[:l_seq].tobytes()
        off += nseq_bytes
        qual_raw = np.frombuffer(block, dtype=np.uint8, count=l_seq,
                                 offset=off)
        # phred+33 clamped at 126 (seqio.h:113)
        qual = np.minimum(qual_raw.astype(np.int16) + 33, 126).astype(
            np.uint8).tobytes()
        yield FastxRecord(name=name, comment="", seq=seq, qual=qual)


# BGZF framing (the real subreads.bam container): gzip members <=64KB
# with a "BC" extra subfield holding the compressed block size, ending in
# a fixed 28-byte empty EOF block.  Valid multi-member gzip, so every
# plain-gzip reader (incl. this module's read path and the reference's
# bamlite, bamlite.h:13-19) still reads it; the native reader additionally
# exploits the block structure for parallel inflate (io_native.cpp).
BGZF_EOF = bytes.fromhex(
    "1f8b08040000000000ff0600424302001b0003000000000000000000")
BGZF_BLOCK_PAYLOAD = 0xFF00      # htslib's default uncompressed chunk


def _bgzf_block(data: bytes) -> bytes:
    import zlib

    co = zlib.compressobj(6, zlib.DEFLATED, -15)
    comp = co.compress(data) + co.flush()
    bsize = 18 + len(comp) + 8 - 1          # total block size minus 1
    header = (b"\x1f\x8b\x08\x04" + b"\x00" * 4 + b"\x00\xff"
              + struct.pack("<H", 6) + b"BC" + struct.pack("<HH", 2, bsize))
    return (header + comp + struct.pack("<II", zlib.crc32(data),
                                        len(data) & 0xFFFFFFFF))


def write_bgzf(path, data: bytes) -> None:
    """Write `data` as a BGZF stream (blocked gzip + EOF marker)."""
    with open(path, "wb") as fh:
        for i in range(0, len(data), BGZF_BLOCK_PAYLOAD):
            fh.write(_bgzf_block(data[i:i + BGZF_BLOCK_PAYLOAD]))
        fh.write(BGZF_EOF)


def write_bam(path, records, refs=(), bgzf: bool = True) -> None:
    """Tiny BAM writer for tests/fixtures (unmapped records only).

    BGZF container by default, like real subreads.bam; ``bgzf=False``
    writes one plain gzip member (also valid BAM-through-gzip, and
    exercises the native reader's non-BGZF fallback)."""
    import zlib

    out = io.BytesIO()
    text = b"@HD\tVN:1.6\n"
    out.write(b"BAM\x01")
    out.write(struct.pack("<i", len(text)))
    out.write(text)
    out.write(struct.pack("<i", len(refs)))
    for name, ln in refs:
        nm = name.encode() + b"\x00"
        out.write(struct.pack("<i", len(nm)))
        out.write(nm)
        out.write(struct.pack("<i", ln))
    rev = {v: i for i, v in enumerate(SEQ_NT16)}
    for name, seq, qual in records:
        nm = name.encode() + b"\x00"
        l_seq = len(seq)
        packed = bytearray((l_seq + 1) // 2)
        for i, b in enumerate(seq):
            code = rev.get(b, 15)
            if i % 2 == 0:
                packed[i // 2] |= code << 4
            else:
                packed[i // 2] |= code
        q = bytes((min(max(x - 33, 0), 93) for x in qual)) if qual \
            else b"\xff" * l_seq
        body = struct.pack("<iiBBHHHiiii", -1, -1, len(nm), 255, 0, 0, 4,
                           l_seq, -1, -1, 0)
        body += nm + bytes(packed) + q
        out.write(struct.pack("<i", len(body)))
        out.write(body)
    data = out.getvalue()
    if bgzf:
        write_bgzf(path, data)
    else:
        with open(path, "wb") as fh:
            fh.write(gzip.compress(data))
