"""Minimal-fix sibling: the same updates through the sanctioned
patterns.  MUST produce no findings."""

import contextvars

_cid = contextvars.ContextVar("ccsx_cid", default=None)


def ingest(metrics, n):
    metrics.bump(holes_in=n)          # locked counter add
    metrics.prep_queue_depth = n      # single-writer gauge publish


def scope_arm(cid):
    return _cid.set(cid)              # token handed to the caller


def cid_scope(cid):
    token = _cid.set(cid)
    try:
        return token
    finally:
        _cid.reset(token)             # the trace.cid_scope shape
