import gzip
import io

import numpy as np
import pytest

from ccsx_tpu.config import CcsConfig
from ccsx_tpu.io import fastx, zmw


FASTA = b""">m0/1/0_10 comment here
ACGTACGTAC
>m0/1/10_15
ACG
TA
>m0/2/0_4
GGGG
>m1/2/0_4
TTTT
"""

FASTQ = b"""@m0/1/0_10
ACGTACGTAC
+
IIIIIIIIII
@m0/1/10_14
ACGT
+anything
IIII
"""


def test_fasta_records():
    recs = list(fastx.read_fastx(io.BufferedReader(io.BytesIO(FASTA))))
    assert [r.name for r in recs] == ["m0/1/0_10", "m0/1/10_15", "m0/2/0_4", "m1/2/0_4"]
    assert recs[0].comment == "comment here"
    assert recs[0].seq == b"ACGTACGTAC"
    assert recs[1].seq == b"ACGTA"  # multi-line sequence
    assert recs[0].qual is None


def test_fastq_records():
    recs = list(fastx.read_fastx(io.BufferedReader(io.BytesIO(FASTQ))))
    assert len(recs) == 2
    assert recs[0].qual == b"IIIIIIIIII"
    assert recs[1].seq == b"ACGT" and recs[1].qual == b"IIII"


def test_fastq_bad_quality_length():
    bad = b"@m0/1/0_4\nACGT\n+\nII\n"
    with pytest.raises(ValueError):
        list(fastx.read_fastx(io.BufferedReader(io.BytesIO(bad))))


def test_gzip_transparent(tmp_path):
    p = tmp_path / "x.fa.gz"
    p.write_bytes(gzip.compress(FASTA))
    recs = list(fastx.read_fastx(p))
    assert len(recs) == 4


def test_group_zmws():
    recs = list(fastx.read_fastx(io.BufferedReader(io.BytesIO(FASTA))))
    zs = list(zmw.group_zmws(recs))
    # same hole id '2' under different movies must NOT merge (seqio.h:183)
    assert [(z.movie, z.hole) for z in zs] == [("m0", "1"), ("m0", "2"), ("m1", "2")]
    z0 = zs[0]
    assert z0.n_passes == 2
    assert z0.seqs == b"ACGTACGTACACGTA"
    assert z0.lens.tolist() == [10, 5]
    assert z0.offs.tolist() == [0, 10]
    assert z0.subread(1) == b"ACGTA"


def test_invalid_name_raises():
    recs = [fastx.FastxRecord("badname", "", b"ACGT", None)]
    with pytest.raises(zmw.InvalidZmwName):
        list(zmw.group_zmws(recs))
    recs = [fastx.FastxRecord("a/b/c/d", "", b"ACGT", None)]
    with pytest.raises(zmw.InvalidZmwName):
        list(zmw.group_zmws(recs))


def _mk(n_passes, total=6000, hole="7"):
    per = total // n_passes
    seqs = b"A" * total
    lens = np.full(n_passes, per, dtype=np.int32)
    lens[-1] += total - per * n_passes
    offs = np.zeros(n_passes, dtype=np.int32)
    np.cumsum(lens[:-1], out=offs[1:])
    return zmw.Zmw("m0", hole, seqs, lens, offs)


def test_zmw_filter_count_and_len():
    cfg = CcsConfig()
    # count >= min_fulllen_count + 2 == 5 (main.c:659)
    assert not zmw.zmw_filter(_mk(4), cfg)
    assert zmw.zmw_filter(_mk(5), cfg)
    # total length window [5000, 500000] (main.c:662-664)
    assert not zmw.zmw_filter(_mk(5, total=4999), cfg)
    assert zmw.zmw_filter(_mk(5, total=5000), cfg)
    assert not zmw.zmw_filter(_mk(5, total=500001), cfg)


def test_zmw_filter_exclusion():
    cfg = CcsConfig(exclude_holes=frozenset({"7"}))
    assert not zmw.zmw_filter(_mk(5, hole="7"), cfg)
    assert zmw.zmw_filter(_mk(5, hole="8"), cfg)


def test_gzip_bytesio_stream():
    """Regression: raw BytesIO (no peek()) carrying gzip data must be
    detected and decompressed, not silently parsed as binary junk."""
    import io as _io
    recs = list(fastx.read_fastx(_io.BytesIO(gzip.compress(FASTA))))
    assert len(recs) == 4


def test_plus_line_after_fasta_record():
    """kseq parity: '+' after a '>' record starts a quality section (kseq.h:196)
    — it must not yield a phantom empty-name record."""
    import io as _io
    data = b">r/1/0_4\nACGT\n+\nIIII\n>r/2/0_4\nTTTT\n"
    recs = list(fastx.read_fastx(_io.BytesIO(data)))
    assert [r.name for r in recs] == ["r/1/0_4", "r/2/0_4"]
    assert recs[0].qual is None  # quality consumed but not reported for FASTA


def test_aux_tag_roundtrip(tmp_path):
    """Aux-tag walk + typed getters (bamlite.c:215-290 parity)."""
    from ccsx_tpu.io import bam as bam_mod

    p = str(tmp_path / "aux.bam")
    aux = [("np", "i", 12), ("rq", "f", 0.5), ("qs", "s", -7),
           ("RG", "Z", "movie1"), ("fl", "A", "F")]
    bam_mod.write_bam(p, [("mv/1/0_8", b"ACGTACGT", b"\x10" * 8, aux)])
    ((rec, tags),) = list(bam_mod.read_bam_records(p, with_aux=True))
    assert rec.name == "mv/1/0_8"
    assert bam_mod.aux2i(tags, "np") == 12
    assert bam_mod.aux2i(tags, "qs") == -7
    assert abs(bam_mod.aux2f(tags, "rq") - 0.5) < 1e-6
    assert bam_mod.aux2Z(tags, "RG") == "movie1"
    assert bam_mod.aux2A(tags, "fl") == "F"
    # wrong-type / missing gets mirror bamlite's 0/NULL returns
    assert bam_mod.aux2i(tags, "RG") == 0
    assert bam_mod.aux2f(tags, "np") == 0.0
    assert bam_mod.aux2Z(tags, "np") is None
    assert bam_mod.aux2i(tags, "zz") == 0
    # records with aux still parse on the no-aux path and native reader
    (rec2,) = list(bam_mod.read_bam_records(p))
    assert rec2.seq == rec.seq


def test_parse_aux_corrupt_does_not_hang(tmp_path):
    """Corrupt aux bytes raise BamError (never loop or leak raw errors)."""
    import struct

    from ccsx_tpu.io import bam as bam_mod

    # negative B-array count (would walk the offset backwards)
    bad = b"AB" + b"B" + b"c" + struct.pack("<i", -8)
    with pytest.raises(bam_mod.BamError):
        bam_mod.parse_aux(bad)
    # Z tag missing its NUL terminator
    with pytest.raises(bam_mod.BamError):
        bam_mod.parse_aux(b"RG" + b"Z" + b"no-nul")
    # truncated scalar
    with pytest.raises(bam_mod.BamError):
        bam_mod.parse_aux(b"np" + b"i" + b"\x01")
    # good B array still parses
    good = b"sn" + b"B" + b"C" + struct.pack("<i", 3) + bytes([1, 2, 3])
    assert bam_mod.parse_aux(good)["sn"] == ("B", [1, 2, 3])


def test_python_reader_checks_bgzf_eof_marker(tmp_path):
    """Python fallback agrees with the native reader on block-boundary
    truncation (missing EOF marker -> error, not a silent short read)."""
    from ccsx_tpu.io import bam as bam_mod

    p = str(tmp_path / "b.bam")
    bam_mod.write_bam(p, [("mv/1/0_4", b"ACGT", b"\x10" * 4)])
    raw = open(p, "rb").read()
    assert raw.endswith(bam_mod.BGZF_EOF)
    open(p, "wb").write(raw[: -len(bam_mod.BGZF_EOF)])
    with pytest.raises(bam_mod.BamError):
        list(bam_mod.read_bam_records(p))
