"""The shared forced-execution timing helper (benchmarks/marginal_time).

This is the measurement layer every perf artifact now rests on (the
lazy-runtime discovery, r5) — pin its contract: positive marginals for
real work, scaling with workload, and an honest refusal when no window
yields a positive sample.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "benchmarks"))

from marginal_time import marginal_time  # noqa: E402


def _work(n):
    def f(x):
        import jax.numpy as jnp

        y = x.astype(jnp.float32)
        for _ in range(n):
            y = y * 1.0001 + 0.5
        return y
    return f


def test_positive_and_scales_with_workload():
    x = np.arange(1 << 16, dtype=np.int32)
    # min over 5 windows (not 3) and a 2x (not 3x) separation: on a
    # contended 1-core CI host a single noisy light-window can inflate
    # `light` enough to flake the tighter bound, while a genuine
    # lazy-runtime regression (both readings ~the fixed RPC latency)
    # still fails 2x by an order of magnitude
    light = min(marginal_time(_work(4), x, iters=40, repeats=5))
    heavy = min(marginal_time(_work(400), x, iters=40, repeats=5))
    assert light > 0 and heavy > 0
    # 100x the elementwise chain must cost measurably more per call —
    # the property the lazy runtime's fake timings violated
    assert heavy > 2 * light, (light, heavy)


def test_refuses_when_no_positive_sample():
    # a no-op measured at iters=2 on a host under load: force the
    # pathological all-nonpositive case deterministically by patching
    # the clock to stand still
    import time as _t

    import marginal_time as mt

    seq = iter([0.0, 1.0, 1.0, 1.0] * 20)  # base=1.0, run_n dt=0.0

    class FakeTime:
        perf_counter = staticmethod(lambda: next(seq))
        sleep = staticmethod(lambda s: None)

    mt.time = FakeTime()
    try:
        with pytest.raises(RuntimeError, match="nonpositive"):
            marginal_time(_work(1), np.arange(128, dtype=np.int32),
                          iters=3, repeats=2)
    finally:
        mt.time = _t
