"""End-to-end driver: input stream -> consensus -> ordered FASTA output.

The reference overlaps read/compute/write with a 3-step ordered pipeline
(kt_pipeline, main.c:856) and fans compute out over threads (kt_for,
main.c:702-704).  Here: a bounded thread pool computes holes concurrently
while the writer drains futures strictly in submission order, so output is
`>movie/hole/ccs` in input order (main.c:714) for any thread count.
"""

from __future__ import annotations

import collections
import os
import sys
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from ccsx_tpu.config import CcsConfig
from ccsx_tpu.consensus.align_host import HostAligner
from ccsx_tpu.consensus.hole import ccs_hole
from ccsx_tpu.io import bam as bam_mod
from ccsx_tpu.io import fastx, zmw
from ccsx_tpu.io.corruption import CorruptionError, SalvageSink
from ccsx_tpu.utils import faultinject
from ccsx_tpu.utils import trace
from ccsx_tpu.utils.device import resolve_device
from ccsx_tpu.utils.journal import Journal
from ccsx_tpu.utils.metrics import (FailureBudgetExceeded, Metrics,
                                    check_failure_budget)


def open_zmw_stream(path: str, cfg: CcsConfig, metrics=None):
    """Filtered ZMW iterator for BAM or FASTA/Q input ('-' = stdin).

    Uses the native C++ streamer (parser + group-by-hole + filters in one
    pass, ccsx_tpu/native) when the library is available and the input is a
    real path; otherwise the pure-Python parsers.  Opens the file eagerly —
    the parsers are generators, and a deferred open() would crash past the
    caller's error handling.  ``metrics`` (optional) receives the
    filtered-hole accounting from either path: per-hole live on the
    Python path, reason-bucketed at EOF from the native reader.

    ``cfg.salvage`` selects salvage-mode ingest on whichever stack
    serves: classified corruption is booked into Metrics
    (holes_corrupt + corrupt_reasons + the degraded mark) and resynced
    past instead of killing the stream (io/corruption.py).
    """
    from ccsx_tpu import native

    salvage = bool(getattr(cfg, "salvage", False))
    if path != "-" and native.available():
        from ccsx_tpu.native.io import (salvage_supported,
                                        stream_zmws_prefetch)

        if not salvage or salvage_supported():
            return stream_zmws_prefetch(path, cfg, metrics=metrics)
        # stale prebuilt .so without the salvage entry points: fall
        # through to the pure-Python salvage readers
    sink = SalvageSink(metrics, getattr(cfg, "max_record_bytes", 0)) \
        if salvage else None
    if cfg.is_bam:
        if path == "-":
            records = bam_mod.read_bam_records(
                sys.stdin.buffer, salvage=sink,
                max_record_bytes=getattr(cfg, "max_record_bytes", 0))
        else:
            open(path, "rb").close()   # eager-open contract (OSError now)
            records = bam_mod.read_bam_records(
                path, salvage=sink,
                max_record_bytes=getattr(cfg, "max_record_bytes", 0))
    else:
        f = sys.stdin.buffer if path == "-" else open(path, "rb")
        records = fastx.read_fastx(f, salvage=sink)
    return zmw.stream_zmws(records, cfg, metrics=metrics, salvage=sink)


def guarded_stream(stream, cfg: CcsConfig, metrics, guard=None):
    """The drivers' shared ingest guard, wrapped around any open ZMW
    stream (single-process, batched, and sharded drivers all route
    ingestion through here — prep-pool workers included, since the
    pool consumes the wrapped iterator):

    * graceful drain: once ``guard.requested`` (SIGTERM/SIGINT,
      utils/drain.py) the stream reports exhausted — admission stops,
      in-flight work finishes, and the driver exits RC_INTERRUPTED;
    * the ``input_corrupt`` fault point (utils/faultinject.py): with
      --salvage the injected corruption drops that one hole and the
      stream CONTINUES; without it, the clean rc-1 path;
    * the salvage rung for classified corruption raised by the stream
      itself (e.g. the range-sharded reader, which classifies but has
      no resync): with --salvage the event is booked and the stream
      ENDS there — a generator that raised is closed, so the remaining
      range is lost either way; booking + rc 0 degraded beats killing
      the whole run.  (The salvage-mode readers resync internally and
      never raise here.)
    * an absolute --max-failed-holes budget is re-checked per admitted
      hole, so reader-booked corruption events (which bypass the
      drivers' per-failure checks) abort the ingest promptly instead
      of salvage-scanning the whole file first.
    """
    sink = SalvageSink(metrics) if getattr(cfg, "salvage", False) \
        else None
    it = iter(stream)
    while True:
        if guard is not None and guard.requested:
            return
        try:
            z = next(it)
        except StopIteration:
            return
        except CorruptionError as e:
            if sink is None:
                raise
            sink.record(e.reason)
            print(f"[ccsx-tpu] salvage: classified corruption from the "
                  f"stream ({e.reason}: {e}); ending ingestion — "
                  "emitting what was salvaged", file=sys.stderr)
            return
        try:
            faultinject.fire("input_corrupt")
        except CorruptionError as e:
            if sink is None:
                raise
            sink.record(e.reason)
            print(f"[ccsx-tpu] salvage: dropped corrupt input unit "
                  f"({e.reason}: {e})", file=sys.stderr)
            continue
        # count-form budgets abort mid-ingest (fractions settle at end
        # of run where the denominator is final)
        check_failure_budget(metrics, cfg)
        yield z


def count_raw_holes(in_path: str, cfg: CcsConfig) -> int:
    """RAW hole count of the input — the fleet scheduler's range-table
    denominator (pipeline/fleet.py).  BAM inputs use (or build) the
    BGZF hole index sidecar; FASTA/Q inputs take one name-only counting
    pass using the same consecutive-(movie,hole) keying as the sharded
    BAM indexer, so range-table ordinals always line up with what
    ``slice_raw_holes`` streams."""
    from ccsx_tpu.io import bamindex

    if cfg.is_bam:
        idx = bamindex.load_index(in_path) or bamindex.build_index(
            in_path,
            max_record_bytes=getattr(cfg, "max_record_bytes", 0))
        return idx["n_holes"]
    n = 0
    prev = None
    with open(in_path, "rb") as f:
        for rec in fastx.read_fastx(f):
            key = bamindex._hole_key(rec.name)
            if key != prev:
                n += 1
                prev = key
    return n


def slice_raw_holes(records, lo: int, hi: int):
    """Pass through only the records of raw holes [lo, hi) — the
    FASTA/Q twin of bamindex.read_hole_range (which seeks; plain text
    cannot, so the lead-in is parsed and dropped).  Stops at hole hi,
    so a front range never pays for the file's tail."""
    from ccsx_tpu.io import bamindex

    if lo >= hi:
        return
    seen = -1
    prev = None
    for rec in records:
        key = bamindex._hole_key(rec.name)
        if key != prev:
            seen += 1
            prev = key
            if seen >= hi:
                return
        if seen >= lo:
            yield rec


def holes_total_hint(in_path: str, cfg: CcsConfig):
    """RAW hole count of the input when cheaply knowable (the BGZF hole
    index sidecar, `ccsx-tpu --make-index`), else None — feeds the
    progress/ETA estimator's total (Metrics.holes_total).  Raw holes:
    filtered holes count toward progress `done`, so the basis matches."""
    if not cfg.is_bam or in_path == "-" or not os.path.exists(in_path):
        return None
    try:
        from ccsx_tpu.io import bamindex

        idx = bamindex.load_index(in_path)
    except (OSError, ValueError):
        return None
    return idx["n_holes"] if idx else None


class _PyWriter:
    """FASTA/FASTQ writer over a Python file object (stdout / fallback /
    journaled runs).  Tracks ``bytes_out`` — the exact output size after
    every record — which the journal records as its torn-tail recovery
    offset; the shared fastx.format_record counts UTF-8-encoded bytes,
    not len(str), so a non-ASCII read name (split_name accepts any
    movie string) cannot skew the offset and mis-truncate a resume."""

    def __init__(self, f, own: bool, start_bytes: int = 0):
        self._f = f
        self._own = own
        self.bytes_out = start_bytes

    def put(self, name: str, seq: bytes, qual: bytes | None = None) -> None:
        # disk_full fault point (ENOSPC): fires BEFORE any bytes land,
        # so the journaled offset stays behind the durable output and a
        # resume recomputes the interrupted hole (no torn record past
        # the cursor)
        faultinject.fire("disk_full")
        rec, nbytes = fastx.format_record(name, seq, qual)
        self._f.write(rec)
        self.bytes_out += nbytes

    def flush(self) -> None:
        self._f.flush()

    def close(self) -> None:
        if self._own:
            self._f.close()


def open_writer(path: str, append: bool, bam: bool = False,
                journaled: bool = False):
    """Async native writer for real paths; Python writer for stdout;
    buffered BAM writer under --bam.

    stdout stays Python-level so redirection (tests, `ccsx-tpu ... -`) works.
    ``journaled`` runs also use the Python writer: the journal's crash
    contract needs a synchronous, flushable stream with byte accounting
    (the record must be durable before the journal cursor claims it),
    which the async native writer cannot order — and write time is ~0%
    of wall (ARCHITECTURE.md stage attribution), so nothing is lost.
    """
    from ccsx_tpu import native

    if bam:
        if path == "-":
            raise OSError("--bam output requires a file path, not stdout")
        if append:
            raise OSError("--bam output does not support --journal resume "
                          "(the BGZF container cannot be appended)")
        return bam_mod.BamWriter(path)
    if path != "-" and native.available() and not journaled:
        from ccsx_tpu.native.io import NativeFastaWriter

        return NativeFastaWriter(path, append=append)
    if path == "-":
        return _PyWriter(sys.stdout, own=False)
    start = os.path.getsize(path) if append and os.path.exists(path) else 0
    # UTF-8 pinned (not the locale default) so bytes_out's encode-based
    # accounting always matches what reaches the file
    return _PyWriter(open(path, "a" if append else "w", encoding="utf-8"),
                     own=True, start_bytes=start)


def run_pipeline(in_path: str, out_path: str, cfg: CcsConfig,
                 journal_path: Optional[str] = None) -> int:
    if getattr(cfg, "prep_threads", None):
        # the per-hole path already overlaps prep with compute through
        # its -j worker pool (each worker preps + computes whole holes);
        # the prep plane is a batched-scheduler construct
        print("[ccsx-tpu] --prep-threads has no effect with --batch off "
              "(use -j; the per-hole path overlaps prep per worker)",
              file=sys.stderr)
    # metrics constructed before the stream so both ingest paths can
    # book their filtered-hole accounting into it
    metrics = Metrics(verbose=cfg.verbose, stream=cfg.metrics_stream())
    metrics.holes_total = holes_total_hint(in_path, cfg)
    try:
        stream = open_zmw_stream(in_path, cfg, metrics=metrics)
    except (OSError, RuntimeError) as e:
        print(f"Error: Failed to open infile! ({e})", file=sys.stderr)
        metrics.close_stream()  # no final event for a non-run
        return 1
    # load under this run's fingerprint + reconcile the output tail
    # (truncate torn / refuse untrustworthy) before the writer opens
    journal = Journal.for_run(journal_path, in_path, cfg, out_path)
    resume = journal.holes_done
    # restore the journaled failure count so --max-failed-holes is
    # judged over the whole logical run, resumes included
    metrics.holes_failed = journal.holes_failed
    metrics.holes_prior_emitted = journal.holes_emitted
    try:
        writer = open_writer(out_path, append=bool(resume),
                             bam=cfg.bam_out,
                             journaled=bool(journal_path))
    except OSError as e:
        print(f"Cannot open file for write! ({e})", file=sys.stderr)
        metrics.close_stream()
        return 1

    resolve_device(cfg.device)
    aligner = HostAligner(cfg.align)

    def compute(z):
        stats: dict = {}
        try:
            faultinject.fire("compute")
            with trace.span("hole_compute", cat="compute",
                            hole=str(z.hole)):
                return z, ccs_hole(z, aligner, cfg, stats), None, stats
        except Exception as e:  # quarantine: one bad hole must not kill the run
            return z, None, e, stats

    def write_result(item):
        z, rec, err, stats = item
        # per-hole counters aggregated here (driver side) so worker
        # threads never touch the Metrics object concurrently.
        # device_dispatches is a lower-bound estimate on this path: each
        # window runs >=1 refinement round of 3 jitted calls (aligner,
        # projector, voter); the batched executor's count is exact (one
        # fused dispatch per shape group)
        metrics.windows += stats.get("windows", 0)
        metrics.device_dispatches += 3 * stats.get("windows", 0)
        wrote = False
        with metrics.timer("write"), \
                trace.span("write_record", cat="write"):
            if err is not None:
                metrics.holes_failed += 1
                print(f"[ccsx-tpu] hole {z.movie}/{z.hole} failed: {err}",
                      file=sys.stderr)
                # failure-rate abort (--max-failed-holes): a count
                # budget aborts immediately, a fraction budget settles
                # at end of run (utils/metrics.py)
                check_failure_budget(metrics, cfg)
            elif rec is not None and rec[0]:
                writer.put(f"{z.movie}/{z.hole}/ccs", rec[0], rec[1])
                metrics.holes_out += 1
                wrote = True
        # flush-before-cursor + write fault point + advance: the shared
        # crash invariant lives in Journal.retire
        journal.retire(writer, wrote, metrics)
        # deterministic drain testing: a real SIGTERM delivered at a
        # retirement point (the graceful-drain acceptance case)
        faultinject.fire("sigterm")
        metrics.tick()

    rc = 0
    pool = ThreadPoolExecutor(max_workers=max(cfg.threads, 1)) \
        if cfg.threads > 1 else None
    pending = collections.deque()
    # graceful drain (utils/drain.py): SIGTERM/SIGINT stop admission;
    # in-flight holes finish, writer + journal settle, rc 75 resumable
    from ccsx_tpu.utils.drain import DrainGuard

    guard = DrainGuard.install()
    stream = guarded_stream(stream, cfg, metrics, guard)
    # flight recorder: the per-hole path has no batched device-dispatch
    # spans for the watchdog to watch (host compute dominates), but the
    # span trace — ingest, per-hole compute (worker threads included),
    # host pair alignments, writes, journal updates — records the same
    # taxonomy the batched driver does.  Constructed INSIDE the try
    # (finally tolerates tracer=None) so neither a watchdog thread nor
    # an open trace file can leak, and an unwritable --trace path gets
    # the same polite rc-1 refusal as an unwritable output path
    tracer = None
    telem = None
    try:
        try:
            tracer = trace.Tracer(cfg.trace_path,
                                  stall_timeout=cfg.stall_timeout_s,
                                  metrics=metrics)
        except OSError as e:
            print(f"Cannot open trace file for write! ({e})",
                  file=sys.stderr)
            return 1
        trace.install(tracer)
        # live telemetry endpoints (--telemetry-port; None when off —
        # a bind failure degrades to a warning, never kills the run)
        if cfg.telemetry_port:
            from ccsx_tpu.utils import telemetry

            telem = telemetry.start(metrics, cfg.telemetry_port)
        while True:
            try:
                with metrics.timer("ingest"), \
                        trace.span("ingest_hole", cat="ingest"):
                    z = next(stream)
                    faultinject.fire("ingest")
            except StopIteration:
                break
            metrics.holes_in += 1
            if metrics.holes_in <= resume:
                continue  # already written in a previous run
            metrics.heartbeat()
            if pool is None:
                with metrics.timer("compute"):
                    item = compute(z)
                write_result(item)
            else:
                pending.append(pool.submit(compute, z))
                # bounded window keeps memory flat; drain in order
                while len(pending) > 2 * cfg.threads:
                    with metrics.timer("compute"):
                        item = pending.popleft().result()
                    write_result(item)
        while pending:
            with metrics.timer("compute"):
                item = pending.popleft().result()
            write_result(item)
        # fraction-form --max-failed-holes settles at end of run — but
        # not on a drain: the denominator is a partial run's
        if not guard.requested:
            check_failure_budget(metrics, cfg, final=True)
    except FailureBudgetExceeded as e:
        from ccsx_tpu import exitcodes

        print(f"Error: {e}; aborting instead of emitting a degraded "
              "output at rc 0", file=sys.stderr)
        rc = exitcodes.RC_FAILED_HOLES
    except (bam_mod.BamError, zmw.InvalidZmwName, ValueError) as e:
        print(f"Error: invalid input stream: {e}", file=sys.stderr)
        rc = 1
    except OSError as e:
        print(f"Error: write failed: {e}", file=sys.stderr)
        rc = 1
    finally:
        guard.restore()
        if pool is not None:
            pool.shutdown(wait=True)
        try:
            writer.close()
        except OSError as e:
            print(f"Error: write failed! ({e})", file=sys.stderr)
            rc = 1
        # settle the (possibly rate-limit-lagging) cursor AFTER the
        # writer has made the records durable
        journal.close()
        trace.uninstall()
        if tracer is not None:
            tracer.close()
        # endpoints down BEFORE the final event: a scraper must never
        # see a half-closed Metrics object
        if telem is not None:
            telem.close()
        metrics.report()
    if rc == 0 and guard.requested:
        from ccsx_tpu import exitcodes

        print("[ccsx-tpu] drained cleanly; resume with the same "
              "command to continue", file=sys.stderr)
        rc = exitcodes.RC_INTERRUPTED
    return rc
