"""Multi-host sharding (parallel/distributed.py): round-robin ownership,
shard writing, k-way merge, CLI wiring.  Most ranks are simulated as
sequential processes in one test process — the sharding logic is a pure
function of (rank, n), so this exercises exactly what real hosts run
(collectives are exercised separately by __graft_entry__.dryrun_multichip).
test_two_process_coordinator_run additionally executes the REAL control
plane: two concurrent OS processes rendezvous through
jax.distributed.initialize on a localhost coordinator."""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from ccsx_tpu import cli
from ccsx_tpu.io import fastx
from ccsx_tpu.parallel import distributed as dist
from ccsx_tpu.utils import synth


def _make_inputs(tmp_path, rng, n_holes, tlen=700):
    zs = [synth.make_zmw(rng, template_len=tlen, n_passes=5 + (h % 2),
                         movie="mv", hole=str(100 + h))
          for h in range(n_holes)]
    fa = tmp_path / "in.fa"
    fa.write_text(synth.make_fasta(zs))
    return zs, fa


def test_shard_stream_partition():
    items = list(range(10))
    shards = [list(dist.shard_stream(iter(items), r, 3)) for r in range(3)]
    assert shards[0] == [0, 3, 6, 9]
    assert shards[1] == [1, 4, 7]
    assert shards[2] == [2, 5, 8]


@pytest.mark.slow  # ~18s 4-shard A/B (r15 budget audit); tier-1 keeps
# the mesh-sharded merge==single-host pin below and the real
# two-process coordinator run
def test_sharded_run_merge_equals_single_host(tmp_path, rng):
    """N sequential 'hosts' + merge == the single-process batched output."""
    zs, fa = _make_inputs(tmp_path, rng, n_holes=7)
    ref = tmp_path / "ref.fa"
    assert cli.main(["-A", "-m", "1000", "--batch", "on",
                     str(fa), str(ref)]) == 0

    out = tmp_path / "dist.fa"
    for r in range(3):
        assert cli.main(["-A", "-m", "1000", "--hosts", "3",
                         "--host-id", str(r), str(fa), str(out)]) == 0
    assert cli.main(["--merge-shards", "3", "ignored.in", str(out)]) == 0
    assert out.read_text() == ref.read_text()


@pytest.mark.slow  # ~12s: FASTQ twin of the BAM merge test above (r11 audit)
def test_sharded_fastq_merge_equals_single_host(tmp_path, rng):
    """--fastq shards (4-line records) must merge byte-identically to
    the single-process FASTQ output."""
    zs, fa = _make_inputs(tmp_path, rng, n_holes=5)
    ref = tmp_path / "ref.fq"
    assert cli.main(["-A", "-m", "1000", "--fastq", "--batch", "on",
                     str(fa), str(ref)]) == 0
    out = tmp_path / "dist.fq"
    for r in range(2):
        assert cli.main(["-A", "-m", "1000", "--fastq", "--hosts", "2",
                         "--host-id", str(r), str(fa), str(out)]) == 0
    assert cli.main(["--merge-shards", "2", "ignored.in", str(out)]) == 0
    assert out.read_text() == ref.read_text()
    for r in fastx.read_fastx(str(out)):
        assert r.qual is not None and len(r.qual) == len(r.seq)


def test_two_process_coordinator_run(tmp_path, rng):
    """The real jax.distributed control plane (SURVEY.md §5.8): two
    concurrent OS processes initialize through a localhost coordinator
    (cli --coordinator -> init_distributed, distributed.py:38-54), each
    runs its shard of the pipeline, and the merge must be byte-identical
    to the single-host batched output.  This is the seam no sequential
    simulation covers — jax.process_index()/process_count() come from
    the coordination service, not from CLI flags."""
    zs, fa = _make_inputs(tmp_path, rng, n_holes=4, tlen=500)
    ref = tmp_path / "ref.fa"
    assert cli.main(["-A", "-m", "1000", "--batch", "on",
                     str(fa), str(ref)]) == 0

    out = tmp_path / "dist.fa"
    # the runner re-asserts platforms=cpu before any backend init: the
    # axon TPU plugin overrides JAX_PLATFORMS at import time (conftest)
    runner = (
        "import sys, jax; jax.config.update('jax_platforms', 'cpu'); "
        "from ccsx_tpu.cli import main; sys.exit(main(sys.argv[1:]))")
    env = dict(os.environ, JAX_PLATFORMS="cpu", CCSX_SKIP_PROBE="1",
               XLA_FLAGS="")
    # bind-then-close port picking is TOCTOU (another process can grab
    # the port before rank 0's coordinator binds it) — retry the whole
    # rendezvous on a fresh port if that race hits, and always reap both
    # subprocesses even when communicate() times out
    for attempt in range(3):
        with socket.socket() as s:  # pick a free localhost port
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", runner, "-A", "-m", "1000",
                 "--hosts", "2", "--host-id", str(r),
                 "--coordinator", f"127.0.0.1:{port}", str(fa), str(out)],
                env=env, cwd=os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))),
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
            for r in range(2)]
        try:
            outs = [p.communicate(timeout=300) for p in procs]
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait()
        if (attempt < 2 and any(p.returncode != 0 for p in procs)
                and any("bind" in se.lower() or "in use" in se.lower()
                        for _, se in outs)):
            continue  # coordinator lost the port race; fresh port
        break
    for p, (so, se) in zip(procs, outs):
        assert p.returncode == 0, f"rank failed:\n{so}\n{se}"
    # both ranks went through the coordination service
    assert (tmp_path / "dist.fa.shard0").exists()
    assert (tmp_path / "dist.fa.shard1").exists()
    assert dist.merge_shards(str(out), 2) == ref.read_text().count(">")
    assert out.read_text() == ref.read_text()


def test_sharded_journal_resume(tmp_path, rng):
    """A crashed rank resumes from its shard journal without re-emitting."""
    zs, fa = _make_inputs(tmp_path, rng, n_holes=6)
    out = tmp_path / "o.fa"
    jp = str(tmp_path / "j.json")
    # run rank 0 fully, then "resume" it: second run must append nothing
    assert cli.main(["-A", "-m", "1000", "--hosts", "2", "--host-id", "0",
                     "--journal", jp, str(fa), str(out)]) == 0
    first = (tmp_path / "o.fa.shard0").read_text()
    assert cli.main(["-A", "-m", "1000", "--hosts", "2", "--host-id", "0",
                     "--journal", jp, str(fa), str(out)]) == 0
    assert (tmp_path / "o.fa.shard0").read_text() == first


def test_hosts_requires_host_id(tmp_path, capsys):
    rc = cli.main(["--hosts", "2", "x.fa", str(tmp_path / "y.fa")])
    assert rc == 1
    assert "--host-id" in capsys.readouterr().err


def test_metrics_jsonl(tmp_path, rng):
    import json

    zs, fa = _make_inputs(tmp_path, rng, n_holes=2)
    m = tmp_path / "m.jsonl"
    assert cli.main(["-A", "-m", "1000", "--batch", "on",
                     "--metrics", str(m), str(fa), str(out := tmp_path / "o.fa")]) == 0
    events = [json.loads(line) for line in m.read_text().splitlines()]
    assert events and events[-1]["event"] == "final"
    assert events[-1]["holes_out"] == out.read_text().count(">")


def test_sharded_run_with_mesh_matches_single_host(tmp_path, rng):
    """--hosts with --mesh 4,2: sharded + pass-parallel rounds must still
    merge to the exact single-host output."""
    zs, fa = _make_inputs(tmp_path, rng, n_holes=5)
    ref = tmp_path / "ref.fa"
    assert cli.main(["-A", "-m", "1000", "--batch", "on",
                     str(fa), str(ref)]) == 0
    out = tmp_path / "dist.fa"
    for r in range(2):
        assert cli.main(["-A", "-m", "1000", "--hosts", "2",
                         "--host-id", str(r), "--mesh", "4,2",
                         str(fa), str(out)]) == 0
    assert cli.main(["--merge-shards", "2", "ignored.in", str(out)]) == 0
    assert out.read_text() == ref.read_text()


def test_sharded_run_invalid_mesh_clean_error(tmp_path, rng, capsys):
    """An infeasible --mesh in a sharded run fails rc 1 without
    truncating an existing shard file."""
    zs, fa = _make_inputs(tmp_path, rng, n_holes=2)
    out = tmp_path / "o.fa"
    shard = tmp_path / "o.fa.shard0"
    shard.write_text("precious\n")
    rc = cli.main(["-A", "-m", "1000", "--hosts", "2", "--host-id", "0",
                   "--mesh", "16,2", str(fa), str(out)])
    assert rc == 1
    assert "invalid --mesh" in capsys.readouterr().err
    assert shard.read_text() == "precious\n"
